"""Snapshot storage: central and staged layouts.

≈ orte/mca/sstore — the `central` component (every rank writes straight
into the shared snapshot root) and the `stage` component (ranks write to
fast node-local storage first; a filem/raw-equivalent *stage* step then
moves the file into the central root).

Layout (one job root, monotonically numbered snapshots):

    <base>/<job>/snapshot_<seq>/rank_<r>.npz      per-rank array shards
    <base>/<job>/snapshot_<seq>/metadata.json     written LAST by rank 0

The metadata file is the commit record (two-phase: a snapshot without it
is garbage and is ignored/cleaned) — the same "all ranks report, then the
coordinator marks the snapshot valid" protocol snapc/full runs over its
RML channels, here carried by the collective layer instead.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Optional

import numpy as np

from ompi_tpu.mpi.constants import ERR_IO, MPIException

__all__ = ["SnapshotStore", "StagedStore", "ShardedSnapshotStore"]

_META = "metadata.json"


def _to_host(v: Any) -> np.ndarray:
    """Materialize any array-like (jax arrays included) on host."""
    return np.asarray(v)


# npz serializes ml_dtypes arrays (bfloat16, float8_*) as raw void —
# bytes survive but the dtype name is dropped (loads back as |V2).
# Record the true dtype of such arrays in a SIDECAR MANIFEST entry
# (user keys are never renamed, so no user key can ever be
# misinterpreted or collide) and view the bytes back on load.
_DTYPE_MANIFEST = "__ompi_tpu_dtype_manifest__"


def _tag_exotic(arrays: dict) -> dict:
    if _DTYPE_MANIFEST in arrays:
        raise MPIException(
            f"checkpoint key {_DTYPE_MANIFEST!r} is reserved for the "
            f"store's dtype manifest — rename it", error_class=ERR_IO)
    mapping = {}
    for k, v in arrays.items():
        if v.dtype.kind == "V" and v.dtype.names is None:
            import ml_dtypes  # noqa: F401 — registers the dtype names

            try:
                if np.dtype(v.dtype.name) == v.dtype:
                    mapping[k] = v.dtype.name
            except TypeError:
                pass   # plain void ('V4' etc.): np.dtype can't parse
                # its .name — store raw, exactly as before this scheme
    if not mapping:
        return arrays
    out = dict(arrays)
    out[_DTYPE_MANIFEST] = np.array(json.dumps(mapping))
    return out


def _untag_exotic(npz) -> dict:
    files = [k for k in npz.files if k != _DTYPE_MANIFEST]
    mapping: dict = {}
    if _DTYPE_MANIFEST in npz.files:
        import ml_dtypes  # noqa: F401 — registers the dtype names

        try:
            mapping = json.loads(str(npz[_DTYPE_MANIFEST][()]))
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise MPIException(
                f"corrupt checkpoint dtype manifest: {e}",
                error_class=ERR_IO) from None
    out = {}
    for k in files:
        v = npz[k]
        if k in mapping:
            try:
                v = v.view(np.dtype(mapping[k]))
            except (TypeError, ValueError) as e:
                # dtype unknown to THIS environment (older ml_dtypes) or
                # manifest/bytes mismatch: corrupt-snapshot contract, not
                # a raw numpy error (snapc handles MPIException/ERR_IO)
                raise MPIException(
                    f"restoring checkpoint array {k!r} as dtype "
                    f"{mapping[k]!r}: {e}", error_class=ERR_IO) from None
        out[k] = v
    return out


class SnapshotStore:
    """sstore/central: ranks write directly into the shared root."""

    def __init__(self, base_dir: str, job: str = "job") -> None:
        self.base = os.path.join(os.path.abspath(base_dir), job)
        os.makedirs(self.base, exist_ok=True)

    # -- paths -------------------------------------------------------------

    def snapshot_dir(self, seq: int) -> str:
        return os.path.join(self.base, f"snapshot_{seq}")

    def _rank_file(self, seq: int, rank: int) -> str:
        return os.path.join(self.snapshot_dir(seq), f"rank_{rank}.npz")

    # -- write path --------------------------------------------------------

    def write_rank(self, seq: int, rank: int,
                   state: dict[str, Any]) -> str:
        """Serialize one rank's state dict (atomic: tmp file + rename)."""
        d = self.snapshot_dir(seq)
        os.makedirs(d, exist_ok=True)
        arrays = _tag_exotic({k: _to_host(v) for k, v in state.items()})
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            dst = self._rank_file(seq, rank)
            os.replace(tmp, dst)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self._rank_file(seq, rank)

    def commit(self, seq: int, nranks: int,
               extra: Optional[dict] = None) -> None:
        """The coordinator's commit record — written only after every rank
        has reported success (two-phase; ≈ snapc marking the global
        snapshot valid)."""
        missing = [r for r in range(nranks)
                   if not os.path.exists(self._rank_file(seq, r))]
        if missing:
            raise MPIException(
                f"commit of snapshot {seq}: rank files missing for "
                f"{missing}", error_class=ERR_IO)
        meta = {"seq": seq, "nranks": nranks, "time": time.time(),
                "status": "committed"}
        if extra:
            meta.update(extra)
        tmp = os.path.join(self.snapshot_dir(seq), _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.snapshot_dir(seq), _META))

    # -- read path ---------------------------------------------------------

    def metadata(self, seq: int) -> Optional[dict]:
        try:
            with open(os.path.join(self.snapshot_dir(seq), _META)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def snapshots(self) -> list[int]:
        """All *committed* snapshot seqs, ascending."""
        out = []
        try:
            names = os.listdir(self.base)
        except OSError:
            return []
        for n in names:
            if n.startswith("snapshot_"):
                try:
                    seq = int(n.split("_", 1)[1])
                except ValueError:
                    continue
                if self.metadata(seq) is not None:
                    out.append(seq)
        return sorted(out)

    def latest(self) -> Optional[int]:
        s = self.snapshots()
        return s[-1] if s else None

    def load_rank(self, seq: int, rank: int) -> dict[str, np.ndarray]:
        meta = self.metadata(seq)
        if meta is None:
            raise MPIException(
                f"snapshot {seq} is not committed", error_class=ERR_IO)
        path = self._rank_file(seq, rank)
        try:
            with np.load(path) as z:
                return _untag_exotic(z)
        except OSError as e:
            raise MPIException(
                f"loading snapshot {seq} rank {rank}: {e}",
                error_class=ERR_IO) from None

    # -- lifecycle ---------------------------------------------------------

    def gc(self, keep_last: int) -> list[int]:
        """Drop old committed snapshots (and any uncommitted debris) —
        keep the newest `keep_last`. Returns removed seqs."""
        committed = self.snapshots()
        drop = committed[:-keep_last] if keep_last > 0 else committed
        removed = []
        for seq in drop:
            shutil.rmtree(self.snapshot_dir(seq), ignore_errors=True)
            removed.append(seq)
        # uncommitted debris older than the newest committed snapshot
        newest = committed[-1] if committed else None
        try:
            names = os.listdir(self.base)
        except OSError:
            return removed
        for n in names:
            if not n.startswith("snapshot_"):
                continue
            try:
                seq = int(n.split("_", 1)[1])
            except ValueError:
                continue
            if (self.metadata(seq) is None and newest is not None
                    and seq < newest):
                shutil.rmtree(self.snapshot_dir(seq), ignore_errors=True)
                removed.append(seq)
        return removed


class StagedStore(SnapshotStore):
    """sstore/stage + filem/raw: write node-local first, then stage the
    finished file into the central root with an atomic move (same-fs) or
    copy+rename (cross-fs)."""

    def __init__(self, base_dir: str, local_dir: str,
                 job: str = "job") -> None:
        super().__init__(base_dir, job)
        self.local = os.path.abspath(local_dir)
        os.makedirs(self.local, exist_ok=True)

    def write_rank(self, seq: int, rank: int,
                   state: dict[str, Any]) -> str:
        arrays = _tag_exotic({k: _to_host(v) for k, v in state.items()})
        local_path = os.path.join(self.local,
                                  f"stage_{seq}_rank_{rank}.npz")
        with open(local_path, "wb") as f:
            np.savez(f, **arrays)
        # filem/raw stage: move into the central snapshot dir
        d = self.snapshot_dir(seq)
        os.makedirs(d, exist_ok=True)
        dst = self._rank_file(seq, rank)
        try:
            os.replace(local_path, dst)
        except OSError:  # cross-filesystem: copy then atomic rename
            tmp = dst + ".tmp"
            shutil.copyfile(local_path, tmp)
            os.replace(tmp, dst)
            os.unlink(local_path)
        return dst


class ShardedSnapshotStore(SnapshotStore):
    """Single-file sharded checkpoints over collective MPI-IO.

    Where :class:`SnapshotStore` writes one ``rank_<r>.npz`` per rank
    (the reference's sstore/central file-per-proc layout), this store
    writes ONE file per array: each rank's block lands at its byte
    displacement through an MPI file view, and the write is a collective
    ``write_at_all`` — so it flows through the fcoll aggregation layer
    (on multi-host jobs: one OS writer per host, per the job mapping)
    instead of N independent OS streams.  This is the canonical
    parallel-IO checkpoint layout (the thing the reference builds from
    ROMIO + a parallel filesystem), and it ties ckpt/ to the io/ stack.

    Blocks may be ragged in SHAPE (per-rank shapes are allgathered and
    recorded in the commit metadata, so ``load`` returns exactly the
    block this rank saved — or any requested rank's block after a
    respawn); the DTYPE of each named array must agree across ranks,
    validated collectively at save time.
    """

    #: numpy's own limit is 32; the allgathered shape record carries 16
    MAX_NDIM = 16

    def __init__(self, base_dir: str, comm, job: str = "job") -> None:
        super().__init__(base_dir, job)
        self.comm = comm

    def _array_file(self, seq: int, name: str) -> str:
        if "/" in name or name.startswith("."):
            raise MPIException(f"bad array name {name!r}", error_class=3)
        return os.path.join(self.snapshot_dir(seq), f"{name}.bin")

    def write_rank(self, seq: int, rank: int, state: dict[str, Any]) -> str:
        raise MPIException(
            "ShardedSnapshotStore is collective — use save(seq, state) "
            "(the per-rank write_rank/commit protocol belongs to the "
            "file-per-rank stores)", error_class=3)

    def commit(self, seq: int, nranks: int,
               extra: Optional[dict] = None) -> None:
        raise MPIException(
            "ShardedSnapshotStore commits inside save()", error_class=3)

    def save(self, seq: int, state: dict[str, Any],
             extra: Optional[dict] = None) -> None:
        """Collective: every rank passes its LOCAL block per array name;
        blocks are concatenated in rank order in one shared file each.
        Rank 0 writes the commit record after all writes complete."""
        import zlib

        from ompi_tpu.mpi import io as mio
        from ompi_tpu.mpi.info import Info

        comm = self.comm
        # validate BEFORE the first collective: a raise after peers have
        # entered an allgather would strand them
        arrays = {}
        for name in sorted(state):
            arr = np.ascontiguousarray(_to_host(state[name]))
            if arr.ndim > self.MAX_NDIM:
                raise MPIException(
                    f"array {name!r} has ndim {arr.ndim} > "
                    f"{self.MAX_NDIM} (shape-record limit)", error_class=3)
            arrays[name] = arr
        d = self.snapshot_dir(seq)
        if comm.rank == 0:
            os.makedirs(d, exist_ok=True)
        comm.barrier()
        # the store's point is the aggregated shared-file write path, so
        # pin the collective component (the auto decision would classify
        # each rank's single contiguous run as individual IO)
        hints = Info({"fcoll": "two_phase"})
        shards_meta: dict[str, list] = {}
        for name, arr in arrays.items():
            # allgather per-rank (nbytes, ndim, shape…, dtype-crc)
            shp = np.zeros(2 + self.MAX_NDIM + 1, np.int64)
            shp[0] = arr.nbytes
            shp[1] = arr.ndim
            shp[2:2 + arr.ndim] = arr.shape
            shp[-1] = zlib.crc32(str(arr.dtype).encode())
            allm = np.asarray(comm.allgather(shp)).reshape(
                comm.size, len(shp))
            if len(set(int(c) for c in allm[:, -1])) != 1:
                raise MPIException(
                    f"array {name!r}: dtype differs across ranks "
                    f"(blocks may be ragged in shape, not dtype)",
                    error_class=3)
            offs = np.concatenate([[0], np.cumsum(allm[:, 0])])
            f = mio.File.open(comm, self._array_file(seq, name),
                              mio.MODE_RDWR | mio.MODE_CREATE,
                              info=hints)
            f.set_view(disp=int(offs[comm.rank]))
            f.write_at_all(0, arr.reshape(-1).view(np.uint8))
            f.close()
            shards_meta[name] = [{
                "rank": r,
                "offset": int(offs[r]),
                "nbytes": int(allm[r, 0]),
                "shape": [int(s) for s in
                          allm[r, 2:2 + int(allm[r, 1])]],
                "dtype": str(arr.dtype),
            } for r in range(comm.size)]
        comm.barrier()
        if comm.rank == 0:
            meta = {"seq": seq, "nranks": comm.size, "time": time.time(),
                    "status": "committed", "layout": "sharded-file",
                    "arrays": shards_meta}
            if extra:
                meta.update(extra)
            tmp = os.path.join(d, _META + ".tmp")
            with open(tmp, "w") as fh:
                json.dump(meta, fh)
            os.replace(tmp, os.path.join(d, _META))
        comm.barrier()

    def load(self, seq: int, rank: Optional[int] = None
             ) -> dict[str, np.ndarray]:
        """Collective read of each rank's own block (``rank`` overrides,
        e.g. a revived rank pulling its predecessor's shard).  Routed
        through read_at_all so aggregators coalesce the disk reads."""
        from ompi_tpu.mpi import io as mio

        meta = self.metadata(seq)
        if meta is None:
            raise MPIException(
                f"snapshot {seq} is not committed", error_class=ERR_IO)
        r = self.comm.rank if rank is None else int(rank)
        out: dict[str, np.ndarray] = {}
        from ompi_tpu.mpi.info import Info

        hints = Info({"fcoll": "two_phase"})
        for name, shards in meta["arrays"].items():
            rec = shards[r]
            f = mio.File.open(self.comm, self._array_file(seq, name),
                              mio.MODE_RDONLY, info=hints)
            f.set_view(disp=rec["offset"])
            raw = f.read_at_all(0, rec["nbytes"])
            f.close()
            out[name] = np.frombuffer(
                raw.tobytes(), dtype=np.dtype(rec["dtype"])
            ).reshape(rec["shape"]).copy()
        return out

    def load_rank(self, seq: int, rank: int) -> dict[str, np.ndarray]:
        """SnapshotStore-compatible accessor (used by restart plumbing)."""
        return self.load(seq, rank=rank)
