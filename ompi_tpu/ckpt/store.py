"""Snapshot storage: central and staged layouts.

≈ orte/mca/sstore — the `central` component (every rank writes straight
into the shared snapshot root) and the `stage` component (ranks write to
fast node-local storage first; a filem/raw-equivalent *stage* step then
moves the file into the central root).

Layout (one job root, monotonically numbered snapshots):

    <base>/<job>/snapshot_<seq>/rank_<r>.npz      per-rank array shards
    <base>/<job>/snapshot_<seq>/metadata.json     written LAST by rank 0

The metadata file is the commit record (two-phase: a snapshot without it
is garbage and is ignored/cleaned) — the same "all ranks report, then the
coordinator marks the snapshot valid" protocol snapc/full runs over its
RML channels, here carried by the collective layer instead.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Optional

import numpy as np

from ompi_tpu.mpi.constants import MPIException

__all__ = ["SnapshotStore", "StagedStore"]

_META = "metadata.json"


def _to_host(v: Any) -> np.ndarray:
    """Materialize any array-like (jax arrays included) on host."""
    return np.asarray(v)


class SnapshotStore:
    """sstore/central: ranks write directly into the shared root."""

    def __init__(self, base_dir: str, job: str = "job") -> None:
        self.base = os.path.join(os.path.abspath(base_dir), job)
        os.makedirs(self.base, exist_ok=True)

    # -- paths -------------------------------------------------------------

    def snapshot_dir(self, seq: int) -> str:
        return os.path.join(self.base, f"snapshot_{seq}")

    def _rank_file(self, seq: int, rank: int) -> str:
        return os.path.join(self.snapshot_dir(seq), f"rank_{rank}.npz")

    # -- write path --------------------------------------------------------

    def write_rank(self, seq: int, rank: int,
                   state: dict[str, Any]) -> str:
        """Serialize one rank's state dict (atomic: tmp file + rename)."""
        d = self.snapshot_dir(seq)
        os.makedirs(d, exist_ok=True)
        arrays = {k: _to_host(v) for k, v in state.items()}
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            dst = self._rank_file(seq, rank)
            os.replace(tmp, dst)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self._rank_file(seq, rank)

    def commit(self, seq: int, nranks: int,
               extra: Optional[dict] = None) -> None:
        """The coordinator's commit record — written only after every rank
        has reported success (two-phase; ≈ snapc marking the global
        snapshot valid)."""
        missing = [r for r in range(nranks)
                   if not os.path.exists(self._rank_file(seq, r))]
        if missing:
            raise MPIException(
                f"commit of snapshot {seq}: rank files missing for "
                f"{missing}", error_class=5)
        meta = {"seq": seq, "nranks": nranks, "time": time.time(),
                "status": "committed"}
        if extra:
            meta.update(extra)
        tmp = os.path.join(self.snapshot_dir(seq), _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.snapshot_dir(seq), _META))

    # -- read path ---------------------------------------------------------

    def metadata(self, seq: int) -> Optional[dict]:
        try:
            with open(os.path.join(self.snapshot_dir(seq), _META)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def snapshots(self) -> list[int]:
        """All *committed* snapshot seqs, ascending."""
        out = []
        try:
            names = os.listdir(self.base)
        except OSError:
            return []
        for n in names:
            if n.startswith("snapshot_"):
                try:
                    seq = int(n.split("_", 1)[1])
                except ValueError:
                    continue
                if self.metadata(seq) is not None:
                    out.append(seq)
        return sorted(out)

    def latest(self) -> Optional[int]:
        s = self.snapshots()
        return s[-1] if s else None

    def load_rank(self, seq: int, rank: int) -> dict[str, np.ndarray]:
        meta = self.metadata(seq)
        if meta is None:
            raise MPIException(
                f"snapshot {seq} is not committed", error_class=5)
        path = self._rank_file(seq, rank)
        try:
            with np.load(path) as z:
                return {k: z[k] for k in z.files}
        except OSError as e:
            raise MPIException(
                f"loading snapshot {seq} rank {rank}: {e}",
                error_class=5) from None

    # -- lifecycle ---------------------------------------------------------

    def gc(self, keep_last: int) -> list[int]:
        """Drop old committed snapshots (and any uncommitted debris) —
        keep the newest `keep_last`. Returns removed seqs."""
        committed = self.snapshots()
        drop = committed[:-keep_last] if keep_last > 0 else committed
        removed = []
        for seq in drop:
            shutil.rmtree(self.snapshot_dir(seq), ignore_errors=True)
            removed.append(seq)
        # uncommitted debris older than the newest committed snapshot
        newest = committed[-1] if committed else None
        try:
            names = os.listdir(self.base)
        except OSError:
            return removed
        for n in names:
            if not n.startswith("snapshot_"):
                continue
            try:
                seq = int(n.split("_", 1)[1])
            except ValueError:
                continue
            if (self.metadata(seq) is None and newest is not None
                    and seq < newest):
                shutil.rmtree(self.snapshot_dir(seq), ignore_errors=True)
                removed.append(seq)
        return removed


class StagedStore(SnapshotStore):
    """sstore/stage + filem/raw: write node-local first, then stage the
    finished file into the central root with an atomic move (same-fs) or
    copy+rename (cross-fs)."""

    def __init__(self, base_dir: str, local_dir: str,
                 job: str = "job") -> None:
        super().__init__(base_dir, job)
        self.local = os.path.abspath(local_dir)
        os.makedirs(self.local, exist_ok=True)

    def write_rank(self, seq: int, rank: int,
                   state: dict[str, Any]) -> str:
        arrays = {k: _to_host(v) for k, v in state.items()}
        local_path = os.path.join(self.local,
                                  f"stage_{seq}_rank_{rank}.npz")
        with open(local_path, "wb") as f:
            np.savez(f, **arrays)
        # filem/raw stage: move into the central snapshot dir
        d = self.snapshot_dir(seq)
        os.makedirs(d, exist_ok=True)
        dst = self._rank_file(seq, rank)
        try:
            os.replace(local_path, dst)
        except OSError:  # cross-filesystem: copy then atomic rename
            tmp = dst + ".tmp"
            shutil.copyfile(local_path, tmp)
            os.replace(tmp, dst)
            os.unlink(local_path)
        return dst
