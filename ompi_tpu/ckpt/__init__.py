"""Checkpoint/restart: snapshot stores, job-level coordination, message log.

≈ the reference's four cooperating FT layers (SURVEY §5):

- opal/mca/crs  (single-process image)   → per-rank state-dict serialization
  (a Python/JAX process's checkpointable state IS its arrays + a pytree of
  scalars; BLCR-style whole-process images are replaced by orbax-style
  array snapshots, which is also why no message draining is needed when
  checkpoints align with step boundaries)
- ompi/mca/crcp/bkmrk (quiesce/drain)    → a barrier at the step boundary
  (snapc.checkpoint is collective; SPMD programs have no in-flight
  user messages at a step boundary by construction)
- orte/mca/snapc/full (global coordination) → ckpt.snapc two-phase commit
- orte/mca/sstore/{central,stage} + filem/raw (storage/staging)
  → ckpt.store SnapshotStore / StagedStore
- ompi/mca/vprotocol/pessimist (message logging) → ckpt.msglog
"""

from ompi_tpu.ckpt.msglog import MessageLog
from ompi_tpu.ckpt.snapc import CheckpointManager, checkpoint, restart
from ompi_tpu.ckpt.store import (
    ShardedSnapshotStore, SnapshotStore, StagedStore,
)

__all__ = [
    "ShardedSnapshotStore",
    "SnapshotStore", "StagedStore", "checkpoint", "restart",
    "CheckpointManager", "MessageLog",
]
