"""Orbax interop: the ckpt layer's snapshot contract over
orbax.checkpoint — for users whose existing JAX stacks already manage
checkpoints with orbax (the ecosystem-standard store), while keeping
this framework's sequence/commit semantics.

Unlike :class:`~ompi_tpu.ckpt.store.SnapshotStore` (npz per rank) this
saves one orbax checkpoint per snapshot sequence, preserving pytree
structure and restoring arrays with their shardings when a mesh-aware
``abstract_state`` is given (orbax restores straight to devices —
sharded optimizer state from :mod:`ompi_tpu.parallel.zero` included).
"""

from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["OrbaxStore"]


class OrbaxStore:
    """Snapshot-sequence store backed by orbax.checkpoint."""

    def __init__(self, base_dir: str, job: str = "job") -> None:
        import orbax.checkpoint as ocp

        self.base = os.path.join(os.path.abspath(base_dir), job)
        os.makedirs(self.base, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()

    def snapshot_dir(self, seq: int) -> str:
        return os.path.join(self.base, f"snapshot_{seq}")

    def save(self, seq: int, state: Any, force: bool = True) -> str:
        """Write one snapshot (blocking; atomic via orbax's tmp+rename)."""
        path = self.snapshot_dir(seq)
        self._ckptr.save(path, state, force=force)
        self._ckptr.wait_until_finished()
        return path

    def restore(self, seq: int,
                abstract_state: Optional[Any] = None) -> Any:
        """Read a snapshot.  With ``abstract_state`` (a pytree of
        ``jax.ShapeDtypeStruct`` carrying shardings — build it with
        ``jax.eval_shape`` + ``jax.tree.map`` over live arrays), leaves
        restore directly onto devices with those shardings."""
        return self._ckptr.restore(self.snapshot_dir(seq),
                                   abstract_state)

    def latest(self) -> Optional[int]:
        """Highest committed snapshot sequence, or None."""
        seqs = []
        try:
            for name in os.listdir(self.base):
                if name.startswith("snapshot_"):
                    try:
                        seqs.append(int(name.split("_", 1)[1]))
                    except ValueError:
                        pass
        except OSError:
            return None
        return max(seqs) if seqs else None
