"""Multi-host mesh bootstrap: join the job-wide JAX coordination service.

The runtime side of the modex (≈ opal/mca/pmix/pmix.h:328-861: the
business-card exchange that feeds transport bring-up — fence :384, put
:396, get :407).  The launcher (plm) exports three facts into every rank's
environment:

- ``OMPI_TPU_COORD``  — ``host:port`` of the coordination service (a free
  port on rank 0's host, picked by the HNP);
- ``OMPI_TPU_NHOSTS`` — how many hosts the job spans;
- rank identity (``OMPI_TPU_RANK``/``SIZE``) from pmix.

``initialize_from_env()`` turns those into a global JAX view: every rank
becomes one ``jax.distributed`` process (rank 0 hosts the coordinator),
after which ``jax.devices()`` enumerates the chips of ALL hosts and a
``Mesh`` built over them shards programs across the pod — XLA collectives
ride ICI within a host/slice and DCN between them, which is the reference's
btl latency/bandwidth ranking (btl.h:1181-1183) decided by mesh layout
instead of parameters.

Single-chip caveat: with one real TPU behind a tunnel, multi-process TPU
bring-up is untestable on real hardware; the sim-plm test joins N CPU
processes through the same coordinator and checks the fused global device
view (``jax.process_count()``), which exercises every line of this path
except the TPU topology fan-in.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["ENV_COORD", "ENV_NHOSTS", "is_multihost_env",
           "initialize_from_env", "global_mesh"]

_log = output.get_stream("multihost")

ENV_COORD = "OMPI_TPU_COORD"
ENV_NHOSTS = "OMPI_TPU_NHOSTS"

register_var("multihost", "init_timeout", VarType.DOUBLE, 60.0,
             "seconds to wait for all ranks to join the jax.distributed "
             "coordination service")
register_var("multihost", "auto_init", VarType.BOOL, True,
             "join the job-wide device view during MPI init when the "
             "launcher exported a coordinator address")

_lock = threading.Lock()
_state = {"initialized": False}


def is_multihost_env() -> bool:
    """Did a multi-host launcher export a coordinator for this job?"""
    return ENV_COORD in os.environ


def initialize_from_env() -> bool:
    """Join the job-wide jax.distributed service if the env names one.

    Returns True once this process is part of the global device view
    (idempotent), False when the job is not multi-host.  Must run before
    any JAX backend use in this process — call it early (mpi.runtime.init
    does, when ``multihost_auto_init`` is on).
    """
    with _lock:
        if _state["initialized"]:
            return True
        if not is_multihost_env():
            return False
        coord = os.environ[ENV_COORD]
        rank = int(os.environ.get("OMPI_TPU_RANK", "0"))
        size = int(os.environ.get("OMPI_TPU_SIZE", "1"))
        timeout = int(var_registry.get("multihost_init_timeout") or 60)

        import jax

        # one jax.distributed process per rank: rank 0 hosts the
        # coordinator (the HNP picked its port on rank 0's host)
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=size,
            process_id=rank,
            initialization_timeout=timeout,
        )
        _state["initialized"] = True
        # NOTE: do NOT call jax.process_count()/device_count() here — they
        # force accelerator-backend initialization, and a rank whose chip
        # tunnel is down would hang inside MPI init (the join itself is
        # pure coordination-service gRPC).  The device view materializes
        # lazily on first backend use.
        _log.verbose(1, "multihost: rank %d/%d joined %s",
                     rank, size, coord)
        return True


def is_initialized() -> bool:
    return _state["initialized"]


def shutdown(graceful: bool = True) -> None:
    """Leave the coordination service (call after the final barrier, so
    every rank disconnects before rank 0's coordinator goes away).

    ``graceful=False`` skips the synchronized jax.distributed.shutdown —
    required when a rank was respawned mid-job: its coordination-service
    task never rejoined (a new incarnation is rejected), so the shutdown
    barrier would wait on it forever.  Process exit reclaims everything.
    """
    with _lock:
        if not _state["initialized"]:
            return
        _state["initialized"] = False
    if not graceful:
        _log.verbose(1, "multihost: skipping synchronized shutdown "
                     "(respawned rank in the job)")
        return

    def _do() -> None:
        try:
            import jax

            jax.distributed.shutdown()
        except Exception as e:  # pragma: no cover - teardown best-effort
            _log.verbose(1, "multihost shutdown: %r", e)

    # watchdog: the synchronized shutdown blocks on every task arriving.
    # If ranks DISAGREE about graceful (a respawn raced the decision) the
    # barrier would never fill — bound the wait so the worst case is a
    # delay, not a hang; process exit reclaims the service either way.
    t = threading.Thread(target=_do, daemon=True)
    t.start()
    t.join(timeout=10.0)
    if t.is_alive():  # pragma: no cover - requires a raced respawn
        _log.error("multihost: synchronized shutdown did not complete "
                   "in 10s (peer skipped it?); abandoning the wait")


def global_mesh(axes: Optional[dict | list] = None):
    """A Mesh over the job's GLOBAL device set (all hosts).

    In a multi-host job this first joins the coordination service; in a
    single-host job it is plain ``make_mesh`` over the local devices.
    """
    initialize_from_env()
    from ompi_tpu.parallel.mesh import make_mesh

    return make_mesh(axes)
