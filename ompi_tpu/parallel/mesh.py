"""Mesh construction and sharding helpers.

The device-mesh analog of ras/rmaps (SURVEY.md §2.6): where the reference
maps ranks onto nodes, the TPU build addresses chips as a
``jax.sharding.Mesh`` whose axes carry parallelism roles (dp/sp/tp/...).
``make_mesh`` respects hardware order (jax.devices() enumerates ICI
neighbors adjacently on TPU, so the innermost mesh axis rides the
fastest links — the latency/bandwidth ranking knob of btl.h:1181-1183,
decided by layout instead of parameters).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

__all__ = ["make_mesh", "mesh_shape_for"]


def mesh_shape_for(n_devices: int, axis_names: Sequence[str]) -> dict[str, int]:
    """Factor n_devices over the axes, largest factors innermost (the last
    axis gets the largest factor → tensor-parallel on the fastest links).

    Outer axes take the largest divisor ≤ the remaining geometric mean
    (rounded down), so the leftover — always ≥ the mean — lands innermost.
    """
    names = list(axis_names)
    shape = {name: 1 for name in names}
    remaining = n_devices
    for i, name in enumerate(names[:-1]):
        axes_left = len(names) - i
        target = int(math.floor(remaining ** (1 / axes_left)))
        f = 1
        for cand in range(max(1, target), 0, -1):
            if remaining % cand == 0:
                f = cand
                break
        shape[name] = f
        remaining //= f
    shape[names[-1]] = remaining
    return shape


def make_mesh(axes: Optional[dict[str, int] | Sequence[str]] = None,
              devices=None):
    """Build a Mesh.

    - ``make_mesh()`` → 1-D mesh ("world") over all devices.
    - ``make_mesh({"dp": 2, "tp": 4})`` → explicit shape (must multiply to
      the device count; a -1 entry is inferred).
    - ``make_mesh(["dp", "tp"])`` → auto-factored shape.
    """
    import jax
    from jax.sharding import Mesh

    devs = np.array(devices if devices is not None else jax.devices())
    n = devs.size
    if axes is None:
        return Mesh(devs.reshape(n), axis_names=("world",))
    if not isinstance(axes, dict):
        axes = mesh_shape_for(n, list(axes))
    names = list(axes)
    sizes = [axes[a] for a in names]
    if sizes.count(-1) == 1:
        known = -int(np.prod(sizes))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"mesh shape {dict(zip(names, sizes))} needs {total} devices, "
            f"have {n}")
    return Mesh(devs.reshape(sizes), axis_names=tuple(names))
