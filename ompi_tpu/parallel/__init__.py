"""Parallelism layer: mesh construction, sharding helpers, and the
long-context/parallelism primitives built on the framework's device
collectives — all five dimensions:

- **dp/sp/tp** — data, sequence (ring/Ulysses attention), and Megatron
  tensor parallelism (``attention``, ``layers``, the flagship model);
- **ep** — switch-MoE expert parallelism over all_to_all (``moe``);
- **pp** — GPipe pipeline schedule over ppermute (``pipeline``).

These are the TPU-native expression of the reference's communication
patterns (SURVEY.md §5): ring attention is the segmented-ring allreduce
shape (coll_base_allreduce.c:615) with double buffering; Ulysses and MoE
dispatch are the pairwise alltoall (coll_base_alltoall.c:132); the
pipeline handoff is the chain bcast's neighbor hop (coll_base_bcast.c:257).
"""

from ompi_tpu.parallel.mesh import make_mesh, mesh_shape_for
from ompi_tpu.parallel.moe import moe_params, switch_moe
from ompi_tpu.parallel.pipeline import gpipe
