"""Parallelism layer: mesh construction, sharding helpers, and the
long-context/sequence-parallel primitives (ring attention, all-to-all head
parallelism) built on the framework's device collectives.

These are the TPU-native expression of the reference's communication
patterns (SURVEY.md §5): ring attention is the segmented-ring allreduce
shape (coll_base_allreduce.c:615) with double buffering; Ulysses-style
sequence parallelism is the pairwise alltoall (coll_base_alltoall.c:132)
over the head dimension.
"""

from ompi_tpu.parallel.mesh import make_mesh, mesh_shape_for
