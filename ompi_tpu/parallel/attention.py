"""Sequence-parallel attention: ring attention and all-to-all (Ulysses-style).

Long-context support is first-class (SURVEY.md §5): a sequence longer than
one chip's HBM is sharded over a mesh axis, and attention runs either as

- **ring attention** — K/V blocks rotate around the ``sp`` ring via
  ``ppermute`` while each device accumulates its queries' attention with an
  online (flash-style) softmax.  Communication shape = the reference's
  segmented-ring allreduce (coll_base_allreduce.c:615): p-1 neighbor hops of
  1/p of the data, overlapped with compute by XLA. O(T_local²·sp) FLOPs,
  O(T_local) memory.
- **all-to-all (Ulysses)** — one ``all_to_all`` re-shards from
  sequence-sharded to head-sharded, full attention runs locally, and a
  second ``all_to_all`` restores sequence sharding.  Communication shape =
  pairwise alltoall (coll_base_alltoall.c:132). Needs heads % sp == 0.

Both are exact (not approximations) and differentiable; tests cross-check
them against gathered full attention on the virtual CPU mesh.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["local_attention", "local_attention_lse", "ring_attention",
           "ulysses_attention", "gathered_attention"]

_NEG = -1e30


def _flash_blocks(t_q: int, t_k: int) -> tuple[int, int]:
    """Resolve the ops_flash_block_q/k tuning vars against this shape:
    non-positive values and non-tiling combinations fall back (each side
    independently) to the kernel's 128 default.  flash_tiles stays the
    single source of the tiling rule."""
    from ompi_tpu.core.config import var_registry
    from ompi_tpu.ops.flash_attention import flash_tiles

    bq = int(var_registry.get("ops_flash_block_q") or 128)
    bk = int(var_registry.get("ops_flash_block_k") or 128)
    if bq <= 0:
        bq = 128
    if bk <= 0:
        bk = 128
    if not flash_tiles(t_q, t_k, bq, bk):
        if flash_tiles(t_q, t_k, bq, 128):
            bk = 128
        elif flash_tiles(t_q, t_k, 128, bk):
            bq = 128
        else:
            bq = bk = 128
    return bq, bk


def _flash_wanted(impl: str, t_q: int, t_k: int,
                  bq: int = 128, bk: int = 128) -> bool:
    """Route to the pallas kernel?  "auto" = yes on TPU when the shape
    tiles AT THE RESOLVED BLOCK SIZES (CPU test meshes keep the cheap
    jnp path — interpret-mode pallas is orders of magnitude slower and
    tests cross-check both paths explicitly); "flash" = required, raise
    if untileable."""
    import jax

    from ompi_tpu.ops.flash_attention import flash_tiles

    if impl == "jnp":
        return False
    tiles = flash_tiles(t_q, t_k, bq, bk)
    if impl == "flash":
        if not tiles:
            raise ValueError("flash impl needs block-tiling shapes")
        return True
    return tiles and jax.default_backend() == "tpu"


def local_attention(q, k, v, causal: bool = True,
                    q_offset=0, k_offset=0, scale: Optional[float] = None,
                    impl: str = "auto"):
    """Plain attention over local blocks; offsets give global positions for
    causal masking when the blocks are slices of a longer sequence (they
    may be traced int32 scalars — e.g. a ring hop's source index).

    Shapes: q (B, Tq, H, D), k/v (B, Tk, H, D) → (B, Tq, H, D).

    ``impl``: "flash" = the pallas blockwise kernel (ompi_tpu.ops),
    "jnp" = materialized scores, "auto" = flash on TPU when the shape
    tiles, jnp otherwise.
    """
    o, _ = local_attention_lse(q, k, v, causal=causal, q_offset=q_offset,
                               k_offset=k_offset, scale=scale, impl=impl)
    return o.astype(q.dtype)


def local_attention_lse(q, k, v, causal: bool = True,
                        q_offset=0, k_offset=0,
                        scale: Optional[float] = None, impl: str = "auto"):
    """:func:`local_attention` that also returns the (B, H, Tq) f32
    logsumexp — the merge state for combining partial attention blocks
    (ring hops).  Output dtype follows q for flash, f32 for jnp."""
    import jax.numpy as jnp

    scale = scale if scale is not None else q.shape[-1] ** -0.5
    bq, bk = _flash_blocks(q.shape[1], k.shape[1])
    if _flash_wanted(impl, q.shape[1], k.shape[1], bq, bk):
        from ompi_tpu.ops.flash_attention import flash_attention_lse

        return flash_attention_lse(q, k, v, causal=causal,
                                   q_offset=q_offset, k_offset=k_offset,
                                   scale=scale, block_q=bq, block_k=bk)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG)
    m = scores.max(axis=-1)                                   # (B,H,Tq)
    w = jnp.exp(scores - m[..., None])
    if causal:
        w = jnp.where(mask[None, None], w, 0.0)
    l = w.sum(axis=-1)
    safe_l = jnp.maximum(l, 1e-30)
    o = jnp.einsum("bhqk,bkhd->bqhd", w.astype(q.dtype), v,
                   preferred_element_type=jnp.float32)
    o = o / safe_l.transpose(0, 2, 1)[..., None]
    return o, m + jnp.log(safe_l)


def ring_attention(comm, q, k, v, axis: Optional[str] = None,
                   causal: bool = True, scale: Optional[float] = None,
                   impl: str = "auto"):
    """Exact attention over a sequence sharded along ``axis`` of
    ``comm.mesh``; call inside shard_map.

    Each step attends my queries against the currently-held K/V block —
    through the pallas flash kernel on TPU (``impl="auto"``; the hop's
    traced source index feeds the kernel's k_offset) — then rotates K/V
    one hop around the ring (device r → r+1), so after sp steps every
    (query, key) pair has met.  Hop results are merged by their logsumexp
    (out' = out·σ(lse) + out_i·σ(lse_i), σ = softmax over hop lse), the
    blockwise-attention identity; everything accumulates in float32.
    """
    import jax.numpy as jnp
    from jax import lax

    ax = axis or comm.axes[-1]
    sp = int(comm.mesh.shape[ax])
    if sp == 1:  # degenerate ring: skip the loop machinery entirely
        return local_attention(q, k, v, causal=causal, scale=scale,
                               impl=impl)
    my = lax.axis_index(ax)
    B, T, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(i, carry):
        out, lse, k_cur, v_cur = carry
        src = (my - i) % sp  # whose block I currently hold
        o_i, lse_i = local_attention_lse(
            q, k_cur, v_cur, causal=causal, q_offset=my * T,
            k_offset=src * T, scale=scale, impl=impl)
        lse_new = jnp.logaddexp(lse, lse_i)               # (B,H,Tq)
        c_old = jnp.exp(lse - lse_new)
        c_new = jnp.exp(lse_i - lse_new)
        # (B,H,Tq) coefficients against (B,Tq,H,D) outputs
        out = (out * c_old.transpose(0, 2, 1)[..., None]
               + o_i.astype(jnp.float32)
               * c_new.transpose(0, 2, 1)[..., None])
        k_nxt = lax.ppermute(k_cur, ax, perm)
        v_nxt = lax.ppermute(v_cur, ax, perm)
        return (out, lse_new, k_nxt, v_nxt)

    out0 = jnp.zeros((B, T, H, D), jnp.float32)
    lse0 = jnp.full((B, H, T), _NEG, jnp.float32)
    out, _, _, _ = lax.fori_loop(0, sp, step, (out0, lse0, k, v))
    return out.astype(q.dtype)


def ulysses_attention(comm, q, k, v, axis: Optional[str] = None,
                      causal: bool = True, scale: Optional[float] = None,
                      impl: str = "auto"):
    """All-to-all sequence parallelism: re-shard seq→heads, attend fully
    locally, re-shard back.  Exact; one alltoall each way.  The local
    attention runs the pallas flash kernel with ``impl='flash'`` (static
    offsets by construction — the canonical place to use it)."""
    from jax import lax

    ax = axis or comm.axes[-1]
    sp = int(comm.mesh.shape[ax])
    H = q.shape[2]
    if H % sp:
        raise ValueError(f"ulysses needs heads ({H}) divisible by sp ({sp})")
    if sp == 1:
        # degenerate axis: a single-participant all_to_all still lowers
        # to a channel op (copy + scheduling barrier, 4 per layer) —
        # skip the resharding entirely
        return local_attention(q, k, v, causal=causal, scale=scale,
                               impl=impl)
    # (B, T/sp, H, D) → (B, T, H/sp, D)
    q2, k2, v2 = (lax.all_to_all(t, ax, split_axis=2, concat_axis=1,
                                 tiled=True) for t in (q, k, v))
    o = local_attention(q2, k2, v2, causal=causal, scale=scale, impl=impl)
    # (B, T, H/sp, D) → (B, T/sp, H, D)
    return lax.all_to_all(o, ax, split_axis=1, concat_axis=2, tiled=True)


def gathered_attention(comm, q, k, v, axis: Optional[str] = None,
                       causal: bool = True, scale: Optional[float] = None):
    """Reference implementation: allgather K/V and attend (O(T) memory per
    device — the thing ring attention exists to avoid). Used for testing."""
    import jax.numpy as jnp
    from jax import lax

    ax = axis or comm.axes[-1]
    if int(comm.mesh.shape[ax]) == 1:
        return local_attention(q, k, v, causal=causal, scale=scale)
    my = lax.axis_index(ax)
    T = q.shape[1]
    k_all = lax.all_gather(k, ax, axis=1, tiled=True)
    v_all = lax.all_gather(v, ax, axis=1, tiled=True)
    return local_attention(q, k_all, v_all, causal=causal,
                           q_offset=my * T, k_offset=0, scale=scale)
