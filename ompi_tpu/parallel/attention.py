"""Sequence-parallel attention: ring attention and all-to-all (Ulysses-style).

Long-context support is first-class (SURVEY.md §5): a sequence longer than
one chip's HBM is sharded over a mesh axis, and attention runs either as

- **ring attention** — K/V blocks rotate around the ``sp`` ring via
  ``ppermute`` while each device accumulates its queries' attention with an
  online (flash-style) softmax.  Communication shape = the reference's
  segmented-ring allreduce (coll_base_allreduce.c:615): p-1 neighbor hops of
  1/p of the data, overlapped with compute by XLA. O(T_local²·sp) FLOPs,
  O(T_local) memory.
- **all-to-all (Ulysses)** — one ``all_to_all`` re-shards from
  sequence-sharded to head-sharded, full attention runs locally, and a
  second ``all_to_all`` restores sequence sharding.  Communication shape =
  pairwise alltoall (coll_base_alltoall.c:132). Needs heads % sp == 0.

Both are exact (not approximations) and differentiable; tests cross-check
them against gathered full attention on the virtual CPU mesh.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["local_attention", "ring_attention", "ulysses_attention",
           "gathered_attention"]

_NEG = -1e30


def local_attention(q, k, v, causal: bool = True,
                    q_offset=0, k_offset=0, scale: Optional[float] = None,
                    impl: str = "jnp"):
    """Plain attention over local blocks; offsets give global positions for
    causal masking when the blocks are slices of a longer sequence.

    Shapes: q (B, Tq, H, D), k/v (B, Tk, H, D) → (B, Tq, H, D).

    ``impl``: "flash" = the pallas blockwise kernel (ompi_tpu.ops),
    "jnp" = materialized scores, "auto" = flash when the shape tiles and
    the offsets are static (traced offsets — e.g. a traced ring source
    index — need the jnp path).
    """
    import jax.numpy as jnp

    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if impl != "jnp":
        from ompi_tpu.ops import flash_attention
        from ompi_tpu.ops.flash_attention import flash_tiles

        static_offsets = isinstance(q_offset, int) and isinstance(
            k_offset, int)
        if static_offsets and flash_tiles(q.shape[1], k.shape[1]):
            return flash_attention(q, k, v, causal=causal,
                                   q_offset=q_offset, k_offset=k_offset,
                                   scale=scale)
        if impl == "flash":
            raise ValueError(
                "flash impl needs static offsets and block-tiling shapes")
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG)
    w = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def ring_attention(comm, q, k, v, axis: Optional[str] = None,
                   causal: bool = True, scale: Optional[float] = None):
    """Exact attention over a sequence sharded along ``axis`` of
    ``comm.mesh``; call inside shard_map.

    Each step attends my queries against the currently-held K/V block, then
    rotates K/V one hop around the ring (device r → r+1), so after sp steps
    every (query, key) pair has met.  Accumulation is the numerically-stable
    online softmax (running max m, normalizer l, weighted value sum acc) in
    float32.
    """
    import jax.numpy as jnp
    from jax import lax

    ax = axis or comm.axes[-1]
    sp = int(comm.mesh.shape[ax])
    if sp == 1:  # degenerate ring: skip the loop machinery entirely
        return local_attention(q, k, v, causal=causal, scale=scale)
    my = lax.axis_index(ax)
    B, T, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5

    qf = q.astype(jnp.float32)
    qpos = my * T + jnp.arange(T)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(i, carry):
        m, l, acc, k_cur, v_cur = carry
        src = (my - i) % sp  # whose block I currently hold
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_cur.astype(jnp.float32)) * scale
        if causal:
            kpos = src * T + jnp.arange(T)
            keep = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(keep[None, None], scores, _NEG)
        s_max = scores.max(axis=-1)                       # (B,H,Tq)
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(scores - m_new[..., None])            # (B,H,Tq,Tk)
        if causal:
            p = jnp.where(keep[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)                         # (B,H,Tq)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32))
        acc_new = acc * corr[..., None] + pv
        k_nxt = lax.ppermute(k_cur, ax, perm)
        v_nxt = lax.ppermute(v_cur, ax, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt)

    m0 = jnp.full((B, H, T), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    acc0 = jnp.zeros((B, H, T, D), jnp.float32)
    m, l, acc, _, _ = lax.fori_loop(0, sp, step, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]          # (B,H,Tq,D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)      # (B,Tq,H,D)


def ulysses_attention(comm, q, k, v, axis: Optional[str] = None,
                      causal: bool = True, scale: Optional[float] = None,
                      impl: str = "jnp"):
    """All-to-all sequence parallelism: re-shard seq→heads, attend fully
    locally, re-shard back.  Exact; one alltoall each way.  The local
    attention runs the pallas flash kernel with ``impl='flash'`` (static
    offsets by construction — the canonical place to use it)."""
    from jax import lax

    ax = axis or comm.axes[-1]
    sp = int(comm.mesh.shape[ax])
    H = q.shape[2]
    if H % sp:
        raise ValueError(f"ulysses needs heads ({H}) divisible by sp ({sp})")
    # (B, T/sp, H, D) → (B, T, H/sp, D)
    q2, k2, v2 = (lax.all_to_all(t, ax, split_axis=2, concat_axis=1,
                                 tiled=True) for t in (q, k, v))
    o = local_attention(q2, k2, v2, causal=causal, scale=scale, impl=impl)
    # (B, T, H/sp, D) → (B, T/sp, H, D)
    return lax.all_to_all(o, ax, split_axis=1, concat_axis=2, tiled=True)


def gathered_attention(comm, q, k, v, axis: Optional[str] = None,
                       causal: bool = True, scale: Optional[float] = None):
    """Reference implementation: allgather K/V and attend (O(T) memory per
    device — the thing ring attention exists to avoid). Used for testing."""
    import jax.numpy as jnp
    from jax import lax

    ax = axis or comm.axes[-1]
    my = lax.axis_index(ax)
    T = q.shape[1]
    k_all = lax.all_gather(k, ax, axis=1, tiled=True)
    v_all = lax.all_gather(v, ax, axis=1, tiled=True)
    return local_attention(q, k_all, v_all, causal=causal,
                           q_offset=my * T, k_offset=0, scale=scale)
