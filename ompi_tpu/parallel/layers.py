"""Tensor-parallel building blocks (Megatron-style column/row sharding).

The matmul-sharding recipe of the scaling playbook: a column-parallel matmul
keeps its activation sharded over ``tp`` (no comm), the following
row-parallel matmul contracts the sharded dimension and finishes with one
``psum`` over ``tp`` — one allreduce per MLP/attention block, riding ICI.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["column_parallel", "row_parallel"]


def column_parallel(x, w_shard):
    """x: (..., D) replicated over tp; w_shard: (D, F/tp) local shard.
    Returns (..., F/tp) — output stays tp-sharded, no communication."""
    import jax.numpy as jnp

    return jnp.einsum("...d,df->...f", x, w_shard)


def row_parallel(x_shard, w_shard, comm, axis: Optional[str] = None):
    """x_shard: (..., F/tp); w_shard: (F/tp, D).  Contracts the sharded
    dimension and psums partial products over tp → replicated (..., D)."""
    import jax.numpy as jnp
    from jax import lax

    partial = jnp.einsum("...f,fd->...d", x_shard, w_shard)
    ax = axis or comm.axes[-1]
    if int(comm.mesh.shape[ax]) == 1:
        return partial  # degenerate tp: psum is identity, skip the channel op
    return lax.psum(partial, ax)
