"""ZeRO-1 optimizer-state sharding over a mesh axis.

The optimizer's persistent tree (f32 master weights + Adam moments) is
the largest HBM resident after activations; under data parallelism it is
redundantly replicated.  ZeRO stage 1 shards it over the dp axis: each
rank stores and updates 1/dp of every leaf, then the updated parameters
are re-gathered to replicated form for the next forward.

TPU-first realization: no parameter server, no hand-written gather — each
leaf is flattened, padded to a dp multiple and reshaped to (dp, n); the
optimizer state carries a `NamedSharding(mesh, P(axis))` on that leading
axis, `with_sharding_constraint` pins the update math to the shards, and
XLA's SPMD partitioner emits exactly one all-gather per leaf to produce
the replicated updated params (the scaling-book recipe: annotate
shardings, let XLA insert the collectives).

Reference analog: there is none in Open MPI itself — this is the
distributed-training subsystem the flagship model exercises (SURVEY §5
row 77/78 scale story); the pattern matches optimizer sharding in public
JAX training stacks.
"""

from __future__ import annotations

from typing import Any

__all__ = ["zero1_wrap"]


def _flatten_pad(x, dp: int):
    import jax.numpy as jnp

    flat = jnp.ravel(x)
    n = -(-flat.size // dp) * dp
    if n != flat.size:
        flat = jnp.pad(flat, (0, n - flat.size))
    return flat.reshape(dp, n // dp)


def zero1_wrap(opt, mesh, axis: str = "dp", param_dtype: Any = None,
               param_specs: Any = None):
    """Wrap an optax GradientTransformation into a ZeRO-1 sharded update.

    Returns (init, update):
      init(params)  -> opt_state whose every leaf is (dp, n/dp)-shaped
                       and committed to NamedSharding(mesh, P(axis))
                       (state = {"opt": inner_state, "master": f32 tree})
      update(grads, opt_state, params) -> (new_params, new_opt_state)
                       for use INSIDE jit: shards the Adam math over
                       ``axis`` and re-gathers the updated params.

    ``param_dtype``: dtype of the returned live params (the master copy
    stays f32, exactly the mixed-precision master-weights scheme).
    ``param_specs``: optional pytree of PartitionSpec matching params —
    updated live params are constrained to THESE specs (tp-sharded
    weights stay tp-sharded; only the ``axis`` redundancy is gathered).
    Without it params re-gather fully replicated.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.shape:
        raise ValueError(
            f"zero1 axis {axis!r} is not a mesh axis "
            f"(have {tuple(mesh.shape)}); set zero1_axis to one of "
            f"those or None")
    dp = int(mesh.shape[axis])
    shard = NamedSharding(mesh, P(axis))

    def init(params):
        def prep(p):
            return jax.device_put(
                _flatten_pad(jnp.asarray(p, jnp.float32), dp), shard)

        master = jax.tree_util.tree_map(prep, params)
        inner = opt.init(master)
        # moments inherit master's (dp, n) shape; commit them to the
        # same sharding so the jitted update starts sharded, not
        # replicated-then-resharded
        inner = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, shard)
            if getattr(leaf, "ndim", 0) == 2 else leaf, inner)
        return {"opt": inner, "master": master}

    def update(grads, opt_state, params):
        del params  # the master copy is authoritative
        constrain = jax.lax.with_sharding_constraint

        def shard_grad(g):
            return constrain(_flatten_pad(g.astype(jnp.float32), dp),
                             shard)

        g32 = jax.tree_util.tree_map(shard_grad, grads)
        import optax

        updates, inner = opt.update(g32, opt_state["opt"],
                                    opt_state["master"])
        master = optax.apply_updates(opt_state["master"], updates)
        master = jax.tree_util.tree_map(
            lambda m: constrain(m, shard), master)
        # moments must STAY sharded too — without the constraint their
        # post-step sharding is whatever propagation decides, and a
        # replicated resolution would silently undo the HBM saving
        inner = jax.tree_util.tree_map(
            lambda leaf: constrain(leaf, shard)
            if getattr(leaf, "ndim", 0) == 2 else leaf, inner)

        def regather(m, p_like, spec):
            # constraint to the param's own spec = the SPMD partitioner
            # gathers ONLY the `axis` redundancy; tp/ep-sharded weights
            # stay sharded
            full = m.reshape(-1)[:p_like.size].reshape(p_like.shape)
            tgt = NamedSharding(mesh, spec if spec is not None else P())
            return constrain(full, tgt).astype(
                param_dtype or p_like.dtype)

        # manual flatten: PartitionSpec is itself a pytree node, so a
        # naive tree_map over the specs tree would recurse INTO the
        # specs; flatten_up_to treats each spec as one leaf
        m_leaves, treedef = jax.tree_util.tree_flatten(master)
        g_leaves = treedef.flatten_up_to(grads)
        s_leaves = (treedef.flatten_up_to(param_specs)
                    if param_specs is not None
                    else [None] * len(m_leaves))
        new_params = jax.tree_util.tree_unflatten(
            treedef, [regather(m, g, s) for m, g, s
                      in zip(m_leaves, g_leaves, s_leaves)])
        return new_params, {"opt": inner, "master": master}

    return init, update
