"""Expert parallelism: a switch-style MoE layer over an ``ep`` mesh axis.

The fourth parallelism dimension (after dp/sp/tp): experts shard over
``ep`` and tokens travel to their expert's device through
``all_to_all`` — the communication pattern the reference realizes as
pairwise alltoall (coll_base_alltoall.c:132) and this framework lowers to
one fused ICI exchange each way.

Design (top-1 "switch" routing, capacity-factor dispatch — the standard
SPMD formulation, all shapes static):

1. gate: ``logits = x @ wg`` → top-1 expert per token, gate prob ``p``.
2. capacity ``C = ceil(tokens_per_device / E · capacity_factor)``; for
   each expert, the first C tokens routed to it are kept (position by
   cumulative count), the rest are DROPPED (standard switch semantics —
   the residual connection carries dropped tokens unchanged).
3. dispatch: one-hot combine matrix (T_local × E × C) built with
   MXU-friendly one-hots; ``all_to_all`` ships (E, C, D) token blocks to
   the expert-owning devices.
4. each device runs its local experts' FFN on (E_local · ep, C, D).
5. the inverse ``all_to_all`` + combine matrix returns outputs to their
   source positions, scaled by the gate prob.

Exact: a pure-numpy reference with identical routing reproduces the
layer bit-for-bit (tests/parallel/test_moe.py).
"""

from __future__ import annotations

from typing import Optional

__all__ = ["switch_moe", "moe_params"]


def moe_params(rng, d_model: int, d_ff: int, n_experts: int,
               dtype="float32"):
    """Gate + per-expert FFN weights (experts stacked on axis 0)."""
    import numpy as np

    def w(*shape, scale=None):
        scale = scale if scale is not None else (shape[-2] ** -0.5)
        return rng.normal(0, scale, size=shape).astype(dtype)

    return {
        "wg": w(d_model, n_experts, scale=0.02),
        "w1": w(n_experts, d_model, d_ff),
        "w2": w(n_experts, d_ff, d_model),
    }


def switch_moe(comm, x, params, axis: str = "ep",
               capacity_factor: float = 1.25,
               capacity: Optional[int] = None,
               with_aux: bool = False):
    """Top-1 MoE layer inside shard_map: x (B, T, D) local tokens →
    (B, T, D).  ``params['w1']/['w2']`` hold the LOCAL experts
    (E_local = E / ep_size rows on each device); ``wg`` is replicated.

    ``with_aux=True`` additionally returns the switch load-balancing
    loss ``E · Σ_e f_e · p_e`` (fraction routed × mean gate prob per
    expert, over THIS device's tokens) — add it to the training loss
    scaled by ~1e-2 or experts collapse onto one device.

    Call with ``axis=None`` (or an absent axis) for the single-device
    degenerate case — routing and capacity behave identically, only the
    all_to_all disappears.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, T, D = x.shape
    if axis in comm.mesh.axis_names and axis not in comm.axes:
        raise ValueError(f"axis {axis!r} not bound to this communicator "
                         f"(axes {comm.axes})")
    ep = int(comm.mesh.shape[axis]) if axis in comm.mesh.axis_names else 1
    e_local = params["w1"].shape[0]
    E = e_local * ep
    n_tok = B * T
    if capacity is None:
        import math

        capacity = max(1, math.ceil((n_tok / E) * capacity_factor))
    C = capacity

    xf = x.reshape(n_tok, D)
    logits = jnp.einsum("td,de->te", xf, params["wg"].astype(x.dtype),
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                  # (n_tok,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    # position of each token within its expert's queue (0-based); tokens
    # at position >= C are dropped
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # (n_tok, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot            # 1-based in-slot
    pos = pos.sum(axis=-1) - 1                           # (n_tok,)
    keep = pos < C

    # dispatch tensor (n_tok, E, C): MXU-friendly one-hot outer product
    dis = (onehot.astype(x.dtype)[:, :, None]
           * jax.nn.one_hot(jnp.where(keep, pos, C), C + 1,
                            dtype=x.dtype)[:, None, :-1]
           )                                             # (n_tok, E, C)
    send = jnp.einsum("tec,td->ecd", dis, xf)            # (E, C, D)

    if ep > 1:
        # (E, C, D) → every device ends with (E_local·ep, C, D): the
        # blocks of ITS experts from every source device
        send = comm.alltoall_stacked(send.reshape(ep, e_local, C, D),
                                     axis=axis)
        # (ep, e_local, C, D): source-device-major blocks of my experts
        recv = send.reshape(ep, e_local, C, D)
    else:
        recv = send.reshape(1, e_local, C, D)

    # expert FFN on my local experts (batched over source devices)
    w1 = params["w1"].astype(x.dtype)                    # (e_local, D, F)
    w2 = params["w2"].astype(x.dtype)                    # (e_local, F, D)
    h = jnp.einsum("secd,edf->secf", recv, w1,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    h = jax.nn.gelu(h)
    out = jnp.einsum("secf,efd->secd", h, w2,
                     preferred_element_type=jnp.float32).astype(x.dtype)

    if ep > 1:
        # inverse exchange: give every source device back its tokens
        out = comm.alltoall_stacked(out, axis=axis)
        out = out.reshape(E, C, D)
    else:
        out = out.reshape(E, C, D)

    # combine back to token positions, scaled by the gate prob; dropped
    # tokens contribute zero (their residual path carries them)
    y = jnp.einsum("tec,ecd->td", dis, out)
    y = y * gate[:, None].astype(x.dtype)
    y = y.reshape(B, T, D)
    if not with_aux:
        return y
    # switch load-balancing loss (Fedus et al.): differentiable through
    # the mean gate prob; the routed fraction is the (stop-grad) signal
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)      # (E,)
    mean_p = jnp.mean(probs, axis=0)                         # (E,)
    aux = E * jnp.sum(frac * mean_p)
    return y, aux
