"""Pipeline parallelism: a GPipe microbatch schedule over a ``pp`` axis.

The fifth parallelism dimension (dp/sp/tp/ep/pp): layers shard into
stages over ``pp``; activations flow stage→stage through single-hop
``ppermute`` (the neighbor-exchange wire pattern of the reference's
chain/pipeline broadcast, coll_base_bcast.c:257), and M microbatches keep
every stage busy outside the (pp−1)-tick fill/drain bubbles.

SPMD formulation (everything static for XLA): all devices run the same
``lax.fori_loop`` of M+pp−1 ticks; at tick t device d computes microbatch
``m = t − d`` (garbage outside [0, M) — discarded by masking, the
standard bubble cost), then the activations rotate one hop while stage 0
injects the next microbatch.  Outputs accumulate on the last stage and a
final masked psum replicates them (one collective, for a clean return
contract).
"""

from __future__ import annotations

from typing import Callable

__all__ = ["gpipe"]


def gpipe(comm, stage_fn: Callable, stage_params, x, microbatches: int,
          axis: str = "pp"):
    """Run ``stage_fn(stage_params, h)`` as a pp-deep pipeline inside
    shard_map.

    - ``stage_params``: THIS device's stage weights (shard the stacked
      per-stage pytree with ``P('pp')`` in the enclosing shard_map).
    - ``x``: (B, ...) input, same on every device (or valid on stage 0 —
      others' copies are ignored); B must divide by ``microbatches``.
    - returns (B, ...) output of the full stage chain, replicated.

    Activations must keep the same shape through every stage (uniform
    pipelines — the GPipe assumption).
    """
    import jax.numpy as jnp
    from jax import lax

    if axis not in comm.axes:
        raise ValueError(f"axis {axis!r} not bound to this communicator "
                         f"(axes {comm.axes})")
    pp = int(comm.mesh.shape[axis])
    d = lax.axis_index(axis)
    B = x.shape[0]
    M = microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    x_mb = x.reshape((M, mb) + x.shape[1:])
    if pp == 1:
        y = stage_fn(stage_params, x)
        return y

    perm = [(i, i + 1) for i in range(pp - 1)]  # stage d → d+1 (no wrap)
    last = pp - 1

    def tick(t, carry):
        cur, out = carry
        y = stage_fn(stage_params, cur)          # bubbles compute garbage
        m = t - d                                # my microbatch this tick
        # last stage: write finished microbatch m into its output slot
        m_clamp = jnp.clip(m, 0, M - 1)
        valid_out = (d == last) & (m >= 0) & (m < M)
        slot = lax.dynamic_index_in_dim(out, m_clamp, 0, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(valid_out, y, slot), m_clamp, 0)
        # rotate activations one stage forward; stage 0 injects the next
        shifted = comm.permute(y, perm, axis=axis)
        nxt_idx = jnp.clip(t + 1, 0, M - 1)
        inject = lax.dynamic_index_in_dim(x_mb, nxt_idx, 0,
                                          keepdims=False)
        cur = jnp.where(d == 0, inject, shifted)
        return cur, out

    cur0 = jnp.where(d == 0, x_mb[0], jnp.zeros_like(x_mb[0]))
    out0 = jnp.zeros_like(x_mb)
    _, out = lax.fori_loop(0, M + pp - 1, tick, (cur0, out0))
    # replicate: every slot was written exactly once, on the last stage
    out = comm.sub((axis,)).allreduce(
        jnp.where(d == last, out, jnp.zeros_like(out)))
    return out.reshape((B,) + x.shape[1:])
