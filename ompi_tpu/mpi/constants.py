"""MPI-style constants (≈ mpi.h values; semantics, not numeric parity)."""

from __future__ import annotations

ANY_SOURCE = -1  # MPI_ANY_SOURCE: match a message from any rank
ANY_TAG = -2     # MPI_ANY_TAG: match any tag
PROC_NULL = -3   # MPI_PROC_NULL: send/recv to nowhere completes immediately
ROOT = -4        # MPI_ROOT (intercomm collectives)
UNDEFINED = -32766  # MPI_UNDEFINED (e.g. split color, no-group rank)

# MPI_Comm_split_type types
COMM_TYPE_SHARED = 1   # ranks that share a memory domain (same host)


class _InPlace:
    """Singleton marker for MPI_IN_PLACE."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "IN_PLACE"


IN_PLACE = _InPlace()

# Error classes (subset of MPI_ERR_*)
SUCCESS = 0
ERR_COMM = 5
ERR_RANK = 6
ERR_TAG = 4
ERR_COUNT = 2
ERR_TYPE = 3
ERR_TRUNCATE = 15
ERR_PENDING = 18
ERR_IN_STATUS = 19


class MPIException(RuntimeError):
    """Raised by MPI-layer operations (≈ error handler MPI_ERRORS_RETURN path)."""

    def __init__(self, msg: str, error_class: int = 13) -> None:
        super().__init__(msg)
        self.error_class = error_class
