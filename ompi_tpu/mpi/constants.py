"""MPI-style constants (≈ mpi.h values; semantics, not numeric parity)."""

from __future__ import annotations

ANY_SOURCE = -1  # MPI_ANY_SOURCE: match a message from any rank
ANY_TAG = -2     # MPI_ANY_TAG: match any tag
PROC_NULL = -3   # MPI_PROC_NULL: send/recv to nowhere completes immediately
ROOT = -4        # MPI_ROOT (intercomm collectives)
UNDEFINED = -32766  # MPI_UNDEFINED (e.g. split color, no-group rank)

# MPI_Comm_split_type types
COMM_TYPE_SHARED = 1   # ranks that share a memory domain (same host)


class _InPlace:
    """Singleton marker for MPI_IN_PLACE."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "IN_PLACE"


IN_PLACE = _InPlace()

# Error classes (subset of MPI_ERR_*)
SUCCESS = 0
ERR_BUFFER = 1
ERR_COMM = 5
ERR_RANK = 6
ERR_TAG = 4
ERR_COUNT = 2
ERR_TYPE = 3
ERR_TRUNCATE = 15
ERR_OTHER = 16
ERR_PENDING = 18
ERR_IN_STATUS = 19
ERR_INTERN = 13
ERR_NAME = 33     # MPI_ERR_NAME: service name not published
ERR_SERVICE = 41  # MPI_ERR_SERVICE: publish/unpublish failure
ERR_PORT = 27     # MPI_ERR_PORT: invalid/unknown port name
ERR_IO = 38

# ULFM fault-tolerance error classes (MPI_ERR_PROC_FAILED & friends —
# the user-level fault tolerance chapter's additions; numbered in the
# post-standard space the ULFM prototype uses)
ERR_PROC_FAILED = 75          # target/peer process is dead
ERR_PROC_FAILED_PENDING = 76  # wildcard recv cannot complete: peer died
ERR_REVOKED = 77              # the communicator was revoked

_ERROR_STRINGS = {
    SUCCESS: "no error",
    ERR_BUFFER: "invalid buffer",
    ERR_COUNT: "invalid count argument",
    ERR_TYPE: "invalid datatype argument",
    ERR_TAG: "invalid tag argument",
    ERR_COMM: "invalid communicator",
    ERR_RANK: "invalid rank",
    ERR_TRUNCATE: "message truncated on receive",
    ERR_OTHER: "known error not in this list",
    ERR_INTERN: "internal error",
    ERR_PENDING: "pending request",
    ERR_IN_STATUS: "error code in status",
    ERR_NAME: "service name not published",
    ERR_SERVICE: "name service operation failed",
    ERR_PORT: "invalid port name",
    ERR_IO: "I/O error",
    ERR_PROC_FAILED: "peer process has failed",
    ERR_PROC_FAILED_PENDING: "operation pending on a failed process",
    ERR_REVOKED: "communicator has been revoked",
}


# Dynamic error classes/codes (≈ ompi/errhandler/errcode.c's user space):
# user classes/codes are allocated above LASTCODE so they never collide
# with the predefined table.
LASTUSEDCODE = 100  # ≈ MPI_LASTUSEDCODE attribute's initial value
_user_next = [LASTUSEDCODE + 1]
_user_class_of: dict[int, int] = {}   # code → its error class


def add_error_class() -> int:
    """≈ MPI_Add_error_class: allocate a fresh user error class."""
    cls = _user_next[0]
    _user_next[0] += 1
    _user_class_of[cls] = cls
    return cls


def add_error_code(error_class: int) -> int:
    """≈ MPI_Add_error_code: allocate a fresh code in ``error_class``
    (predefined or user-added)."""
    code = _user_next[0]
    _user_next[0] += 1
    _user_class_of[code] = int(error_class)
    return code


def add_error_string(code: int, text: str) -> None:
    """≈ MPI_Add_error_string for a user-added class/code."""
    if int(code) not in _user_class_of:
        raise MPIException(
            f"add_error_string: {code} was not user-added", error_class=3)
    _ERROR_STRINGS[int(code)] = str(text)


def error_class(code: int) -> int:
    """≈ MPI_Error_class: the class a (possibly user-added) code maps to;
    predefined codes are their own class here."""
    return _user_class_of.get(int(code), int(code))


def error_string(error_class: int) -> str:
    """≈ MPI_Error_string: human text for an error class (the values
    MPIException.error_class carries)."""
    return _ERROR_STRINGS.get(int(error_class),
                              f"unknown error class {error_class}")


class MPIException(RuntimeError):
    """Raised by MPI-layer operations (≈ error handler MPI_ERRORS_RETURN path)."""

    def __init__(self, msg: str, error_class: int = 13) -> None:
        super().__init__(msg)
        self.error_class = error_class
