"""MPI_T tool interface: control variables (cvars) + performance variables
(pvars).

≈ ompi/mpi/tool + opal/mca/base/mca_base_pvar.c: the cvar side is a
read/write window onto the MCA variable registry (every ``register_var``
call is automatically an MPI_T cvar, exactly as in the reference); the
pvar side is a registry of typed performance variables with session-scoped
handles that can be bound to an object (a communicator, a monitor) the way
MPI_T handles bind to MPI objects.

Pvar classes mirror MPI_T_PVAR_CLASS_*: COUNTER (monotonic), LEVEL
(instantaneous utilization), SIZE, HIGHWATERMARK, LOWWATERMARK, TIMER,
STATE, AGGREGATE.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Callable, Optional

from ompi_tpu.core.config import var_registry
from ompi_tpu.mpi.constants import MPIException

__all__ = [
    "PvarClass", "Pvar", "pvar_registry", "PvarRegistry", "PvarSession",
    "cvar_num", "cvar_names", "cvar_get_info", "cvar_read", "cvar_write",
]


# ---------------------------------------------------------------------------
# cvars — a thin MPI_T window onto the MCA var registry
# ---------------------------------------------------------------------------

def cvar_names() -> list[str]:
    """All control-variable names (≈ MPI_T_cvar_get_num + iteration)."""
    return [v.full_name for v in var_registry.all_vars()]


def cvar_num() -> int:
    return len(cvar_names())


def cvar_get_info(name: str) -> dict[str, Any]:
    """≈ MPI_T_cvar_get_info — type/default/description metadata."""
    var = var_registry.lookup(name)
    if var is None:
        raise MPIException(f"unknown cvar {name}")
    return {
        "name": name,
        "type": var.vtype.value if hasattr(var.vtype, "value")
        else str(var.vtype),
        "default": var.default,
        "description": var.description,
    }


def cvar_read(name: str) -> Any:
    """≈ MPI_T_cvar_read."""
    return var_registry.get(name)


def cvar_write(name: str, value: Any) -> None:
    """≈ MPI_T_cvar_write."""
    var_registry.set(name, value)


# ---------------------------------------------------------------------------
# pvars
# ---------------------------------------------------------------------------

class PvarClass(enum.Enum):
    COUNTER = "counter"            # monotonically increasing
    LEVEL = "level"                # instantaneous value
    SIZE = "size"                  # fixed resource size
    HIGHWATERMARK = "highwatermark"
    LOWWATERMARK = "lowwatermark"
    TIMER = "timer"                # accumulated seconds
    STATE = "state"                # discrete state id
    AGGREGATE = "aggregate"        # arbitrary aggregated value


class Pvar:
    """A performance variable (≈ mca_base_pvar_t).

    Two flavors:
    - *storage-backed*: holds its own value; mutate with inc()/set_value()/
      watermark(); the common case for framework-internal counters.
    - *read-function-backed*: ``read_fn(bound_obj)`` pulls the value from a
      live object at read time (how the monitoring component exports its
      matrices); such pvars usually require a bound object at handle
      allocation, mirroring MPI_T bindings.
    """

    def __init__(self, name: str, klass: PvarClass, unit: str = "",
                 description: str = "",
                 read_fn: Optional[Callable[[Any], Any]] = None,
                 requires_binding: bool = False) -> None:
        self.name = name
        self.klass = klass
        self.unit = unit
        self.description = description
        self.read_fn = read_fn
        self.requires_binding = requires_binding
        self._lock = threading.Lock()
        self._value: Any = 0
        self._wm_sampled = False  # watermark classes: any sample yet?

    # storage-backed mutation

    def inc(self, delta: Any = 1) -> None:
        with self._lock:
            self._value += delta

    def set_value(self, v: Any) -> None:
        with self._lock:
            self._value = v

    def watermark(self, v: Any) -> None:
        with self._lock:
            if self.klass not in (PvarClass.HIGHWATERMARK,
                                  PvarClass.LOWWATERMARK):
                raise MPIException(f"{self.name} is not a watermark pvar")
            if not self._wm_sampled:
                self._value = v
                self._wm_sampled = True
            elif self.klass is PvarClass.HIGHWATERMARK:
                self._value = max(self._value, v)
            else:
                self._value = min(self._value, v)

    def read(self, bound: Any = None) -> Any:
        if self.read_fn is not None:
            if bound is None and self.requires_binding:
                raise MPIException(
                    f"pvar {self.name} requires a bound object")
            return self.read_fn(bound)
        with self._lock:
            return self._value

    def __repr__(self) -> str:
        return f"Pvar({self.name}, {self.klass.value})"


class PvarRegistry:
    """Process-global pvar directory (≈ the mca_base_pvar registry)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._vars: dict[str, Pvar] = {}

    def register(self, pvar: Pvar) -> Pvar:
        with self._lock:
            if pvar.name in self._vars:
                raise MPIException(f"pvar {pvar.name} already registered")
            self._vars[pvar.name] = pvar
        return pvar

    def register_or_get(self, pvar: Pvar) -> Pvar:
        with self._lock:
            return self._vars.setdefault(pvar.name, pvar)

    def lookup(self, name: str) -> Pvar:
        try:
            return self._vars[name]
        except KeyError:
            raise MPIException(f"unknown pvar {name}") from None

    def names(self) -> list[str]:
        return sorted(self._vars)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._vars.pop(name, None)


pvar_registry = PvarRegistry()


class _PvarHandle:
    """A session handle (≈ MPI_T_pvar_handle): start/stop/read/reset with
    a per-handle baseline so concurrent tools don't disturb each other."""

    def __init__(self, pvar: Pvar, bound: Any) -> None:
        self.pvar = pvar
        self.bound = bound
        self._started = False
        # counters read cumulative values until reset() sets a baseline
        # (MPI_T_pvar_reset semantics)
        self._base: Any = 0
        self._t0: Optional[float] = None
        self._acc = 0.0

    def start(self) -> None:
        self._started = True
        if self.pvar.klass is PvarClass.TIMER:
            self._t0 = time.perf_counter()

    def stop(self) -> None:
        if self.pvar.klass is PvarClass.TIMER and self._t0 is not None:
            self._acc += time.perf_counter() - self._t0
            self._t0 = None
        self._started = False

    def read(self) -> Any:
        if self.pvar.klass is PvarClass.TIMER:
            live = (time.perf_counter() - self._t0
                    if self._started and self._t0 is not None else 0.0)
            return self._acc + live
        cur = self.pvar.read(self.bound)
        if self.pvar.klass is PvarClass.COUNTER:
            return cur - self._base
        return cur

    def reset(self) -> None:
        if self.pvar.klass is PvarClass.TIMER:
            self._acc = 0.0
            if self._started:
                self._t0 = time.perf_counter()
        elif self.pvar.klass is PvarClass.COUNTER:
            self._base = self.pvar.read(self.bound)


class PvarSession:
    """≈ MPI_T_pvar_session_create/free."""

    def __init__(self) -> None:
        self._handles: list[_PvarHandle] = []

    def handle_alloc(self, name: str, bound: Any = None) -> _PvarHandle:
        h = _PvarHandle(pvar_registry.lookup(name), bound)
        self._handles.append(h)
        return h

    def handle_free(self, handle: _PvarHandle) -> None:
        self._handles.remove(handle)

    def free(self) -> None:
        self._handles.clear()
