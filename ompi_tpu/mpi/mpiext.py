"""MPIX extensions — the non-standard-but-supported API surface.

≈ ompi/mpiext (the MPIX_ mechanism; its flagship is
``MPIX_Query_cuda_support`` in ompi/mpiext/cuda): a registry of named
extensions a program can probe at runtime instead of guessing from
version strings.  The TPU build's equivalents report on the device data
plane.

    >>> import ompi_tpu.mpi.mpiext as mpix
    >>> mpix.query_tpu_support()        # is the XLA device path usable?
    >>> mpix.extensions()               # {"tpu", "device_heap", ...}
"""

from __future__ import annotations

from typing import Callable

__all__ = ["extensions", "has_extension", "register_extension",
           "query_tpu_support", "query_device_heap_support",
           "query_sequence_parallel_support"]

_registry: dict[str, Callable[[], bool]] = {}


def register_extension(name: str, probe: Callable[[], bool]) -> None:
    """Register an MPIX extension (≈ dropping a dir under ompi/mpiext)."""
    _registry[name] = probe


def extensions() -> set[str]:
    """Names of every registered extension (probed or not)."""
    return set(_registry)


def has_extension(name: str) -> bool:
    """Probe one extension; unknown names are False, probes never raise."""
    probe = _registry.get(name)
    if probe is None:
        return False
    try:
        return bool(probe())
    except Exception:  # noqa: BLE001 — a probe failure means "not usable"
        return False


def query_tpu_support() -> bool:
    """≈ MPIX_Query_cuda_support, inverted to this build's accelerator:
    True when jax sees at least one non-CPU device (the coll/xla data
    plane has somewhere to run)."""
    return has_extension("tpu")


def query_device_heap_support() -> bool:
    """True when the OSHMEM device symmetric heap (shmem/device.py) can
    host identically-sharded arrays — i.e. a live device mesh exists."""
    return has_extension("device_heap")


def query_sequence_parallel_support() -> bool:
    """True when ring/Ulysses sequence-parallel attention is importable
    (pallas flash kernel or jnp fallback)."""
    return has_extension("sequence_parallel")


def _probe_tpu() -> bool:
    import jax

    return any(d.platform != "cpu" for d in jax.devices())


def _probe_device_heap() -> bool:
    # contract: "a live device mesh exists" — the heap needs actual
    # devices to shard over (CPU meshes included), not mere importability
    import jax

    from ompi_tpu.shmem import device as _dev  # noqa: F401

    return len(jax.devices()) >= 1


def _probe_seq_parallel() -> bool:
    from ompi_tpu.parallel import attention as _attn  # noqa: F401

    return True


register_extension("tpu", _probe_tpu)
register_extension("device_heap", _probe_device_heap)
register_extension("sequence_parallel", _probe_seq_parallel)
