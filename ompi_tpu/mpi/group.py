"""Groups: ordered sets of ranks with MPI set operations.

≈ ompi/group: a Group is an ordered list of world ranks; communicators are a
group + a context id.  Set ops (union/intersection/difference), incl/excl,
and rank translation follow MPI semantics (order preserved from the first
group, UNDEFINED for absent ranks).
"""

from __future__ import annotations

from typing import Sequence

from ompi_tpu.mpi.constants import UNDEFINED, MPIException

__all__ = ["Group"]


class Group:
    """An ordered set of global (world) ranks."""

    def __init__(self, world_ranks: Sequence[int]) -> None:
        self._ranks = tuple(int(r) for r in world_ranks)
        if len(set(self._ranks)) != len(self._ranks):
            raise MPIException(f"group has duplicate ranks: {self._ranks}")

    @property
    def size(self) -> int:
        return len(self._ranks)

    @property
    def ranks(self) -> tuple[int, ...]:
        return self._ranks

    def rank_of(self, world_rank: int) -> int:
        """This group's rank for a world rank (UNDEFINED if absent)."""
        try:
            return self._ranks.index(world_rank)
        except ValueError:
            return UNDEFINED

    def world_rank(self, group_rank: int) -> int:
        return self._ranks[group_rank]

    # -- set operations (≈ MPI_Group_union/intersection/difference) -------

    def union(self, other: "Group") -> "Group":
        seen = set(self._ranks)
        return Group(self._ranks +
                     tuple(r for r in other._ranks if r not in seen))

    def intersection(self, other: "Group") -> "Group":
        o = set(other._ranks)
        return Group(tuple(r for r in self._ranks if r in o))

    def difference(self, other: "Group") -> "Group":
        o = set(other._ranks)
        return Group(tuple(r for r in self._ranks if r not in o))

    def incl(self, ranks: Sequence[int]) -> "Group":
        """Subset by *group* ranks, in the given order (≈ MPI_Group_incl)."""
        return Group(tuple(self._ranks[r] for r in ranks))

    def excl(self, ranks: Sequence[int]) -> "Group":
        drop = set(ranks)
        bad = [r for r in drop if not 0 <= r < self.size]
        if bad:
            raise MPIException(f"excl: invalid group ranks {bad}")
        return Group(tuple(r for i, r in enumerate(self._ranks)
                           if i not in drop))

    def _expand_ranges(self, ranges: Sequence[Sequence[int]]) -> list[int]:
        out: list[int] = []
        for first, last, stride in ranges:
            if stride == 0:
                raise MPIException("range stride may not be 0")
            stop = last + (1 if stride > 0 else -1)
            for r in range(first, stop, stride):
                if not 0 <= r < self.size:
                    raise MPIException(
                        f"range rank {r} outside group of {self.size}")
                out.append(r)
        return out

    def range_incl(self, ranges: Sequence[Sequence[int]]) -> "Group":
        """≈ MPI_Group_range_incl: ranges are (first, last, stride)
        triples, expanded inclusively in order."""
        return self.incl(self._expand_ranges(ranges))

    def range_excl(self, ranges: Sequence[Sequence[int]]) -> "Group":
        """≈ MPI_Group_range_excl."""
        return self.excl(self._expand_ranges(ranges))

    def translate_ranks(self, ranks: Sequence[int],
                        other: "Group") -> list[int]:
        """≈ MPI_Group_translate_ranks: my group ranks → other's group ranks."""
        return [other.rank_of(self._ranks[r]) for r in ranks]

    def compare(self, other: "Group") -> str:
        """≈ MPI_Group_compare: 'ident' | 'similar' | 'unequal'."""
        if self._ranks == other._ranks:
            return "ident"
        if set(self._ranks) == set(other._ranks):
            return "similar"
        return "unequal"

    def __eq__(self, other) -> bool:
        return isinstance(other, Group) and self._ranks == other._ranks

    def __hash__(self) -> int:
        return hash(self._ranks)

    def __repr__(self) -> str:
        return f"Group({list(self._ranks)})"
