"""MPI-IO: file handles, views, individual/collective/shared access.

≈ the reference's ``io`` framework — the native OMPIO implementation
(ompi/mca/io/ompio + ompi/mca/common/ompio's file-view and read/write
engine) with its sub-frameworks collapsed into one module:

- fs (open/close/delete; fs/ufs)            → :meth:`File.open` etc.
- fbtl (posix data movement)                → pread/pwrite on the fd
- fcoll (collective two-phase;
  fcoll/two_phase + dynamic)                → :meth:`File.write_at_all`
- sharedfp (shared file pointer;
  sharedfp/lockedfile + sm)                 → :meth:`File.write_shared`

File *views* (MPI_File_set_view: displacement + etype + filetype) reuse the
datatype engine: a filetype's compiled byte segments tile the file, and the
view maps a contiguous etype stream onto the holes — the same descriptor
walk the reference's common_ompio file-view engine does, vectorized over
runs instead of a per-byte loop.

Device arrays are accepted everywhere and staged through host memory
(``np.asarray``); sharded-array checkpoint IO has its own orbax-style fast
path in ompi_tpu.ckpt, which is the TPU-native answer to parallel IO of
array data.

Two-phase collective IO: every rank is an aggregator for an equal
contiguous file domain (the reference's default: one aggregator per node,
cb_buffer_size domains).  Requests are exchanged with alltoallv, aggregated
into large contiguous pread/pwrite calls, and routed back — turning N
small strided accesses into a few big sequential ones.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional, Sequence

import numpy as np

from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.mpi import datatype as dt_mod
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.constants import ERR_IO, MPIException
from ompi_tpu.mpi.datatype import Datatype
from ompi_tpu.mpi.request import CompletedRequest, Request

__all__ = [
    "File", "FileView",
    "MODE_RDONLY", "MODE_WRONLY", "MODE_RDWR", "MODE_CREATE", "MODE_EXCL",
    "MODE_APPEND", "MODE_DELETE_ON_CLOSE", "SEEK_SET", "SEEK_CUR", "SEEK_END",
]

# amode flags (values mirror MPI's spirit, not its ABI)
MODE_RDONLY = 0x01
MODE_WRONLY = 0x02
MODE_RDWR = 0x04
MODE_CREATE = 0x08
MODE_EXCL = 0x10
MODE_APPEND = 0x20
MODE_DELETE_ON_CLOSE = 0x40

SEEK_SET = 0
SEEK_CUR = 1
SEEK_END = 2

register_var("io", "twophase", VarType.BOOL, True,
             "use two-phase aggregation for collective IO "
             "(False: collective calls run as independent IO + barrier)")
register_var("io", "twophase_min_bytes", VarType.SIZE, 1,
             "minimum total bytes before two-phase aggregation kicks in")
register_var("io", "fcoll", VarType.STRING, "",
             "force a collective-IO component: individual | two_phase | "
             "dynamic | static | dynamic_gen2 (empty = auto-decide from "
             "the access pattern, like the reference's fcoll "
             "query/priority selection)")
register_var("io", "stripe_bytes", VarType.SIZE, 1 << 20,
             "file stripe width for the static (cyclic stripe->aggregator "
             "round-robin) and dynamic_gen2 (stripe-aligned payload "
             "domains) fcoll components; match the filesystem stripe for "
             "lock-contention-free aggregator writes")
register_var("io", "cb_aggregators_per_host", VarType.INT, 1,
             "collective-buffering aggregators per host (aggregators are "
             "the lowest ranks of each host in the job mapping, like "
             "OMPIO's one-per-node cb_nodes default)")
register_var("io", "fs_adaptive", VarType.BOOL, True,
             "adapt collective-IO defaults to the filesystem backing the "
             "file (the fs framework's job, ompi/mca/fs: fs/lustre tunes "
             "stripe-aware defaults; here: memory-backed fs prefer "
             "individual IO, network fs aggregate aggressively)")

# memory-backed: aggregation only adds exchange hops (no seek to amortize)
_FS_MEMORY = {"tmpfs", "ramfs", "devtmpfs"}
# network: per-client streams are expensive — aggregate aggressively
_FS_NETWORK = {"nfs", "nfs4", "lustre", "gpfs", "cifs", "smb2", "9p",
               "fuse.sshfs", "glusterfs", "beegfs"}


def _fs_type(path: str) -> str:
    """Filesystem type backing ``path`` (longest mount-prefix match in
    /proc/mounts; '' when undeterminable).  ≈ the detection the fs
    framework components do with statfs magic (fs_lustre.c checks the
    LL_SUPER_MAGIC the same way)."""
    try:
        real = os.path.realpath(path)
        best, best_type = "", ""
        with open("/proc/mounts", encoding="utf-8") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                mnt, typ = parts[1], parts[2]
                if real.startswith(mnt.rstrip("/") + "/") or real == mnt \
                        or mnt == "/":
                    if len(mnt) > len(best):
                        best, best_type = mnt, typ
        return best_type
    except OSError:
        return ""

# shared-file-pointer serialization for in-process ranks (threads share the
# process, so fcntl locks alone can't order them); keyed by realpath
_shfp_locks: dict[str, threading.Lock] = {}
_shfp_registry_lock = threading.Lock()


# -- data representations (≈ MPI_Register_datarep, io_ompio datarep) -------
#
# name → (read_conv, write_conv); each is f(raw_bytes, etype) -> bytes or
# None for identity.  Conversions must preserve byte count (the file-view
# byte-run arithmetic assumes it) — MPI's variable-size datareps are out of
# scope on this substrate and register_datarep enforces same-size by
# checking a probe conversion.

def _ext32_swap(raw: bytes, etype) -> bytes:
    import sys as _sys

    if _sys.byteorder == "big" or etype.size <= 1:
        return raw
    n = len(raw) // etype.size
    tail = raw[n * etype.size:]
    return dt_mod._swap_stream(etype, raw[:n * etype.size], n) + tail


_datareps: dict[str, tuple] = {
    "native": (None, None),
    "internal": (None, None),
    "external32": (_ext32_swap, _ext32_swap),
}


def register_datarep(name: str, read_conv=None, write_conv=None) -> None:
    """≈ MPI_Register_datarep: a user data representation usable in
    set_view.  ``read_conv(raw, etype) -> bytes`` converts file→native,
    ``write_conv`` native→file; byte count must be preserved."""
    if name in _datareps:
        raise MPIException(f"datarep {name!r} already registered",
                           error_class=ERR_IO)
    probe = bytes(8)
    for fn in (read_conv, write_conv):
        if fn is not None and len(fn(probe, dt_mod.BYTE)) != len(probe):
            raise MPIException(
                f"datarep {name!r}: conversion changed byte count "
                f"(unsupported here)", error_class=ERR_IO)
    _datareps[name] = (read_conv, write_conv)


def _shfp_lock(path: str) -> threading.Lock:
    with _shfp_registry_lock:
        return _shfp_locks.setdefault(path, threading.Lock())


import itertools as _it  # noqa: E402

_shfp_nonce = _it.count(1)   # per-process component of the sm open nonce


# -- sharedfp strategies (≈ ompi/mca/sharedfp components) -----------------

register_var("io", "sharedfp", VarType.STRING, "",
             "shared-file-pointer component: lockedfile | sm | individual "
             "(empty = auto: sm when every rank shares the host and the "
             "native atomics built, else lockedfile — the reference's "
             "sharedfp component split; individual is opt-in only, it "
             "relaxes the shared-pointer semantics)")


class _LockedFileSharedFp:
    """sharedfp/lockedfile: an 8-byte sidecar file guarded by a fcntl
    range lock (+ a thread lock for in-process ranks) — works on any
    shared filesystem, multi-host included."""

    name = "lockedfile"

    def __init__(self, path: str) -> None:
        self.path = path + ".ompi_tpu_shfp"

    def create(self, initial: int) -> None:
        self.store(initial)

    def attach(self) -> None:
        pass                     # the filesystem is the rendezvous

    def load(self) -> int:
        with open(self.path, "rb") as f:
            return int.from_bytes(f.read(8), "big")

    def store(self, val: int) -> None:
        with open(self.path, "wb") as f:
            f.write(int(val).to_bytes(8, "big"))

    def fetch_add(self, n: int) -> int:
        import fcntl

        with _shfp_lock(self.path):
            with open(self.path, "r+b") as f:
                fcntl.lockf(f, fcntl.LOCK_EX)
                try:
                    cur = int.from_bytes(f.read(8), "big")
                    f.seek(0)
                    f.write((cur + n).to_bytes(8, "big"))
                    f.flush()
                finally:
                    fcntl.lockf(f, fcntl.LOCK_UN)
        return cur

    def close(self, root: bool) -> None:
        if root:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class _SmSharedFp:
    """sharedfp/sm: the pointer is an 8-byte counter in a shared-memory
    segment, advanced with native u64 atomics (fastdss.atomic_add) —
    lock-free fetch-add for same-host jobs, the reference's
    sharedfp/sm strategy."""

    name = "sm"

    def __init__(self, path: str) -> None:
        import zlib

        self._base = f"otpu-shfp-{os.getuid()}-{zlib.crc32(path.encode()):08x}"
        self._name = self._base
        self._seg = None
        self._fast = None

    def set_nonce(self, nonce: int) -> None:
        """Per-OPEN disambiguation (agreed collectively): MPI shared
        pointers belong to the open, so two concurrent opens of the same
        path must not share — or unlink — each other's counter."""
        self._name = f"{self._base}-{nonce:x}"

    @staticmethod
    def usable() -> bool:
        from ompi_tpu import _native

        return (os.path.isdir("/dev/shm")
                and _native.fastdss() is not None)

    def _path(self) -> str:
        return os.path.join("/dev/shm", self._name)

    def create(self, initial: int) -> None:
        from ompi_tpu import _native
        from ompi_tpu.core import shmseg

        self._fast = _native.fastdss()
        # nonce names never collide with a crashed job's, so stale
        # segments need active GC: sweep siblings of this path older
        # than 10 min (their jobs are gone; live opens are short-lived)
        import glob

        for old in glob.glob(os.path.join("/dev/shm",
                                          self._base + "-*")):
            try:
                if time.time() - os.path.getmtime(old) > 600:
                    os.unlink(old)
            except OSError:
                pass
        # initialize BEFORE publishing: an attacher must never observe
        # the counter without its initial value
        self._seg = shmseg.create(self._name, 8, dir="/dev/shm",
                                  publish=False)
        self._fast.atomic_store(self._seg.buf, 0, int(initial))
        self._seg.publish()

    def attach(self) -> None:
        # no retry needed: the create outcome was broadcast before any
        # attacher runs, so the published segment already exists — and
        # retrying would stretch permanent errors (EACCES, corrupt
        # segment) into long stalls
        from ompi_tpu import _native
        from ompi_tpu.core import shmseg

        self._fast = _native.fastdss()
        self._seg = shmseg.attach(self._path())

    def load(self) -> int:
        return int(self._fast.atomic_load(self._seg.buf, 0))

    def store(self, val: int) -> None:
        self._fast.atomic_store(self._seg.buf, 0, int(val))

    def fetch_add(self, n: int) -> int:
        return int(self._fast.atomic_add(self._seg.buf, 0, int(n)))

    def close(self, root: bool) -> None:
        """EVERY rank detaches its mapping (a rank-0-only teardown would
        leak one live tmpfs mapping per open on every other rank); the
        root also unlinks the segment name."""
        if root:
            try:
                os.unlink(self._path())
            except OSError:
                pass
        if self._seg is not None:
            try:
                self._seg.detach()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            self._seg = None


class _IndividualSharedFp:
    """sharedfp/individual: the reference's third strategy
    (ompi/mca/sharedfp/individual) — RELAXED shared-pointer semantics.
    Each rank spools its ``write_shared`` payloads to a local temp file
    with a timestamp per record; the global interleaving is reconstructed
    collectively at sync/close (and before any ordered op) by merging
    every rank's records in timestamp order.  Zero inter-process
    coordination per write — the fastest strategy when the program only
    ever *writes* through the shared pointer and can live with the order
    materializing at sync points.  ``read_shared``/``seek_shared`` are
    erroneous, exactly as in the reference (it implements only the write
    side).  Opt-in only (``--mca io sharedfp individual``): auto-selection
    must never silently weaken MPI semantics."""

    name = "individual"
    local_log = True      # File routes write_shared through log_write()

    def __init__(self, path: str) -> None:
        self.path = path
        self._spool = None              # local payload spool (tempfile)
        self._recs: list[tuple[int, int]] = []   # (t_ns, nbytes)
        self.merged_end = 0             # etype units; agreed at each merge
        # record append + spool write must be ONE step: under
        # THREAD_MULTIPLE two interleaved write_shared calls would
        # otherwise desync _recs order from spool byte order and the
        # merge would write the wrong bytes at each record's offset
        self._lock = threading.Lock()

    def create(self, initial: int) -> None:
        """LOCAL setup — every rank runs this (there is no shared state
        to rendezvous on; that is the point of the strategy)."""
        import tempfile

        self._spool = tempfile.TemporaryFile(prefix="otpu-shfp-ind-")
        self.merged_end = int(initial)

    def attach(self) -> None:
        pass   # nothing shared to attach to

    def log_write(self, raw: bytes) -> None:
        with self._lock:
            self._recs.append((time.time_ns(), len(raw)))
            self._spool.write(raw)

    def _unsupported(self) -> MPIException:
        return MPIException(
            "sharedfp/individual supports only write_shared and the "
            "ordered collectives; shared-pointer reads/seeks need the "
            "sm or lockedfile component", error_class=ERR_IO)

    def load(self) -> int:
        raise self._unsupported()

    def store(self, val: int) -> None:
        raise self._unsupported()

    def fetch_add(self, n: int) -> int:
        raise self._unsupported()

    def close(self, root: bool) -> None:
        if self._spool is not None:
            try:
                self._spool.close()
            except OSError:
                pass
            self._spool = None


class FileView:
    """displacement + etype + filetype (MPI_File_set_view).

    The filetype tiles the file starting at ``disp``; its payload byte runs
    (``segments()``) are the accessible holes.  Positions/counts are in
    etype units, as the MPI spec requires.
    """

    def __init__(self, disp: int = 0,
                 etype: Datatype = dt_mod.BYTE,
                 filetype: Optional[Datatype] = None) -> None:
        if filetype is None:
            filetype = etype
        if filetype.size % etype.size:
            raise MPIException(
                f"filetype size {filetype.size} not a multiple of etype "
                f"size {etype.size}", error_class=3)
        self.disp = int(disp)
        self.etype = etype
        self.filetype = filetype
        # payload runs per tile, array-native (a million-run filetype
        # must not materialize a tuple list here)
        self._run_starts, self._run_lens = filetype.segment_arrays()
        self._n_runs = len(self._run_starts)
        self._tile_bytes = filetype.size     # payload bytes per tile
        self._tile_extent = filetype.extent  # file bytes spanned per tile
        # prefix sums of run lengths for payload→file mapping
        self._run_cum = np.concatenate(
            [[0], np.cumsum(self._run_lens)]).astype(np.int64)

    @property
    def contiguous(self) -> bool:
        return (self._n_runs == 1 and int(self._run_starts[0]) == 0
                and self._tile_bytes == self._tile_extent)

    def payload_bytes_up_to(self, file_size: int) -> int:
        """How many payload bytes the view exposes below `file_size` — the
        inverse mapping needed by SEEK_END."""
        avail = file_size - self.disp
        if avail <= 0:
            return 0
        if self.contiguous:
            return avail
        tiles, within = divmod(avail, self._tile_extent)
        pay = tiles * self._tile_bytes
        # PREFIX of the declaration-ordered runs below `within` (a
        # non-monotone filetype's later runs may sit below it in the
        # file but are NOT readable payload prefix — the original
        # walk-with-break semantics)
        below = self._run_starts < within
        k = (len(below) if bool(below.all())
             else int(np.argmin(below)))
        pay += int(np.minimum(
            self._run_lens[:k],
            within - self._run_starts[:k]).sum())
        return pay

    def byte_runs(self, offset_etypes: int, nbytes: int
                  ) -> list[tuple[int, int]]:
        """File (offset, length) runs covering `nbytes` of payload starting
        at view position `offset_etypes` — the descriptor walk.

        Vectorized over the view's tile periodicity: the runs of every
        FULL tile are the filetype's segments shifted by tile·extent, so
        they expand with one broadcast instead of a python loop per run
        (a 20k-run strided view costs ~100 numpy calls, not ~80k)."""
        start = offset_etypes * self.etype.size
        if nbytes <= 0:
            return []
        if self.contiguous:
            return [(self.disp + start, nbytes)]
        end = start + nbytes
        tile0, w0 = divmod(start, self._tile_bytes)
        tile1, w1 = divmod(end, self._tile_bytes)   # w1 bytes into tile1

        def tile_slice(tile: int, lo: int, hi: int) -> tuple:
            """(starts, lens) of payload bytes [lo, hi) within one tile."""
            i0 = int(np.searchsorted(self._run_cum, lo, "right")) - 1
            i1 = int(np.searchsorted(self._run_cum, hi, "left"))
            s = self._run_starts[i0:i1].copy()
            ln = self._run_lens[i0:i1].copy()
            if len(s):
                head = lo - int(self._run_cum[i0])
                s[0] += head
                ln[0] -= head
                tail = int(self._run_cum[i1]) - hi
                ln[-1] -= tail
            base = self.disp + tile * self._tile_extent
            return base + s, ln

        parts = []
        if tile0 == tile1:
            parts.append(tile_slice(tile0, w0, w1))
        else:
            if w0:
                parts.append(tile_slice(tile0, w0, self._tile_bytes))
                first_full = tile0 + 1
            else:
                first_full = tile0
            if first_full < tile1:      # the full middle tiles, broadcast
                tiles = np.arange(first_full, tile1, dtype=np.int64)
                base = (self.disp + tiles[:, None] * self._tile_extent
                        + self._run_starts[None, :])
                lens = np.broadcast_to(self._run_lens[None, :], base.shape)
                parts.append((base.reshape(-1), lens.reshape(-1)))
            if w1:
                parts.append(tile_slice(tile1, 0, w1))
        starts = np.concatenate([p[0] for p in parts])
        lens = np.concatenate([p[1] for p in parts])
        keep = lens > 0
        starts, lens = starts[keep], lens[keep]
        if len(starts) == 0:
            return []
        # adjacency merge (runs touching across tile seams), vectorized:
        # a new group starts wherever the previous run doesn't reach us
        brk = np.empty(len(starts), bool)
        brk[0] = True
        np.not_equal(starts[1:], starts[:-1] + lens[:-1], out=brk[1:])
        g = np.flatnonzero(brk)
        gstarts = starts[g]
        glens = np.add.reduceat(lens, g)
        return list(zip(gstarts.tolist(), glens.tolist()))


def _coalesce(runs: list[tuple[int, int, bytes]]
              ) -> list[tuple[int, bytes]]:
    """Merge byte runs into maximal contiguous writes (stable sort keeps
    rank order on equal offsets; overlapping writes without atomicity are
    erroneous in MPI, so adjacency is the only case that matters)."""
    runs = sorted(runs, key=lambda r: r[0])
    out: list[tuple[int, bytearray]] = []
    for off, ln, data in runs:
        if out and out[-1][0] + len(out[-1][1]) == off:
            out[-1][1].extend(data[:ln])
        else:
            out.append((off, bytearray(data[:ln])))
    return [(o, bytes(b)) for o, b in out]


class File:
    """An open MPI file handle (≈ ompi_file_t + the ompio module state)."""

    def __init__(self, comm, path: str, amode: int) -> None:
        # private communicator for all file-internal traffic (ROMIO dups
        # for the same reason): the nonblocking-collective worker thread
        # runs collectives concurrently with the caller's thread, and on
        # the user's comm those could cross-match the user's same-tag
        # collectives.  Collective, so it must be the first comm op here.
        self.comm = comm.dup(name=f"{getattr(comm, 'name', 'comm')}.io")
        if hasattr(comm, "_io_host_override"):  # test/placement hook
            self.comm._io_host_override = comm._io_host_override
        self.path = os.path.abspath(path)
        self.amode = amode
        self.view = FileView()
        self._pos = 0                    # individual pointer, etype units
        self._atomicity = False
        self._closed = False
        self._fd: Optional[int] = None
        from ompi_tpu.mpi.errhandler import ERRORS_RETURN
        from ompi_tpu.mpi.info import Info

        self.errhandler = ERRORS_RETURN  # note: MPI's File default IS
        # ERRORS_RETURN (unlike comms) — here they agree
        self.info = Info()
        self._io_lock = threading.Lock()
        # fs framework: the filesystem kind steers collective-IO defaults
        self.fs_type = _fs_type(os.path.dirname(self.path) or ".")
        flags = os.O_RDWR if amode & (MODE_RDWR | MODE_WRONLY) else os.O_RDONLY
        # MPI_MODE_WRONLY still needs reads for read-modify on views; POSIX
        # O_WRONLY would break pread — open RDWR and gate in software
        if amode & MODE_CREATE:
            flags |= os.O_CREAT
        err = ""
        if amode & MODE_EXCL:
            # EXCL is a *collective* exists-check: rank 0 does the
            # exclusive create and broadcasts the outcome (a plain barrier
            # would hang the others if rank 0's open fails), then the rest
            # open the now-existing file
            if self.comm.rank == 0:
                try:
                    self._fd = os.open(self.path, flags | os.O_EXCL, 0o644)
                except OSError as e:
                    err = str(e)
            ok = self.comm.bcast(np.array([0 if err else 1], np.int8), root=0)
            if not int(np.asarray(ok)[0]):
                self.comm.free()   # uniform raise — don't leak the dup
                raise MPIException(
                    f"MPI_File_open({path}): "
                    f"{err or 'exclusive create failed on rank 0'}",
                    error_class=ERR_IO)
            if self.comm.rank != 0:
                try:
                    self._fd = os.open(self.path, flags & ~os.O_CREAT)
                except OSError as e:
                    err = str(e)
        else:
            try:
                self._fd = os.open(self.path, flags, 0o644)
            except OSError as e:
                err = str(e)
        # collective outcome check: a per-rank open failure (perms / path
        # visible on only some ranks / EXCL non-root open racing a delete)
        # must raise on EVERY rank — otherwise the survivors proceed to the
        # barrier below and the job hangs
        nfail = int(np.asarray(self.comm.allreduce(
            np.array([0 if not err else 1], np.int32)))[0])
        if nfail:
            if self._fd is not None and not err:
                os.close(self._fd)
                self._fd = None
            self.comm.free()       # uniform raise — don't leak the dup
            raise MPIException(
                f"MPI_File_open({path}): failed on {nfail} rank(s)"
                + (f": {err}" if err else ""), error_class=ERR_IO)
        if amode & MODE_APPEND:
            self._pos = os.fstat(self._fd).st_size // self.view.etype.size
        # shared file pointer: pick a sharedfp component collectively,
        # rank 0 creates/resets it (to EOF under APPEND — MPI requires
        # *all* pointers to start at end of file), everyone attaches.
        # A read-only mount (archived snapshot dir) cannot host the
        # lockedfile sidecar — record the failure and raise only if
        # shared-pointer ops are actually used, so plain reads of
        # immutable files work.
        self._shfp_err = ""
        try:
            self._shfp = self._select_sharedfp()
        except MPIException:
            os.close(self._fd)   # the raise is uniform across ranks
            self._fd = None      # (collectively agreed) — don't leak fd
            self.comm.free()     # ... or the comm dup'd above
            raise
        initial = int(self._pos if amode & MODE_APPEND else 0)
        if getattr(self._shfp, "local_log", False):
            # sharedfp/individual: per-rank local spool, nothing shared —
            # every rank creates its own (initial is identical: same
            # fstat of the same file); agreement happens below
            try:
                self._shfp.create(initial)
            except OSError as e:
                self._shfp_err = str(e)
        else:
            if self._shfp.name == "sm":
                # per-open nonce, rank 0's choice broadcast: concurrent
                # opens of one path must not collide on the segment name
                nonce = int(np.asarray(self.comm.bcast(np.array(
                    [os.getpid() << 16 | (next(_shfp_nonce) & 0xFFFF)],
                    np.int64), root=0))[0])
                self._shfp.set_nonce(nonce)
            if self.comm.rank == 0:
                try:
                    self._shfp.create(initial)
                except OSError as e:
                    self._shfp_err = str(e)
            # every rank must agree whether the pointer exists (shared ops
            # are collective-adjacent): broadcast the create outcome,
            # attach, then agree on the attach outcomes too — a single
            # rank with a broken pointer would otherwise raise
            # mid-collective while its peers block in the matching barrier
            flag = self.comm.bcast(np.array(
                [1 if not self._shfp_err else 0], np.int8), root=0)
            if not int(np.asarray(flag)[0]):
                if self.comm.rank != 0:
                    self._shfp_err = \
                        "shared-pointer creation failed on rank 0"
            elif self.comm.rank != 0:
                try:
                    self._shfp.attach()
                except OSError as e:
                    self._shfp_err = str(e)
        from ompi_tpu.mpi import op as op_mod

        ok_everywhere = int(np.asarray(self.comm.allreduce(np.array(
            [0 if self._shfp_err else 1], np.int32),
            op=op_mod.MIN))[0])
        if not ok_everywhere and not self._shfp_err:
            self._shfp_err = "shared-pointer setup failed on a peer rank"
        self.comm.barrier()

    def _select_sharedfp(self):
        """Component choice, identical on every rank: forced var > auto
        (sm when every rank shares the host and the native atomics
        built — the sm/lockedfile split of ompi/mca/sharedfp).  The
        usable/host check is COLLECTIVE even when forced: a partially
        usable sm must fail uniformly, not strand peers in the open's
        bcast."""
        forced = var_registry.get("io_sharedfp") or ""
        if forced and forced not in ("sm", "lockedfile", "individual"):
            raise MPIException(
                f"unknown sharedfp component {forced!r} "
                f"(lockedfile/sm/individual)", error_class=3)
        keys = np.asarray(self.comm.allgather(np.array(
            [self._my_host_key(), 1 if _SmSharedFp.usable() else 0],
            np.int64))).reshape(-1, 2)
        sm_ok = (len(set(int(k) for k in keys[:, 0])) == 1
                 and int(keys[:, 1].min()) == 1)
        if forced == "individual":
            return _IndividualSharedFp(self.path)
        if forced == "sm":
            if not sm_ok:
                raise MPIException(
                    "io_sharedfp=sm forced but unusable (ranks span "
                    "hosts, or the native atomics did not build on "
                    "every rank)", error_class=3)
            return _SmSharedFp(self.path)
        if forced == "lockedfile":
            return _LockedFileSharedFp(self.path)
        return _SmSharedFp(self.path) if sm_ok \
            else _LockedFileSharedFp(self.path)

    # -- fs framework ------------------------------------------------------

    @classmethod
    def open(cls, comm, path: str, amode: int = MODE_RDONLY,
             info=None) -> "File":
        """≈ MPI_File_open — collective over comm.  Consulted ``info``
        hints: ``collective_buffering`` / ``romio_cb_write`` ("false"
        disables collective aggregation), ``cb_nodes`` (caps the
        aggregator count), ``fcoll`` (pins the collective component for
        this file).  Other hints are retrievable (MPI_File_get_info) but
        inert; global knobs live in the MCA registry (io_*)."""
        if amode & MODE_RDONLY and amode & (MODE_WRONLY | MODE_RDWR):
            raise MPIException("RDONLY combined with write mode",
                               error_class=3)
        f = cls(comm, path, amode)
        if info is not None:
            f.info = info
        return f

    def get_info(self):
        """≈ MPI_File_get_info."""
        return self.info

    def set_errhandler(self, eh) -> None:
        """≈ MPI_File_set_errhandler."""
        self.errhandler = eh

    def get_errhandler(self):
        return self.errhandler

    def close(self) -> None:
        """≈ MPI_File_close — collective."""
        if self._closed:
            return
        q = getattr(self, "_io_queue", None)
        if q is not None:      # drain + stop the nonblocking-IO worker
            q.put(None)
            self._io_thread.join(timeout=60.0)
            if self._io_thread.is_alive():
                # a queued collective IO op is stuck (e.g. a peer died
                # mid-collective).  Closing the fd now would hand the
                # worker a recycled descriptor — leak it instead and
                # surface the hang.
                self._closed = True
                raise MPIException(
                    f"MPI_File_close({self.path}): nonblocking-IO worker "
                    "still running after 60s — outstanding collective op "
                    "never completed (fd leaked, not closed)",
                    error_class=ERR_IO)
            self._io_queue = None
        self.sync()
        self.comm.barrier()
        os.close(self._fd)
        self._closed = True
        self._shfp.close(root=self.comm.rank == 0)
        if self.comm.rank == 0:
            if self.amode & MODE_DELETE_ON_CLOSE:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
        self.comm.barrier()
        self.comm.free()       # the private dup taken at open

    @staticmethod
    def delete(path: str) -> None:
        """≈ MPI_File_delete — local."""
        try:
            os.unlink(path)
        except OSError as e:
            raise MPIException(f"MPI_File_delete({path}): {e}",
                               error_class=ERR_IO) from None

    def set_size(self, size: int) -> None:
        """≈ MPI_File_set_size — collective."""
        self._check_open()
        if self.comm.rank == 0:
            os.ftruncate(self._fd, size)
        self.comm.barrier()

    def preallocate(self, size: int) -> None:
        """≈ MPI_File_preallocate — collective (grow-only truncate)."""
        self._check_open()
        if self.comm.rank == 0 and os.fstat(self._fd).st_size < size:
            os.ftruncate(self._fd, size)
        self.comm.barrier()

    def get_size(self) -> int:
        self._check_open()
        return os.fstat(self._fd).st_size

    def sync(self) -> None:
        """≈ MPI_File_sync.  With sharedfp/individual this is where the
        spooled shared-pointer writes land (collective merge) — callers
        of the individual component must treat sync as collective, which
        MPI requires of MPI_File_sync anyway."""
        self._check_open()
        self._shfp_merge()
        os.fsync(self._fd)

    def set_atomicity(self, flag: bool) -> None:
        self._atomicity = bool(flag)

    def get_atomicity(self) -> bool:
        return self._atomicity

    # -- view --------------------------------------------------------------

    def set_view(self, disp: int = 0, etype: Datatype = dt_mod.BYTE,
                 filetype: Optional[Datatype] = None,
                 datarep: str = "native") -> None:
        """≈ MPI_File_set_view — collective; resets both file pointers.
        ``datarep`` selects the file data representation: "native",
        "internal", "external32" (canonical big-endian), or a name
        registered with :func:`register_datarep`."""
        self._check_open()
        if datarep not in _datareps:
            self._err(MPIException(
                f"unknown datarep {datarep!r} (register_datarep first)",
                error_class=ERR_IO))
        self._shfp_merge()       # pending individual writes use the OLD view
        self.view = FileView(disp, etype, filetype)
        self._datarep = datarep
        self._pos = 0
        if getattr(self._shfp, "local_log", False):
            self._shfp.merged_end = 0
        elif not self._shfp_err:  # pointer unavailable (read-only mount):
            self._shfp_store(0)   # the reset is moot — only shared ops
        self.comm.barrier()       # would need it, and they raise anyway

    def get_view(self) -> tuple[int, Datatype, Datatype]:
        return self.view.disp, self.view.etype, self.view.filetype

    # -- individual IO (fbtl/posix equivalent) -----------------------------

    def _err(self, exc: MPIException) -> None:
        """Route through the file's errhandler (≈ invoking the handler
        installed by MPI_File_set_errhandler; raises unless swallowed)."""
        self.errhandler.invoke(self, exc)
        raise exc  # a swallowed file error still cannot proceed: the
        # access-mode/closed-fd condition persists

    def _check_open(self) -> None:
        if self._closed:
            self._err(MPIException("file is closed", error_class=ERR_IO))

    def _check_read(self) -> None:
        self._check_open()
        if self.amode & MODE_WRONLY:
            self._err(MPIException("file opened write-only",
                                   error_class=ERR_IO))

    def _check_write(self) -> None:
        self._check_open()
        if not self.amode & (MODE_WRONLY | MODE_RDWR):
            self._err(MPIException("file opened read-only",
                                   error_class=ERR_IO))

    def _as_bytes(self, data: Any):
        """User data → the byte stream the view consumes.  Returns a
        bytes-like object: a zero-copy memoryview of the caller's array
        when no conversion is needed (right dtype, C-contiguous, identity
        datarep — the plan-collapsed case), else materialized bytes.
        Callers only slice and hand it to pwrite/alltoallv within the
        call, so the view never outlives the caller's buffer."""
        arr = np.asarray(data)
        want = self.view.etype.base_np
        if arr.dtype != want:
            arr = arr.astype(want)
        wr = _datareps[getattr(self, "_datarep", "native")][1]
        if wr is None and arr.flags["C_CONTIGUOUS"]:
            return arr.reshape(-1).view(np.uint8).data
        raw = np.ascontiguousarray(arr).tobytes()
        return raw if wr is None else wr(raw, self.view.etype)

    def _from_bytes(self, raw: bytes) -> np.ndarray:
        rd = _datareps[getattr(self, "_datarep", "native")][0]
        if rd is not None:
            raw = rd(raw, self.view.etype)
        et = self.view.etype.base_np
        n = len(raw) // et.itemsize
        return np.frombuffer(bytearray(raw[:n * et.itemsize]),
                             dtype=et).copy()

    def read_at(self, offset: int, count: int) -> np.ndarray:
        """≈ MPI_File_read_at — offset/count in etype units of the view."""
        self._check_read()
        if trace_mod.active:
            with trace_mod.span("io", "read_at", rank=self.comm.pml.rank,
                                offset=offset,
                                nbytes=count * self.view.etype.size):
                return self._read_at_impl(offset, count)
        return self._read_at_impl(offset, count)

    def _read_at_impl(self, offset: int, count: int) -> np.ndarray:
        runs = self.view.byte_runs(offset, count * self.view.etype.size)
        rd = _datareps[getattr(self, "_datarep", "native")][0]
        if rd is None and len(runs) == 1 and hasattr(os, "preadv"):
            # plan-collapsed layout (contiguous view, or a single merged
            # run): ONE pread straight into the result array — skips the
            # bytes join + frombuffer + copy staging of the general path.
            # An EOF-short pread truncates the result, same as the
            # general path's short chunks.
            off, ln = runs[0]
            et = self.view.etype.base_np
            buf = np.empty(ln, np.uint8)
            got = os.preadv(self._fd, [memoryview(buf)], off)
            n = got // et.itemsize
            return buf[:n * et.itemsize].view(et)
        chunks = [os.pread(self._fd, ln, off) for off, ln in runs]
        return self._from_bytes(b"".join(chunks))

    def write_at(self, offset: int, data: Any) -> int:
        """≈ MPI_File_write_at — returns etypes written."""
        self._check_write()
        raw = self._as_bytes(data)
        if trace_mod.active:
            with trace_mod.span("io", "write_at", rank=self.comm.pml.rank,
                                offset=offset, nbytes=len(raw)):
                return self._write_raw_at(offset, raw)
        return self._write_raw_at(offset, raw)

    def _write_raw_at(self, offset: int, raw: bytes) -> int:
        runs = self.view.byte_runs(offset, len(raw))
        pos = 0
        for off, ln in runs:
            os.pwrite(self._fd, raw[pos:pos + ln], off)
            pos += ln
        return len(raw) // self.view.etype.size

    def _etypes_of(self, out: np.ndarray) -> int:
        """Etype count of a just-read element array (pointers advance in
        etype units, not base elements — they differ for derived etypes)."""
        return out.nbytes // self.view.etype.size

    def read(self, count: int) -> np.ndarray:
        """≈ MPI_File_read — individual pointer."""
        with self._io_lock:
            out = self.read_at(self._pos, count)
            self._pos += self._etypes_of(out)
        return out

    def write(self, data: Any) -> int:
        """≈ MPI_File_write — individual pointer."""
        with self._io_lock:
            n = self.write_at(self._pos, data)
            self._pos += n
        return n

    def seek(self, offset: int, whence: int = SEEK_SET) -> None:
        """≈ MPI_File_seek (etype units)."""
        with self._io_lock:
            if whence == SEEK_SET:
                self._pos = offset
            elif whence == SEEK_CUR:
                self._pos += offset
            elif whence == SEEK_END:
                self._pos = self.view.payload_bytes_up_to(
                    self.get_size()) // self.view.etype.size + offset
            else:
                raise MPIException(f"bad whence {whence}", error_class=3)

    def get_position(self) -> int:
        return self._pos

    # nonblocking variants: IO here is host-side and synchronous; MPI allows
    # immediate completion, so these return pre-completed requests (the
    # reference's ompio equally runs most iread/iwrite inline via progress)

    def iread_at(self, offset: int, count: int) -> Request:
        return CompletedRequest(self.read_at(offset, count), kind="iread")

    def iwrite_at(self, offset: int, data: Any) -> Request:
        return CompletedRequest(self.write_at(offset, data), kind="iwrite")

    def iread(self, count: int) -> Request:
        return CompletedRequest(self.read(count), kind="iread")

    def iwrite(self, data: Any) -> Request:
        return CompletedRequest(self.write(data), kind="iwrite")

    # -- nonblocking collective IO (≈ MPI_File_iread_all & co.) ------------
    #
    # The blocking collective runs on a per-file worker thread (one
    # thread, FIFO — issue order is completion order, the MPI requirement
    # for multiple outstanding collective IO ops on one handle).  All
    # ranks' workers meet inside the collective, so the caller's thread
    # never blocks — true split-phase, unlike the eager individual
    # i-ops above.

    def _io_async(self, kind: str, fn, *args) -> Request:
        import queue

        self._check_open()  # a post-close i-op must raise here, not
        # spawn a fresh worker that blocks on q.get() forever
        q = getattr(self, "_io_queue", None)
        if q is None:
            q = self._io_queue = queue.Queue()

            def worker() -> None:
                while True:
                    item = q.get()
                    if item is None:
                        return
                    req, f, a = item
                    try:
                        req.complete(f(*a))
                    except BaseException as e:  # noqa: BLE001 — to waiter
                        req.fail(e)

            t = threading.Thread(target=worker, daemon=True,
                                 name=f"io-nbc-{os.path.basename(self.path)}")
            self._io_thread = t
            t.start()
        req = Request(kind=kind)
        q.put((req, fn, args))
        return req

    def _ordered_collective(self, kind: str, fn, *args):
        """Blocking collective ops go through the SAME FIFO as any
        outstanding nonblocking/split collective: MPI requires collective
        file ops on one handle to complete in issue order on every rank,
        and a caller-thread collective racing the worker's can invert
        order on some ranks only — cross-matching their fixed-tag
        traffic.  With no worker running, run inline (no queue spawn)."""
        if getattr(self, "_io_queue", None) is not None:
            return self._io_async(kind, fn, *args).wait()
        return fn(*args)

    def write_at_all(self, offset: int, data: Any) -> int:
        return self._ordered_collective(
            "write_at_all", self._write_at_all_impl, offset, data)

    def read_at_all(self, offset: int, count: int) -> np.ndarray:
        return self._ordered_collective(
            "read_at_all", self._read_at_all_impl, offset, count)

    def write_all(self, data: Any) -> int:
        return self._ordered_collective(
            "write_all", self._write_all_impl, data)

    def read_all(self, count: int) -> np.ndarray:
        return self._ordered_collective(
            "read_all", self._read_all_impl, count)

    def write_ordered(self, data: Any) -> int:
        return self._ordered_collective(
            "write_ordered", self._write_ordered_impl, data)

    def read_ordered(self, count: int) -> np.ndarray:
        return self._ordered_collective(
            "read_ordered", self._read_ordered_impl, count)

    def iread_all(self, count: int) -> Request:
        return self._io_async("iread_all", self._read_all_impl, count)

    def iwrite_all(self, data: Any) -> Request:
        return self._io_async("iwrite_all", self._write_all_impl, data)

    def iread_at_all(self, offset: int, count: int) -> Request:
        return self._io_async("iread_at_all", self._read_at_all_impl, offset,
                              count)

    def iwrite_at_all(self, offset: int, data: Any) -> Request:
        return self._io_async("iwrite_at_all", self._write_at_all_impl, offset,
                              data)

    def iread_shared(self, count: int) -> Request:
        return self._io_async("iread_shared", self.read_shared, count)

    def iwrite_shared(self, data: Any) -> Request:
        return self._io_async("iwrite_shared", self.write_shared, data)

    # -- split collectives (≈ MPI_File_read_all_begin/end family) ----------
    #
    # begin = issue the nonblocking collective; end = wait.  MPI allows at
    # most ONE outstanding split collective per file handle, and the end
    # call must match the begin kind.

    def _split_begin(self, kind: str, fn, *args) -> None:
        if getattr(self, "_split_req", None) is not None:
            self._err(MPIException(
                f"split collective {self._split_kind} already outstanding "
                f"on this file handle", error_class=ERR_IO))
        self._split_kind = kind
        self._split_req = self._io_async(kind, fn, *args)

    def _split_end(self, kind: str):
        req = getattr(self, "_split_req", None)
        if req is None or self._split_kind != kind:
            self._err(MPIException(
                f"{kind}_end without matching {kind}_begin",
                error_class=ERR_IO))
        self._split_req = None
        return req.wait()

    def read_all_begin(self, count: int) -> None:
        self._split_begin("read_all", self._read_all_impl, count)

    def read_all_end(self) -> np.ndarray:
        return self._split_end("read_all")

    def write_all_begin(self, data: Any) -> None:
        self._split_begin("write_all", self._write_all_impl, data)

    def write_all_end(self) -> int:
        return self._split_end("write_all")

    def read_at_all_begin(self, offset: int, count: int) -> None:
        self._split_begin("read_at_all", self._read_at_all_impl, offset, count)

    def read_at_all_end(self) -> np.ndarray:
        return self._split_end("read_at_all")

    def write_at_all_begin(self, offset: int, data: Any) -> None:
        self._split_begin("write_at_all", self._write_at_all_impl, offset, data)

    def write_at_all_end(self) -> int:
        return self._split_end("write_at_all")

    def read_ordered_begin(self, count: int) -> None:
        self._split_begin("read_ordered", self._read_ordered_impl, count)

    def read_ordered_end(self) -> np.ndarray:
        return self._split_end("read_ordered")

    def write_ordered_begin(self, data: Any) -> None:
        self._split_begin("write_ordered", self._write_ordered_impl, data)

    def write_ordered_end(self) -> int:
        return self._split_end("write_ordered")

    # -- handle inquiries (≈ file_get_amode.c & co.) -----------------------

    def get_amode(self) -> int:
        """≈ MPI_File_get_amode."""
        return self.amode

    def get_group(self):
        """≈ MPI_File_get_group: the group of the comm the file was
        opened on."""
        return self.comm.group

    def get_byte_offset(self, offset: int) -> int:
        """≈ MPI_File_get_byte_offset: view-relative offset (etype units)
        → absolute byte offset in the file."""
        runs = self.view.byte_runs(int(offset), self.view.etype.size)
        if not runs:
            return self.view.disp
        return runs[0][0]

    def get_type_extent(self, datatype: Datatype) -> int:
        """≈ MPI_File_get_type_extent: the datatype's extent in the file's
        current data representation (same-size representations here)."""
        return datatype.extent

    def set_info(self, info) -> None:
        """≈ MPI_File_set_info."""
        self.info = info

    # -- collective IO (the fcoll framework) -------------------------------
    #
    # ≈ ompi/mca/fcoll: selectable collective algorithms (individual /
    # two_phase / dynamic — the reference's fcoll components of the same
    # names) + OMPIO-style aggregator selection (one per host from the job
    # mapping, like cb_nodes defaulting to one aggregator per node).
    # Component choice: info hints > io_fcoll var > auto decision from the
    # allgathered access pattern (every rank computes the same answer from
    # the same collective data).

    @staticmethod
    def _stripe_bytes() -> int:
        """Configured stripe width with the registered default as the
        single fallback (shared by static routing, dynamic_gen2 bound
        snapping and the aggregator read coalescer)."""
        from ompi_tpu.core.config import var_registry

        return int(var_registry.get("io_stripe_bytes")) or (1 << 20)

    def _my_host_key(self) -> int:
        """Stable host identity for aggregator grouping — THE single
        source (Communicator._my_host_key: shm BTL / split_type / IO all
        group by the same identity; tests override per-comm via
        ``comm._io_host_override``)."""
        return self.comm._my_host_key()

    def _aggregators(self) -> list[int]:
        """Aggregator ranks: the lowest ``io_cb_aggregators_per_host``
        ranks of each host (≈ OMPIO's one-aggregator-per-node default,
        mca_io_ompio_num_aggregators / cb_nodes).  The ``cb_nodes`` info
        hint caps the total.  Cached: the rank→host mapping is invariant
        for the communicator's lifetime, so the allgather runs once per
        file, not once per collective call."""
        cached = getattr(self, "_aggs_cache", None)
        if cached is not None:
            return cached
        from ompi_tpu.core.config import var_registry

        comm = self.comm
        keys = np.asarray(comm.allgather(
            np.array([self._my_host_key()], np.int64))).ravel()
        per_host = int(var_registry.get("io_cb_aggregators_per_host") or 1)
        by_host: dict[int, list[int]] = {}
        for rank, k in enumerate(keys):
            by_host.setdefault(int(k), []).append(rank)
        aggs = sorted(r for ranks in by_host.values()
                      for r in ranks[:max(1, per_host)])
        cap = self.info.get("cb_nodes") if self.info else None
        if cap:
            try:
                aggs = aggs[:max(1, int(cap))]
            except ValueError:
                pass
        self._aggs_cache = aggs
        return aggs

    def _fcoll_component(self, my_nbytes: int, my_runs) -> str:
        """Pick individual | two_phase | dynamic | static | dynamic_gen2
        — identically on every rank (decision inputs are allgathered).
        Precedence: info hint (collective_buffering/romio_cb_write=
        disable → individual) > io_fcoll var > auto (≈ OMPIO's fcoll
        query: small or contiguous per-rank patterns go individual;
        on network filesystems stripe-aligned domains win — static for
        balanced loads, dynamic_gen2 for skewed; otherwise two_phase
        for balanced, dynamic for skewed)."""
        from ompi_tpu.core.config import var_registry

        hint = ""
        if self.info:
            hint = (self.info.get("collective_buffering")
                    or self.info.get("romio_cb_write") or "")
        if str(hint).lower() in ("false", "disable", "0"):
            return "individual"
        forced = ""
        if self.info:
            forced = self.info.get("fcoll") or ""   # per-file pin
        forced = forced or var_registry.get("io_fcoll") or ""
        if forced:
            if forced not in ("individual", "two_phase", "dynamic",
                              "static", "dynamic_gen2"):
                raise MPIException(
                    f"unknown fcoll component {forced!r} (individual/"
                    f"two_phase/dynamic/static/dynamic_gen2)",
                    error_class=3)
            return forced
        if not var_registry.get("io_twophase"):
            return "individual"
        contig = 1 if (len(my_runs) <= 1) else 0
        stats = np.asarray(self.comm.allgather(np.array(
            [my_nbytes, contig], np.int64))).reshape(-1, 2)
        total = int(stats[:, 0].sum())
        # fs adaptation (≈ the fs framework's per-filesystem tuning,
        # fs_lustre.c): same answer on every rank — fs_type comes from
        # the shared path, and a split mount view would already break
        # shared-file IO in deeper ways
        adaptive = bool(var_registry.get("io_fs_adaptive"))
        if adaptive and self.fs_type in _FS_MEMORY:
            # memory-backed: every write is a memcpy — there is no seek
            # cost for aggregation to amortize, and the alltoallv
            # exchange costs more than the extra pwrite syscalls it
            # saves; individual IO wins for strided patterns too
            return "individual"
        min_bytes = int(var_registry.get("io_twophase_min_bytes"))
        if adaptive and self.fs_type in _FS_NETWORK:
            min_bytes = 1    # network fs: aggregate even small strided IO
        if total < min_bytes:
            return "individual"
        if int(stats[:, 1].min()) == 1:
            return "individual"   # everyone contiguous: direct IO wins
        nz = stats[:, 0][stats[:, 0] > 0]
        skewed = len(nz) and int(nz.max()) > 4 * int(nz.min())
        if adaptive and self.fs_type in _FS_NETWORK:
            # stripe-aligned domains keep each aggregator inside its own
            # filesystem stripes (the fcoll/static and dynamic_gen2
            # rationale: no two aggregators contend for one stripe lock)
            return "dynamic_gen2" if skewed else "static"
        if skewed:
            return "dynamic"      # skewed payloads → balance by bytes
        return "two_phase"

    def _domain_bounds(self, mode: str, my_runs, naggs: int
                       ) -> Optional[list[int]]:
        """Collective: ascending byte offsets b[0..naggs] partitioning
        the global extent into aggregator file domains.  two_phase =
        equal spans (fcoll/two_phase's static assignment); dynamic =
        equal *payload* per aggregator, boundaries derived from the
        allgathered run lists (fcoll/dynamic's data-driven domains).
        ``static`` routes cyclically by stripe (bounds only signal a
        non-empty extent); ``dynamic_gen2`` = dynamic's payload balance
        with every interior boundary snapped DOWN to a stripe multiple,
        so no two aggregator domains share a filesystem stripe (the
        fcoll/dynamic_gen2 refinement).  None ⇒ empty global extent."""
        comm = self.comm
        lo = my_runs[0][0] if my_runs else np.iinfo(np.int64).max
        hi = my_runs[-1][0] + my_runs[-1][1] if my_runs else 0
        ext = np.asarray(comm.allgather(np.array([lo, hi], np.int64)))
        glo, ghi = int(ext[:, 0].min()), int(ext[:, 1].max())
        if ghi <= glo:
            return None
        if mode not in ("dynamic", "dynamic_gen2"):
            dom = -(-(ghi - glo) // naggs)
            return [glo + i * dom for i in range(naggs)] + [ghi]
        # dynamic: payload-weighted boundaries need every rank's run
        # list — a ragged allgather (pad to the max count, like the
        # v-collectives' static-counts convention)
        flat = np.array([v for run in my_runs for v in run], np.int64)
        counts = np.asarray(comm.allgather(
            np.array([len(flat)], np.int64))).ravel()
        maxc = max(2, int(counts.max()))
        padded = np.zeros(maxc, np.int64)
        padded[:len(flat)] = flat
        stacked = np.asarray(comm.allgather(padded)).reshape(
            comm.size, maxc)
        runs: list[tuple[int, int]] = []
        for r in range(comm.size):
            arr = stacked[r, :int(counts[r])].reshape(-1, 2)
            runs.extend((int(o), int(ln)) for o, ln in arr)
        runs.sort()
        total = sum(ln for _, ln in runs)
        if total <= 0:
            return None
        share = -(-total // naggs)   # payload bytes per aggregator
        bounds = [glo]
        acc = 0
        for off, ln in runs:
            # place a boundary wherever cumulative payload crosses the
            # next share multiple (possibly several inside one long run)
            while acc + ln >= share * len(bounds) and len(bounds) < naggs:
                bounds.append(off + (share * len(bounds) - acc))
            acc += ln
        while len(bounds) < naggs:
            bounds.append(ghi)
        bounds.append(ghi)
        for i in range(1, len(bounds)):   # keep monotone under overlap
            bounds[i] = max(bounds[i], bounds[i - 1])
        if mode == "dynamic_gen2":
            stripe = self._stripe_bytes()
            for i in range(1, naggs):  # interior boundaries only
                bounds[i] = max(bounds[i] // stripe * stripe, bounds[0])
            for i in range(1, len(bounds)):
                bounds[i] = max(bounds[i], bounds[i - 1])
        return bounds

    def _route_to_aggregators(self, my_runs, bounds, aggs,
                              raw: Optional[bytes],
                              mode: str = "two_phase"):
        """Split my runs at domain boundaries and bucket (meta, payload)
        per destination rank.  raw=None ⇒ request-only (read path).
        ``static`` ignores the bounds partition and routes stripes
        round-robin: stripe k → aggregator k % naggs (fcoll/static's
        cyclic file domains).

        Also returns the ordered split sequence [(dest, take), …] — the
        read path's reassembly MUST walk the identical splits the
        requests were routed by, so the algorithm lives here once."""
        import bisect

        size = self.comm.size
        naggs = len(aggs)
        stripe = self._stripe_bytes() if mode == "static" else 0
        meta = [[] for _ in range(size)]
        payload = [[] for _ in range(size)] if raw is not None else None
        order: list[tuple[int, int]] = []

        # SPLIT phase, vectorized: most runs land whole inside one
        # domain/stripe — find the few that cross a boundary and expand
        # only those; the rest route with array math (a python loop per
        # run was the strided-view hot spot next to byte_runs)
        runs = np.asarray(my_runs, np.int64).reshape(-1, 2)
        offs, lens = runs[:, 0], runs[:, 1]
        if mode == "static":
            dom = offs // stripe
            dom_end = (dom + 1) * stripe
            idx = (dom % naggs).astype(np.int64)
        else:
            b = np.asarray(bounds, np.int64)
            idx = np.clip(np.searchsorted(b, offs, "right") - 1,
                          0, naggs - 1)
            dom_end = b[idx + 1]    # bounds has naggs+1 entries
        dom_end = np.maximum(dom_end, offs + 1)   # min take of 1
        crosses = offs + lens > dom_end
        if crosses.any():
            # expand crossing runs with the original per-run walk
            # (boundaries ≤ naggs, so crossers are few)
            exp_o, exp_l = [], []
            exp_i = []
            for off, ln in runs[crosses].tolist():
                while ln > 0:
                    if mode == "static":
                        i = (off // stripe) % naggs
                        de = (off // stripe + 1) * stripe
                    else:
                        i = min(max(bisect.bisect_right(bounds, off) - 1,
                                    0), naggs - 1)
                        de = (bounds[i + 1] if i + 1 < len(bounds)
                              else off + ln)
                    take = min(ln, max(de - off, 1))
                    exp_o.append(off)
                    exp_l.append(take)
                    exp_i.append(i)
                    off += take
                    ln -= take
            # stitch expanded pieces back in payload order
            pieces_o = [None] * len(runs)
            pieces_l = [None] * len(runs)
            pieces_i = [None] * len(runs)
            cross_rows = np.flatnonzero(crosses)
            keep_rows = np.flatnonzero(~crosses)
            for r in keep_rows.tolist():
                pieces_o[r] = [int(offs[r])]
                pieces_l[r] = [int(lens[r])]
                pieces_i[r] = [int(idx[r])]
            ci = 0
            for r in cross_rows.tolist():
                n_pieces = 0
                left = int(lens[r])
                while left > 0:
                    left -= exp_l[ci + n_pieces]
                    n_pieces += 1
                pieces_o[r] = exp_o[ci:ci + n_pieces]
                pieces_l[r] = exp_l[ci:ci + n_pieces]
                pieces_i[r] = exp_i[ci:ci + n_pieces]
                ci += n_pieces
            offs = np.array([o for p in pieces_o for o in p], np.int64)
            lens = np.array([v for p in pieces_l for v in p], np.int64)
            idx = np.array([v for p in pieces_i for v in p], np.int64)

        # BUCKET phase: runs arrive in payload order; per-destination
        # metadata is a boolean-mask gather and — when the view walks the
        # file monotonically (every nonpathological datatype) — each
        # domain's payload is ONE contiguous slice
        pay_pos = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        dests = np.asarray(aggs, np.int64)[idx]
        order = list(zip(dests.tolist(), lens.tolist()))
        for d in np.unique(dests).tolist():
            rows = np.flatnonzero(dests == d)
            meta[d] = np.stack([offs[rows], lens[rows]], axis=1)
            if raw is not None:
                if len(rows) and np.array_equal(
                        rows, np.arange(rows[0], rows[0] + len(rows))):
                    lo = int(pay_pos[rows[0]])
                    hi = int(pay_pos[rows[-1] + 1])
                    payload[d] = [raw[lo:hi]]
                else:   # non-monotone view: per-run gather
                    payload[d] = [raw[int(pay_pos[r]):int(pay_pos[r + 1])]
                                  for r in rows.tolist()]
        return meta, payload, order

    def _write_at_all_impl(self, offset: int, data: Any) -> int:
        """≈ MPI_File_write_at_all — collective write through the
        selected fcoll component (ref: fcoll/two_phase/
        fcoll_two_phase_file_write_all.c, fcoll/dynamic)."""
        self._check_write()
        raw = self._as_bytes(data)
        if trace_mod.active:
            with trace_mod.span("io", "write_at_all",
                                rank=self.comm.pml.rank, offset=offset,
                                nbytes=len(raw)):
                return self._write_at_all_body(offset, raw)
        return self._write_at_all_body(offset, raw)

    def _write_at_all_body(self, offset: int, raw: bytes) -> int:
        my_runs = self.view.byte_runs(offset, len(raw))
        comp = self._fcoll_component(len(raw), my_runs)
        if comp == "individual":
            n = self._write_raw_at(offset, raw)
            self.comm.barrier()
            return n
        comm = self.comm
        size = comm.size
        aggs = self._aggregators()
        bounds = self._domain_bounds(comp, my_runs, len(aggs))
        if bounds is None:
            comm.barrier()
            return 0
        meta, payload, _order = self._route_to_aggregators(
            my_runs, bounds, aggs, raw, mode=comp)
        meta_arrs = [np.array(m, np.int64).reshape(-1, 2).ravel()
                     for m in meta]
        pay_arrs = [np.frombuffer(b"".join(p), np.uint8) for p in payload]
        got_meta = comm.alltoallv(meta_arrs)
        got_pay = comm.alltoallv(pay_arrs)
        # aggregation phase: maximal contiguous writes, rank order wins.
        # Vectorized when the incoming runs don't overlap (the only
        # MPI-legal case): scatter every source's payload into one
        # domain-span buffer with numpy indexing, then one pwrite per
        # contiguous group — no per-run python slicing.
        metas = [np.asarray(got_meta[r], np.int64).reshape(-1, 2)
                 for r in range(size)]
        pays = [np.asarray(got_pay[r], np.uint8) for r in range(size)]
        nonempty = [r for r in range(size) if len(metas[r])]
        if not nonempty:
            comm.barrier()
            return len(raw) // self.view.etype.size
        offs_all = np.concatenate([metas[r][:, 0] for r in nonempty])
        lens_all = np.concatenate([metas[r][:, 1] for r in nonempty])
        srt = np.argsort(offs_all, kind="stable")
        so, sl = offs_all[srt], lens_all[srt]
        no_overlap = bool(np.all(so[1:] >= so[:-1] + sl[:-1]))
        base = int(so[0])
        span = int(so[-1] + sl[-1]) - base
        total_pay = int(lens_all.sum())
        # the span buffer trades memory for vectorized assembly — only a
        # good trade while it stays payload-sized (a SPARSE view's domain
        # can span orders of magnitude more file than it touches; there
        # the per-run path's payload-proportional memory wins)
        if no_overlap and span <= max(4 * total_pay, 1 << 20):
            buf = np.empty(span, np.uint8)
            for r in nonempty:
                m, p = metas[r], pays[r]
                L = int(m[0, 1]) if len(m) else 0
                if len(m) >= 16 and L <= 65536 and (m[:, 1] == L).all():
                    # many small uniform runs: one fancy scatter beats
                    # len(m) python slice assignments (the index temp is
                    # 8x payload, bounded by the small-L gate)
                    gidx = ((m[:, 0] - base)[:, None]
                            + np.arange(L, dtype=np.int64)[None, :])
                    buf[gidx.reshape(-1)] = p[:len(m) * L]
                else:
                    cur = 0
                    for foff, fln in m.tolist():
                        buf[foff - base:foff - base + fln] = \
                            p[cur:cur + fln]
                        cur += fln
            # contiguous groups of the sorted runs → one pwrite each
            brk = np.empty(len(so), bool)
            brk[0] = True
            np.not_equal(so[1:], so[:-1] + sl[:-1], out=brk[1:])
            gi = np.flatnonzero(brk)
            gends = np.append(gi[1:], len(so)) - 1
            mv = memoryview(buf)
            for lo, hi in zip(so[gi].tolist(),
                              (so[gends] + sl[gends]).tolist()):
                os.pwrite(self._fd, mv[lo - base:hi - base], lo)
        else:   # sparse domain (span ≫ payload) or overlapping writes
            # (erroneous per MPI): the original payload-proportional
            # rank-order aggregation
            agg: list[tuple[int, int, bytes]] = []
            for r in nonempty:
                p = pays[r].tobytes()
                cur = 0
                for foff, fln in metas[r].tolist():
                    agg.append((foff, fln, p[cur:cur + fln]))
                    cur += fln
            for off, abuf in _coalesce(agg):
                os.pwrite(self._fd, abuf, off)
        comm.barrier()
        return len(raw) // self.view.etype.size

    def _read_at_all_impl(self, offset: int, count: int) -> np.ndarray:
        """≈ MPI_File_read_at_all — collective read through the selected
        fcoll component."""
        self._check_read()
        if trace_mod.active:
            with trace_mod.span("io", "read_at_all",
                                rank=self.comm.pml.rank, offset=offset,
                                nbytes=count * self.view.etype.size):
                return self._read_at_all_body(offset, count)
        return self._read_at_all_body(offset, count)

    def _read_at_all_body(self, offset: int, count: int) -> np.ndarray:
        nbytes = count * self.view.etype.size
        my_runs = self.view.byte_runs(offset, nbytes)
        comp = self._fcoll_component(nbytes, my_runs)
        if comp == "individual":
            out = self.read_at(offset, count)
            self.comm.barrier()
            return out
        comm = self.comm
        size = comm.size
        aggs = self._aggregators()
        bounds = self._domain_bounds(comp, my_runs, len(aggs))
        if bounds is None:
            comm.barrier()
            return self._from_bytes(b"")
        meta, _pay, order = self._route_to_aggregators(
            my_runs, bounds, aggs, None, mode=comp)
        meta_arrs = [np.array(m, np.int64).reshape(-1, 2).ravel()
                     for m in meta]
        got_meta = comm.alltoallv(meta_arrs)
        # aggregators read each requested run once (coalesced pread over
        # their domain slice) and reply per requester; a pread can come
        # up short at EOF, so a reply may be shorter than requested
        import bisect as _bisect

        # bounds-partitioned modes keep the single span pread per
        # requester (runs inside one contiguous domain — one syscall
        # beats many tiny ones); static's cyclic domains cap the merge
        # gap at one stripe so an aggregator doesn't read the whole
        # extent to serve every naggs-th stripe of it
        merge_gap = self._stripe_bytes() if comp == "static" else None
        replies = []
        for r in range(size):
            m = np.asarray(got_meta[r], np.int64).reshape(-1, 2)
            if not len(m):
                replies.append(np.empty(0, np.uint8))
                continue
            offs_, lens_ = m[:, 0], m[:, 1]
            # interval merge, vectorized (sort + running max of ends)
            srt = np.argsort(offs_, kind="stable")
            so, se = offs_[srt], offs_[srt] + lens_[srt]
            cme = np.maximum.accumulate(se)
            if merge_gap is None:
                blocks = [(int(so[0]), int(cme[-1]))]
            else:
                newb = np.empty(len(so), bool)
                newb[0] = True
                np.greater_equal(so[1:], cme[:-1] + merge_gap,
                                 out=newb[1:])
                gi = np.flatnonzero(newb)
                ends = np.append(gi[1:], len(so)) - 1
                blocks = list(zip(so[gi].tolist(),
                                  cme[ends].tolist()))
            data = {blo: os.pread(self._fd, bhi - blo, blo)
                    for blo, bhi in blocks}
            if len(blocks) == 1:
                blo, bhi = blocks[0]
                blob = data[blo]
                arr = np.frombuffer(blob, np.uint8)
                L = int(lens_[0]) if lens_.size else 0
                if (len(blob) == bhi - blo and len(lens_) >= 16
                        and L <= 65536 and (lens_ == L).all()):
                    # many small uniform runs, nothing EOF-short: one
                    # fancy gather replaces the per-run python slicing
                    # (same L gate as the write scatter — for few/large
                    # runs the slice loop below is cheaper)
                    gidx = ((offs_ - blo)[:, None]
                            + np.arange(L, dtype=np.int64)[None, :])
                    replies.append(arr[gidx.reshape(-1)])
                    continue
            starts = [b[0] for b in blocks]
            parts = []
            for o, ln in m.tolist():
                blo = blocks[_bisect.bisect_right(starts, o) - 1][0]
                blob = data[blo]   # may be EOF-short: slice shortens
                parts.append(blob[o - blo:o - blo + ln])
            replies.append(np.frombuffer(b"".join(parts), np.uint8))
        got_pay = comm.alltoallv(replies)
        # reassemble in my original run order by replaying the SAME split
        # sequence the requests were routed by (aggregators preserve
        # request order).  EOF truncation shortens exactly a greedy
        # suffix of an aggregator's ascending runs, so the per-run actual
        # length is derivable from what remains of the reply blob.
        blobs = [np.asarray(got_pay[r], np.uint8).tobytes()
                 for r in range(size)]
        dests_arr = np.array([d for d, _ in order], np.int64)
        takes_arr = np.array([t for _, t in order], np.int64)
        grouped = (len(dests_arr) == 0
                   or (np.count_nonzero(np.diff(dests_arr)) + 1
                       == len(np.unique(dests_arr))))
        full = all(len(blobs[d])
                   == int(takes_arr[dests_arr == d].sum())
                   for d in np.unique(dests_arr).tolist())
        if grouped and full:
            # monotone view, no EOF truncation: each destination owns one
            # consecutive span of the split order, so the output is its
            # blobs concatenated in first-appearance order
            seen: dict[int, bool] = {}
            for d in dests_arr.tolist():
                seen.setdefault(d, True)
            out = bytearray(b"".join(blobs[d] for d in seen))
        else:
            cursors = [0] * size
            out = bytearray()
            for dest, take in order:
                got = min(take, max(0, len(blobs[dest]) - cursors[dest]))
                out += blobs[dest][cursors[dest]:cursors[dest] + got]
                cursors[dest] += got
        comm.barrier()
        return self._from_bytes(bytes(out))

    def _write_all_impl(self, data: Any) -> int:
        """≈ MPI_File_write_all (individual pointer + collective)."""
        with self._io_lock:
            n = self._write_at_all_impl(self._pos, data)
            self._pos += n
        return n

    def _read_all_impl(self, count: int) -> np.ndarray:
        """≈ MPI_File_read_all."""
        with self._io_lock:
            out = self._read_at_all_impl(self._pos, count)
            self._pos += self._etypes_of(out)
        return out

    # -- shared file pointer (sharedfp/lockedfile equivalent) --------------

    def _shfp_guard(self) -> None:
        if self._shfp_err:
            raise MPIException(
                f"shared file pointer unavailable: the "
                f"{self._shfp.name} component could not be set up at "
                f"open ({self._shfp_err})", error_class=ERR_IO)

    def _shfp_load(self) -> int:
        self._shfp_guard()
        return self._shfp.load()

    def _shfp_store(self, val: int) -> None:
        self._shfp_guard()
        self._shfp.store(val)

    def _shfp_fetch_add(self, n: int) -> int:
        """Atomically reserve n etypes of the shared pointer."""
        self._shfp_guard()
        return self._shfp.fetch_add(n)

    def _shfp_merge(self) -> None:
        """COLLECTIVE: the 'collaborate' step of sharedfp/individual —
        reconstruct the global shared-pointer order of the individually
        spooled writes (timestamp order, rank breaking ties) and land
        them in the file.  Runs at sync/close, before ordered ops, and
        before a view change (pending writes belong to the OLD view).
        No-op for the coordinated components."""
        sh = self._shfp
        if not getattr(sh, "local_log", False) or self._shfp_err:
            return
        recs = sh._recs
        mine = (np.array(recs, np.int64) if recs
                else np.zeros((0, 2), np.int64))
        allrecs = self.comm.allgatherv(mine)
        entries = []   # (t_ns, rank, local_idx, nbytes)
        for r, arr in enumerate(allrecs):
            a = np.asarray(arr).reshape(-1, 2)
            for i in range(a.shape[0]):
                entries.append((int(a[i, 0]), r, i, int(a[i, 1])))
        if not entries:
            return
        entries.sort()
        es = self.view.etype.size
        pos = sh.merged_end
        my_offsets = {}
        for _t, r, i, nb in entries:
            if r == self.comm.rank:
                my_offsets[i] = pos
            pos += nb // es
        if recs:
            sh._spool.seek(0)
            for i, (_t, nb) in enumerate(recs):
                raw = sh._spool.read(nb)
                self._write_raw_at(my_offsets[i], raw)
            sh._spool.seek(0)
            sh._spool.truncate()
            sh._recs = []
        sh.merged_end = pos
        self.comm.barrier()

    def read_shared(self, count: int) -> np.ndarray:
        """≈ MPI_File_read_shared."""
        self._check_read()  # before reserving: a failed call must not
        start = self._shfp_fetch_add(count)  # advance the shared pointer
        return self.read_at(start, count)

    def write_shared(self, data: Any) -> int:
        """≈ MPI_File_write_shared."""
        self._check_write()
        raw = self._as_bytes(data)
        n = len(raw) // self.view.etype.size
        if getattr(self._shfp, "local_log", False):
            self._shfp_guard()
            self._shfp.log_write(raw)   # local spool; lands at the merge
            return n
        start = self._shfp_fetch_add(n)
        self._write_raw_at(start, raw)
        return n

    def seek_shared(self, offset: int, whence: int = SEEK_SET) -> None:
        """≈ MPI_File_seek_shared — collective (all must give same args)."""
        self._check_open()
        if getattr(self._shfp, "local_log", False):
            # raise UNIFORMLY before any collective step: with
            # sharedfp/individual a rank-0-only raise inside the body
            # would strand the other ranks in the closing barrier
            raise self._shfp._unsupported()
        if whence == SEEK_CUR:
            offset += self._shfp_load()
        elif whence == SEEK_END:
            offset += self.view.payload_bytes_up_to(
                self.get_size()) // self.view.etype.size
        elif whence != SEEK_SET:
            raise MPIException(f"bad whence {whence}", error_class=3)
        if self.comm.rank == 0:
            self._shfp_store(offset)
        self.comm.barrier()

    def get_position_shared(self) -> int:
        return self._shfp_load()

    # ordered mode: rank-ordered slots computed with an exscan of sizes

    def _ordered_base(self) -> tuple[int, bool]:
        """Start position for an ordered op: the coordinated components
        read the live pointer; sharedfp/individual first lands its
        pending spooled writes (the op is collective, so the merge is
        safe here) and uses the agreed merged end."""
        if getattr(self._shfp, "local_log", False):
            self._shfp_merge()
            self._shfp_guard()
            return self._shfp.merged_end, True
        return self._shfp_load(), False

    def _write_ordered_impl(self, data: Any) -> int:
        """≈ MPI_File_write_ordered — collective, rank order in file."""
        self._check_write()
        raw = self._as_bytes(data)
        n = len(raw) // self.view.etype.size
        sizes = np.asarray(self.comm.allgather(np.array([n], np.int64)))
        base, individual = self._ordered_base()
        my_off = base + int(sizes[:self.comm.rank].sum())
        self._write_raw_at(my_off, raw)
        self.comm.barrier()
        if individual:
            self._shfp.merged_end = base + int(sizes.sum())
        elif self.comm.rank == 0:
            self._shfp_store(base + int(sizes.sum()))
        self.comm.barrier()
        return n

    def _read_ordered_impl(self, count: int) -> np.ndarray:
        """≈ MPI_File_read_ordered."""
        self._check_read()
        sizes = np.asarray(self.comm.allgather(np.array([count], np.int64)))
        base, individual = self._ordered_base()
        my_off = base + int(sizes[:self.comm.rank].sum())
        out = self.read_at(my_off, count)
        self.comm.barrier()
        if individual:
            self._shfp.merged_end = base + int(sizes.sum())
        elif self.comm.rank == 0:
            self._shfp_store(base + int(sizes.sum()))
        self.comm.barrier()
        return out

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        return f"File({self.path!r}, amode={self.amode:#x})"
