"""Dynamic process management: connect/accept, spawn, intercommunicators.

≈ ompi/dpm/dpm.c (MPI_Comm_connect/accept/spawn over ORTE+PMIx) and the
intercommunicator core (ompi/communicator).  Redesign for this stack:

- A *port* (MPI_Open_port) is a plain TCP rendezvous socket on the
  accepting leader; the connect/accept handshake exchanges each job's
  size and per-rank BTL addresses through it.
- Two independently-launched jobs both number ranks from 0, so each side
  installs the other's procs under *translated ids* (offset by its own
  world size) and registers a BTL alias so its frames arrive under the id
  the other side knows it by (btl.py set_alias).
- The resulting :class:`Intercomm` does p2p against the remote group,
  rooted bcast/barrier, and ``merge()`` into a plain intracommunicator
  (MPI_Intercomm_merge) — the merged communicator works because both
  sides agree on member *order* (low group first) while each process
  addresses members through its own namespace ids.
- ``spawn()`` launches a child job via the tpurun launcher with the
  parent's port in the environment; children find it with
  :func:`get_parent` (≈ MPI_Comm_get_parent).

CID agreement: the handshake carries both sides' DPM sequence numbers;
the intercomm cid is drawn from a reserved high window (1<<20) offset by
their max, so it can't collide with either side's intra-comm cids.
"""

from __future__ import annotations

import os
import socket
import struct
import subprocess
import sys
import threading
from typing import Any, Optional, Sequence

import numpy as np

from ompi_tpu.core import dss
from ompi_tpu.mpi.comm import Communicator, _INTERNAL_TAG_BASE as _ITAG_BASE
from ompi_tpu.mpi.constants import (ANY_TAG, ERR_NAME, ERR_PORT, ERR_SERVICE,
                                    PROC_NULL, MPIException)
from ompi_tpu.mpi.group import Group
from ompi_tpu.mpi import op as op_mod
from ompi_tpu.mpi.request import Request, Status

__all__ = ["Intercomm", "open_port", "close_port", "accept", "connect",
           "spawn", "spawn_multiple", "get_parent", "intercomm_create",
           "join", "ENV_PARENT_PORT",
           "publish_name", "unpublish_name", "lookup_name"]

ENV_PARENT_PORT = "OMPI_TPU_PARENT_PORT"
ENV_NAME_DIR = "OMPI_TPU_NAME_DIR"

_DPM_CID_BASE = 1 << 20
# combined tcp+shm business cards carry a filesystem path; 192B covers the
# longest inbox path tempfile generates (the reference's modex equivalently
# grows its byte-object values)
_CARD_BYTES = 192
_dpm_seq_lock = threading.Lock()
_dpm_seq = 0


def _next_dpm_seq() -> int:
    global _dpm_seq
    with _dpm_seq_lock:
        _dpm_seq += 1
        return _dpm_seq


# ---------------------------------------------------------------------------
# ports (≈ MPI_Open_port / MPI_Close_port)
# ---------------------------------------------------------------------------

class _Port:
    """A listening rendezvous socket on the accepting leader."""

    def __init__(self) -> None:
        self.sock = socket.create_server(("127.0.0.1", 0), backlog=8)
        host, port = self.sock.getsockname()
        self.name = f"{host}:{port}"

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


_ports: dict[str, _Port] = {}


def open_port() -> str:
    """≈ MPI_Open_port — returns the port name to hand to connectors."""
    p = _Port()
    _ports[p.name] = p
    return p.name


def close_port(name: str) -> None:
    p = _ports.pop(name, None)
    if p is not None:
        p.close()


# ---------------------------------------------------------------------------
# name service (≈ MPI_Publish_name / MPI_Lookup_name / MPI_Unpublish_name,
# ompi/mpi/c/publish_name.c → pmix publish; the ompi-server/orte-data-server
# role).  Realized as an atomic file registry so independently-launched jobs
# on a host (or on a shared filesystem) can rendezvous without a standing
# server — set OMPI_TPU_NAME_DIR to a shared path for cross-host lookup.
# ---------------------------------------------------------------------------

def _name_dir() -> str:
    import tempfile

    d = os.environ.get(ENV_NAME_DIR)
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"ompi_tpu_names-{os.getuid()}")
    os.makedirs(d, mode=0o700, exist_ok=True)
    return d


def _name_path(service_name: str) -> str:
    # service names are user strings; encode to a safe filename
    import base64

    enc = base64.urlsafe_b64encode(service_name.encode()).decode()
    return os.path.join(_name_dir(), enc)


def publish_name(service_name: str, port_name: str) -> None:
    """≈ MPI_Publish_name: bind ``service_name`` → ``port_name``.  Raises
    ERR_SERVICE if already published.  Publication is atomic (write-then-
    link): a concurrent lookup_name either sees the complete port or
    nothing — never a half-written file."""
    import tempfile

    path = _name_path(service_name)
    fd, tmp = tempfile.mkstemp(dir=_name_dir(), prefix=".pub-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(port_name)
        try:
            os.link(tmp, path)  # atomic + fails if already published
        except FileExistsError:
            raise MPIException(
                f"publish_name: {service_name!r} is already published",
                error_class=ERR_SERVICE)
    finally:
        os.unlink(tmp)


def lookup_name(service_name: str) -> str:
    """≈ MPI_Lookup_name → the published port name (ERR_NAME if absent)."""
    try:
        with open(_name_path(service_name)) as f:
            return f.read()
    except FileNotFoundError:
        raise MPIException(
            f"lookup_name: {service_name!r} is not published",
            error_class=ERR_NAME)


def unpublish_name(service_name: str) -> None:
    """≈ MPI_Unpublish_name (ERR_SERVICE if not currently published)."""
    try:
        os.unlink(_name_path(service_name))
    except FileNotFoundError:
        raise MPIException(
            f"unpublish_name: {service_name!r} is not published",
            error_class=ERR_SERVICE)


def _send_blob(sock: socket.socket, obj: Any) -> None:
    blob = dss.pack(obj)
    sock.sendall(struct.pack("<I", len(blob)) + blob)


def _recv_blob(sock: socket.socket) -> Any:
    raw = b""
    while len(raw) < 4:
        chunk = sock.recv(4 - len(raw))
        if not chunk:
            raise MPIException("dpm handshake: connection closed")
        raw += chunk
    (n,) = struct.unpack("<I", raw)
    blob = b""
    while len(blob) < n:
        chunk = sock.recv(n - len(blob))
        if not chunk:
            raise MPIException("dpm handshake: connection closed")
        blob += chunk
    return dss.unpack(blob, n=1)[0]


# ---------------------------------------------------------------------------
# intercommunicator
# ---------------------------------------------------------------------------

class Intercomm:
    """Two disjoint groups sharing a message context (≈ MPI
    intercommunicator): ranks in p2p calls refer to the REMOTE group."""

    def __init__(self, local_comm: Communicator, remote_ids: Sequence[int],
                 cid: int, low: bool, name: str = "intercomm") -> None:
        self.local_comm = local_comm
        self.remote_ids = list(remote_ids)   # namespace ids, remote order
        self.cid = cid
        self.low = low                       # my group orders first
        self.name = name
        self.pml = local_comm.pml
        self.rank = local_comm.rank
        self._pending: list = []   # outstanding user p2p (disconnect waits)

    @property
    def size(self) -> int:
        return self.local_comm.size

    @property
    def remote_size(self) -> int:
        return len(self.remote_ids)

    # -- p2p against the remote group -------------------------------------

    def _track(self, req: Request) -> Request:
        """Remember outstanding user p2p so disconnect() can honor the
        MPI contract (all pending communication completes first)."""
        self._pending = [r for r in self._pending if not r.test()]
        self._pending.append(req)
        return req

    def isend(self, buf: Any, dest: int, tag: int = 0) -> Request:
        if dest == PROC_NULL:
            from ompi_tpu.mpi.request import CompletedRequest

            return CompletedRequest()
        return self._track(self.pml.isend(np.asarray(buf),
                                          self.remote_ids[dest], tag,
                                          self.cid))

    def send(self, buf: Any, dest: int, tag: int = 0) -> None:
        self.isend(buf, dest, tag).wait()

    def irecv(self, source: int = 0, tag: int = ANY_TAG) -> Request:
        src = self.remote_ids[source] if source >= 0 else source
        return self._track(self.pml.irecv(None, src, tag, self.cid))

    def recv(self, source: int = 0, tag: int = ANY_TAG,
             status: Optional[Status] = None) -> np.ndarray:
        req = self.irecv(source, tag)
        out = req.wait()
        if status is not None:
            status.__dict__.update(req.status.__dict__)
            if status.source >= 0:
                status.source = self.remote_ids.index(status.source)
        return out

    # -- internal p2p on the reserved (negative) tag space ----------------
    # ≈ the reference's MCA_COLL_BASE_TAG_* range: intercomm collectives
    # must never match user p2p on the same context id.

    _CTAG_BARRIER, _CTAG_BCAST, _CTAG_REDUCE = 700, 701, 702
    _CTAG_GATHER, _CTAG_SCATTER, _CTAG_XCHG = 703, 704, 705

    def _coll_isend(self, buf, dest: int, ctag: int) -> Request:
        return self.pml.isend(np.asarray(buf), self.remote_ids[dest],
                              _ITAG_BASE - ctag, self.cid)

    def _check_remote_root(self, root, what: str) -> None:
        """Integer roots name a REMOTE rank; anything out of range (notably
        other negative constants) must raise, not wrap around remote_ids."""
        if not 0 <= root < self.remote_size:
            raise MPIException(
                f"intercomm {what} root {root} out of remote range "
                f"0..{self.remote_size - 1} (use 'root' on the receiving "
                f"rank, PROC_NULL on its group-mates)", error_class=6)

    def _coll_recv(self, source: int, ctag: int) -> np.ndarray:
        return self.pml.irecv(None, self.remote_ids[source],
                              _ITAG_BASE - ctag, self.cid).wait()

    # -- collectives (≈ ompi/mca/coll/inter/: each op is local-group
    # collectives stitched by a leader exchange) ---------------------------

    def barrier(self) -> None:
        """Both groups synchronized: local barriers + leader exchange."""
        self.local_comm.barrier()
        if self.rank == 0:
            sreq = self._coll_isend(np.zeros(0, np.uint8),
                                    0, self._CTAG_BARRIER)
            self._coll_recv(0, self._CTAG_BARRIER)
            sreq.wait()
        self.local_comm.barrier()

    def bcast(self, buf: Any = None, root: Any = None):
        """≈ intercomm MPI_Bcast: ``root='root'`` on the sending rank,
        an int (remote root rank) on the receiving group, PROC_NULL on the
        sending group's non-roots."""
        if root == "root":
            self._coll_isend(np.asarray(buf), 0, self._CTAG_BCAST).wait()
            return np.asarray(buf)
        if root == PROC_NULL or root is None:
            return None
        if not 0 <= root < self.remote_size:
            raise MPIException(
                f"intercomm bcast root {root} out of remote range "
                f"(use 'root' on the sending rank, PROC_NULL on its "
                f"group-mates)", error_class=6)
        if self.rank == 0:
            out = self._coll_recv(root, self._CTAG_BCAST)
        else:
            out = None
        return self.local_comm.bcast(out, root=0)

    def reduce(self, sendbuf, op=None, root: Any = None):
        """≈ intercomm MPI_Reduce: the reduction of the OTHER group's data
        arrives at ``root='root'``; the contributing group passes the
        receiving rank's remote index as ``root`` (PROC_NULL on the root
        group's non-roots, which contribute nothing and get None)."""
        op = op if op is not None else op_mod.SUM
        if root == "root":
            # the contributing group's local rank 0 = my remote index 0
            return np.asarray(self._coll_recv(0, self._CTAG_REDUCE))
        if root == PROC_NULL or root is None:
            return None
        self._check_remote_root(root, "reduce")
        partial = self.local_comm.reduce(np.asarray(sendbuf), op=op, root=0)
        if self.rank == 0:
            self._coll_isend(partial, root, self._CTAG_REDUCE).wait()
        return None

    def allreduce(self, sendbuf, op=None):
        """≈ intercomm MPI_Allreduce: group A's reduction lands on every
        rank of group B and vice versa (MPI-3.1 §5.2.3 swap semantics)."""
        op = op if op is not None else op_mod.SUM
        partial = self.local_comm.reduce(np.asarray(sendbuf), op=op, root=0)
        if self.rank == 0:
            sreq = self._coll_isend(partial, 0, self._CTAG_XCHG)
            theirs = self._coll_recv(0, self._CTAG_XCHG)
            sreq.wait()
        else:
            theirs = None
        return self.local_comm.bcast(theirs, root=0)

    def allgather(self, sendbuf):
        """≈ intercomm MPI_Allgather: every rank receives the REMOTE
        group's contributions, stacked in remote rank order
        (shape ``(remote_size, *part_shape)``)."""
        mine = self.local_comm.gather(np.asarray(sendbuf), root=0)
        if self.rank == 0:
            stacked = np.stack([np.asarray(p) for p in mine])
            sreq = self._coll_isend(stacked, 0, self._CTAG_XCHG)
            theirs = self._coll_recv(0, self._CTAG_XCHG)
            sreq.wait()
        else:
            theirs = None
        return np.asarray(self.local_comm.bcast(theirs, root=0))

    def gather(self, sendbuf=None, root: Any = None):
        """≈ intercomm MPI_Gather: ``root='root'`` receives a list of the
        remote group's contributions in remote rank order."""
        if root == "root":
            return [np.asarray(self._coll_recv(r, self._CTAG_GATHER))
                    for r in range(self.remote_size)]
        if root == PROC_NULL or root is None:
            return None
        self._check_remote_root(root, "gather")
        self._coll_isend(np.asarray(sendbuf), root,
                         self._CTAG_GATHER).wait()
        return None

    def scatter(self, sendparts=None, root: Any = None):
        """≈ intercomm MPI_Scatter: ``root='root'`` sends part i to remote
        rank i; receiving-group ranks pass the root's remote index."""
        if root == "root":
            if len(sendparts) != self.remote_size:
                raise MPIException(
                    f"intercomm scatter needs {self.remote_size} parts, "
                    f"got {len(sendparts)}", error_class=6)
            reqs = [self._coll_isend(np.asarray(p), r, self._CTAG_SCATTER)
                    for r, p in enumerate(sendparts)]
            for r in reqs:
                r.wait()
            return None
        if root == PROC_NULL or root is None:
            return None
        self._check_remote_root(root, "scatter")
        return np.asarray(self._coll_recv(root, self._CTAG_SCATTER))

    # -- merge (≈ MPI_Intercomm_merge) -------------------------------------

    def test_inter(self) -> bool:
        """≈ MPI_Comm_test_inter."""
        return True

    def remote_group(self) -> Group:
        """≈ MPI_Comm_remote_group: the remote side's ids as a Group."""
        return Group(self.remote_ids)

    def get_group(self) -> Group:
        """≈ MPI_Comm_group: the LOCAL group."""
        return self.local_comm.group

    def disconnect(self) -> None:
        """≈ MPI_Comm_disconnect: collective over BOTH groups; completes
        every pending p2p request issued through this intercomm, then
        synchronizes both sides before dropping the local resources —
        so no in-flight message can outlive the communicator."""
        for r in self._pending:
            r.wait()
        self._pending = []
        self.barrier()           # both groups, not just the local one
        self.remote_ids = []

    def merge(self, high: Optional[bool] = None) -> Communicator:
        """Collective on both groups: one intracommunicator, low group's
        ranks first (each process addresses members via its own namespace
        ids, but the ORDER is agreed, so rank numbering is global)."""
        high = (not self.low) if high is None else high
        local_ids = [self.local_comm.world_rank(r)
                     for r in range(self.size)]
        mine_first = not high
        ordered = (local_ids + self.remote_ids if mine_first
                   else self.remote_ids + local_ids)
        merged = Communicator(Group(ordered), self.cid + 1, self.pml,
                              local_ids[self.rank],
                              name=f"{self.name}.merged")
        return merged

    def __repr__(self) -> str:
        return (f"Intercomm({self.name}, local={self.size}, "
                f"remote={self.remote_size}, cid={self.cid})")


# ---------------------------------------------------------------------------
# connect / accept (collective over each side's communicator)
# ---------------------------------------------------------------------------

def _exchange_over_port(sock: socket.socket, mine: dict,
                        first: bool) -> dict:
    if first:
        _send_blob(sock, mine)
        return _recv_blob(sock)
    theirs = _recv_blob(sock)
    _send_blob(sock, mine)
    return theirs


def _wire_remote(comm: Communicator, info: dict, my_info: dict
                 ) -> tuple[list[int], int]:
    """Install remote addresses + aliases; return (remote ids, cid)."""
    my_ns = my_info["ns_size"]           # my namespace base for them
    their_ns = info["ns_size"]
    remote_ids = [my_ns + i for i in range(info["size"])]
    peers = {my_ns + i: addr for i, addr in enumerate(info["addrs"])}
    comm.pml.set_peers(peers)
    for rid in remote_ids:
        # my id in THEIR namespace: their base + my rank in this comm
        # (the index they assign me from my position in the addrs list)
        comm.pml.endpoint.set_alias(rid, their_ns + comm.rank)
    cid = _DPM_CID_BASE + 2 * max(info["seq"], my_info["seq"])
    return remote_ids, cid


def _job_info(comm: Communicator) -> dict:
    """Collect this job's business cards on the leader and agree on the
    namespace base: one past every id this job's endpoints already know
    (world ranks AND ids installed by earlier connect/accept calls, so
    repeated dpm operations never collide)."""
    addr = comm.pml.address.encode()
    # outcome must be collective: a rank-local raise here would leave the
    # other ranks blocked in the gather below
    too_long = int(np.asarray(comm.allreduce(
        np.array([1 if len(addr) > _CARD_BYTES else 0], np.int32),
        op=_max_op()))[0])
    if too_long:
        raise MPIException(
            f"a BTL address exceeds the {_CARD_BYTES}-byte business-card "
            f"slot (mine: {comm.pml.address!r}); cannot exchange over "
            f"fixed-width gather")
    addr_rows = comm.gather(
        np.frombuffer(addr.ljust(_CARD_BYTES), np.uint8), root=0)
    addrs = None
    if comm.rank == 0:
        addrs = [bytes(np.asarray(r)).decode().strip() for r in addr_rows]
    known = max(comm.world_rank(comm.rank),
                comm.pml.endpoint.max_peer_id())
    ns = int(np.asarray(comm.allreduce(
        np.array([known + 1], np.int64), op=_max_op()))[0])
    return {"size": comm.size, "addrs": addrs, "ns_size": ns,
            "seq": _next_dpm_seq()}


def _max_op():
    from ompi_tpu.mpi import op as op_mod

    return op_mod.MAX


def _finish_side(comm: Communicator, port_sock: Optional[socket.socket],
                 my_info: dict, low: bool, name: str) -> Intercomm:
    """Leader exchanged info; broadcast to the group and wire up."""
    if comm.rank == 0:
        theirs = _exchange_over_port(port_sock, my_info, first=not low)
        blob = dss.pack(theirs)
        arr = np.frombuffer(blob, np.uint8)
        comm.bcast(np.array([len(arr)], np.int64), root=0)
        comm.bcast(arr, root=0)
    else:
        n = int(np.asarray(comm.bcast(None, root=0))[0])
        arr = np.asarray(comm.bcast(None, root=0))[:n]
        theirs = dss.unpack(bytes(arr), n=1)[0]
    # seq agreement: every rank must derive the same cid — leaders' seqs
    # rode along in the exchanged dicts
    my_info = dict(my_info)
    my_info["seq"] = int(np.asarray(comm.bcast(
        np.array([my_info["seq"]], np.int64), root=0))[0])
    remote_ids, cid = _wire_remote(comm, theirs, my_info)
    ic = Intercomm(comm, remote_ids, cid, low=low, name=name)
    ic.barrier()     # both sides reachable before user traffic
    return ic


_spawned: list = []   # Popen handles of spawned launchers (not reaped here)

# intercomm_create cids live in their own window above the connect/accept
# block so the two families never collide
_ICC_CID_BASE = 1 << 21

# per-process next-free icc cid offset, agreed by MAX over every
# participant at creation (the reference's cid allocation discipline:
# ompi_comm_nextcid's max-agreement) — a per-pair sequence number would
# let two leader pairs with disjoint histories mint the same cid while
# sharing member processes, silently cross-matching traffic.
_icc_lock = threading.Lock()
_icc_next = [0]


def _icc_bump(cid_off: int) -> None:
    with _icc_lock:
        _icc_next[0] = max(_icc_next[0], cid_off + 1)


def intercomm_create(local_comm: Communicator, local_leader: int,
                     bridge_comm: Communicator, remote_leader: int,
                     tag: int = 0) -> Intercomm:
    """≈ MPI_Intercomm_create: build an intercommunicator from two
    disjoint groups of ONE world, leaders exchanging group info over
    ``bridge_comm`` p2p (dpm.c's same-job path — no sockets, no business
    cards: both groups already share the namespace and transports)."""
    me_leader = local_comm.rank == local_leader
    # collision-free cid: my group's max next-free offset (collective),
    # then leaders exchange and take the global max — any process that
    # ever saw offset k has bumped past it, so no member of the new
    # intercomm can hold an old intercomm with the same cid
    with _icc_lock:
        my_next = _icc_next[0]
    local_next = int(np.asarray(local_comm.allreduce(
        np.array([my_next], np.int64), op=_max_op()))[0])
    if me_leader:
        mine = np.array([local_comm.world_rank(r)
                         for r in range(local_comm.size)], np.int64)
        hdr = np.array([local_next, len(mine)], np.int64)
        sreq = bridge_comm.isend(np.concatenate([hdr, mine]),
                                 dest=remote_leader, tag=tag)
        got = np.asarray(bridge_comm.recv(source=remote_leader, tag=tag))
        sreq.wait()
        their_next, n = int(got[0]), int(got[1])
        remote = got[2:2 + n]
        cid = _ICC_CID_BASE + max(local_next, their_next)
        blob = np.concatenate([np.array([cid], np.int64), remote])
        local_comm.bcast(np.array([len(blob)], np.int64),
                         root=local_leader)
        local_comm.bcast(blob, root=local_leader)
    else:
        n = int(np.asarray(local_comm.bcast(None, root=local_leader))[0])
        blob = np.asarray(local_comm.bcast(None, root=local_leader))[:n]
        cid = int(blob[0])
        remote = blob[1:]
    _icc_bump(cid - _ICC_CID_BASE)
    # overlapping groups are erroneous in MPI — catch the common mistake
    local_ids = {local_comm.world_rank(r) for r in range(local_comm.size)}
    if local_ids & set(int(r) for r in remote):
        raise MPIException(
            "intercomm_create: local and remote groups overlap",
            error_class=5)
    low = min(local_ids) < min(int(r) for r in remote)
    ic = Intercomm(local_comm, [int(r) for r in remote], cid, low=low,
                   name=f"{local_comm.name}.icc")
    ic.barrier()
    return ic


def join(fd: int, comm: Optional[Communicator] = None) -> Intercomm:
    """≈ MPI_Comm_join: a 1×1 intercommunicator between the two processes
    at the ends of a connected socket (comm_join.c).  ``fd`` is the
    caller-owned socket file descriptor; side ordering derives from the
    socket's own address pair, so both ends decide consistently."""
    if comm is None:
        from ompi_tpu.mpi import runtime as rt

        rt.init()
        comm = rt._state["self"]
    sock = socket.socket(fileno=os.dup(fd))  # caller keeps their fd
    try:
        # side ordering by explicit nonce exchange: socket addresses are
        # NOT usable here (AF_UNIX socketpairs report the same empty name
        # on both ends).  Both sides send 16 random bytes and compare —
        # exactly one side is "low"; a tie is astronomically unlikely and
        # rejected rather than mis-merged.
        mine = os.urandom(16)
        sock.sendall(mine)
        theirs = b""
        while len(theirs) < 16:
            chunk = sock.recv(16 - len(theirs))
            if not chunk:
                raise MPIException("join: peer closed during handshake")
            theirs += chunk
        if mine == theirs:
            raise MPIException("join: nonce tie; retry")
        low = mine < theirs
        my_info = _job_info(comm)
        return _finish_side(comm, sock, my_info, low=low,
                            name=f"{comm.name}.join")
    finally:
        sock.close()


def accept(comm: Communicator, port_name: Optional[str]) -> Intercomm:
    """≈ MPI_Comm_accept — collective; leader owns the port (non-leaders
    may pass None)."""
    my_info = _job_info(comm)
    sock = None
    if comm.rank == 0:
        port = _ports.get(port_name)
        if port is None:
            raise MPIException(f"unknown port {port_name}",
                               error_class=ERR_PORT)
        conn, _ = port.sock.accept()
        sock = conn
    try:
        return _finish_side(comm, sock, my_info, low=True,
                            name=f"{comm.name}.accept")
    finally:
        if sock is not None:
            sock.close()


def connect(comm: Communicator, port_name: str,
            timeout: float = 30.0) -> Intercomm:
    """≈ MPI_Comm_connect — collective; leader dials the port."""
    my_info = _job_info(comm)
    sock = None
    if comm.rank == 0:
        host, port = port_name.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        return _finish_side(comm, sock, my_info, low=False,
                            name=f"{comm.name}.connect")
    finally:
        if sock is not None:
            sock.close()


# ---------------------------------------------------------------------------
# spawn (≈ MPI_Comm_spawn) + get_parent
# ---------------------------------------------------------------------------

def _dvm_submit_args(child_env: dict) -> list:
    """Elastic grow on a standing pool: a job that was itself launched
    through a multi-tenant DVM carries ``OMPI_TPU_DVM_URI`` in its env —
    its spawns then go back through the SAME pool's admission queue and
    gang scheduler (``--dvm-submit``) instead of forking a private
    single-shot launcher next to it.  Outside a DVM this is a no-op."""
    uri = child_env.get("OMPI_TPU_DVM_URI")
    if not uri:
        return []
    return ["--dvm-submit", "--dvm-uri", uri]


def spawn(comm: Communicator, argv: Sequence[str], maxprocs: int = 1,
          env: Optional[dict] = None, timeout: float = 120.0) -> Intercomm:
    """Launch `maxprocs` child procs running ``argv`` under the tpurun
    launcher; returns the parent↔children intercommunicator.  Children
    reach us via :func:`get_parent`."""
    port_name = None
    proc = None
    if comm.rank == 0:
        port_name = open_port()
        child_env = dict(os.environ)
        child_env[ENV_PARENT_PORT] = port_name
        if env:
            child_env.update(env)
        cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun",
               *_dvm_submit_args(child_env),
               "-np", str(maxprocs), "--"] + list(argv)
        proc = subprocess.Popen(cmd, env=child_env)
        _spawned.append(proc)   # keep the handle; launcher owns lifetime
    try:
        return accept(comm, port_name)
    finally:
        if port_name is not None:
            close_port(port_name)


def spawn_multiple(comm: Communicator,
                   commands: Sequence[Sequence[str]],
                   maxprocs: Sequence[int],
                   envs: Optional[Sequence[Optional[dict]]] = None,
                   timeout: float = 120.0) -> Intercomm:
    """≈ MPI_Comm_spawn_multiple: MPMD spawn — one child JOB whose world
    concatenates the command blocks (ranks 0..maxprocs[0]-1 run
    commands[0], the next maxprocs[1] run commands[1], …).  Realized by
    launching the job under a dispatch shim that execs each rank's argv
    from a table in the environment — the child world is a single job
    exactly as the reference's plm builds it (one orte_job_t, several
    app contexts)."""
    import json

    if len(commands) != len(maxprocs):
        raise MPIException("spawn_multiple: commands/maxprocs mismatch",
                           error_class=2)
    total = int(sum(maxprocs))
    port_name = None
    if comm.rank == 0:
        port_name = open_port()
        child_env = dict(os.environ)
        child_env[ENV_PARENT_PORT] = port_name
        # per-COMMAND envs ride in the rank table (applied by the dispatch
        # shim pre-exec), not the job-wide environment — MPI's
        # spawn_multiple binds env/info to its command block
        table = []
        for i, (argv, n) in enumerate(zip(commands, maxprocs)):
            e = (envs[i] if envs and i < len(envs) else None) or {}
            table += [[list(argv), dict(e)]] * int(n)
        child_env["OMPI_TPU_MPMD_TABLE"] = json.dumps(table)
        cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun",
               *_dvm_submit_args(child_env),
               "-np", str(total), "--", sys.executable, "-m",
               "ompi_tpu.mpi._mpmd_dispatch"]
        proc = subprocess.Popen(cmd, env=child_env)
        _spawned.append(proc)
    try:
        return accept(comm, port_name)
    finally:
        if port_name is not None:
            close_port(port_name)


def get_parent(comm: Communicator) -> Optional[Intercomm]:
    """≈ MPI_Comm_get_parent — in a spawned job, the intercomm to the
    parent; None when not spawned.  Collective over the child world."""
    port = os.environ.get(ENV_PARENT_PORT)
    if not port:
        return None
    return connect(comm, port)
