"""btl/shm — shared-memory transport for same-host ranks.

≈ opal/mca/btl/vader (btl_vader_component.c:61-69): intra-host frames move
through mmap'd SPSC ring buffers instead of TCP loopback — no syscalls per
message, one memcpy into the ring and one out.

Topology: each rank owns an **inbox directory** (under /dev/shm when
available) published in its business card.  A sender's first frame to a
same-host peer creates a ring file in the peer's inbox (atomic rename, the
filesystem is the rendezvous — the role vader's modex-published segment
names play); the receiver's poller discovers it, maps it, and unlinks it
(the mapping stays valid, so teardown is automatic even on crash).

Ring layout (all little-endian, 64B header then the data area)::

    [ head u64 | tail u64 | capacity u64 | magic u32 | pad ]  [ data ... ]

``head``/``tail`` are monotonic byte counters (no wrap ambiguity); the
sender is the only head-writer, the receiver the only tail-writer, so the
SPSC ring needs no cross-process lock — aligned 8-byte stores on x86 (TSO)
give the required store ordering.  The counters are accessed through a
``memoryview.cast("Q")`` so each read/write is one native 8-byte memory
op: ``struct.pack_into("<Q", ...)`` must NOT be used here — CPython packs
explicit-byte-order formats byte-by-byte, and a reader racing those eight
single-byte stores observes a torn counter and walks off the published
region (found the hard way: a ping-pong soak deadlocked on exactly this).
Frames use the same framing as btl/tcp:
``u32 total | u32 hdrlen | dss(header) | payload``.

A frame larger than half the ring raises :class:`FrameTooBig`; the caller
(BtlEndpoint) reroutes that frame over TCP — safe out-of-order because the
PML enforces per-(peer, cid) sequence numbers and rendezvous data frames
are offset-addressed.

Wakeup protocol (the futex-style hybrid vader would use): the poller spins
through a short window, then arms a receiver-owned ``sleep`` flag in every
ring and blocks in ``select`` on a **doorbell FIFO** in its inbox.  A
writer publishes its frame first, then rings the doorbell only if the flag
is armed (plus unconditionally on its first frame, so a sleeping receiver
discovers brand-new rings).  Under load: zero syscalls.  Idle: one write()
per wakeup, kernel-precise like the tcp BTL — which matters on small
hosts, where pure spinning loses the core the sender needs.
"""

from __future__ import annotations

import ctypes
import os
import struct
import tempfile
import threading
import time
from typing import Callable, Optional

from ompi_tpu import _native
from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.mpi import trace as trace_mod

__all__ = ["ShmBTL", "FrameTooBig", "ShmRingWriter", "ShmRingReader"]

_log = output.get_stream("btl")

register_var("btl", "shm_ring_size", VarType.SIZE, 4 << 20,
             "per-(sender,receiver) shared-memory ring capacity in bytes")
register_var("btl", "shm_send_timeout", VarType.SIZE, 60,
             "seconds a full ring blocks a send before the peer is declared "
             "dead (0 = wait forever); a crashed receiver leaves its rings "
             "full, and unlike tcp there is no RST to surface it")
register_var("btl", "shm_spin", VarType.INT, 512,
             "poller idle iterations (GIL-yielding) before arming the "
             "doorbell and sleeping — a wider window keeps ping-pong "
             "latency off the fifo-wake path on multi-core hosts; "
             "ignored (0) on 1-2 core hosts")
register_var("btl", "shm_native", VarType.BOOL, True,
             "fuse header encode + ring publish (and decode + drain) into "
             "one CPython-C-API call per frame (_native/fastdss.c "
             "ring_send/ring_recv — the vader-class native data plane). "
             "An earlier ctypes route measured SLOWER than python (call "
             "marshalling exceeded the work saved); the C-API route wins. "
             "Off, or a failed build, → pure-python framing")


def _native_ring():
    """The compiled frame engine (fastdss module), or None."""
    if not var_registry.get("btl_shm_native"):
        return None
    from ompi_tpu import _native

    return _native.fastdss()


def _native_park_lib():
    """The GIL-released park executor (_native/arena.c), or None.
    Shares the ``btl_shm_native`` gate with the frame engine: both are
    halves of the same native data plane."""
    if not var_registry.get("btl_shm_native"):
        return None
    return _native.arena()


#: ring-base address helper + park spin burst, shared with the arena
#: executor (_native.addr_of / _native.PARK_SPINS — small hosts park
#: with NO spin burst, like the python spin window already did)
_mv_addr = _native.addr_of
_PARK_SPINS = _native.PARK_SPINS
#: one park slice: the cadence at which the poller re-checks stop/pull
#: state and a blocked writer re-checks its send timeout
_PARK_SLICE_NS = 1_000_000

_HDR = 64                 # ring header bytes
_OFF_HEAD, _OFF_TAIL, _OFF_CAP, _OFF_MAGIC = 0, 8, 16, 24
_OFF_SLEEP = 32           # receiver-owned: 1 ⇒ ring my doorbell on publish
_MAGIC = 0x53484D31       # "SHM1"

OnFrame = Callable[[int, dict, bytes], None]


class FrameTooBig(Exception):
    """Frame exceeds the ring's single-frame limit; send it another way."""


class PeerDeadError(ConnectionError):
    """The ring's receiver process no longer exists — a write would land
    in an orphaned mapping and vanish 'successfully'.  Surfaced instead
    of silently losing the frame (the respawn/retransmit path needs to
    KNOW; ≈ the RST a dead tcp peer would produce)."""


def _shm_dir() -> Optional[str]:
    return "/dev/shm" if os.path.isdir("/dev/shm") else None


class ShmRingWriter:
    """The sender's end: creates the ring file and appends frames."""

    def __init__(self, inbox: str, my_id: int, capacity: int) -> None:
        from ompi_tpu.core import shmseg

        capacity = (capacity + 7) & ~7      # counter view needs 8B multiple
        self.capacity = capacity
        # segment lifecycle rides the generic shmem framework
        # (≈ opal/mca/shmem/mmap), UNPUBLISHED until the ring header is
        # initialized: the receiver's inbox scan must never observe a
        # ring without its magic/capacity in place
        self._seg = shmseg.create(f"ring_{my_id}", _HDR + capacity,
                                  dir=inbox, publish=False)
        self._mm = self._seg.buf
        # counters as a u64 view: single native load/store per access
        self._ctr = self._mm[:_HDR].cast("Q")
        self._ctr[_OFF_CAP // 8] = capacity
        struct.pack_into("<I", self._mm, _OFF_MAGIC, _MAGIC)
        self._seg.publish()       # ring header complete: now visible
        self._head = 0            # local mirror: we are the only writer
        self._ctr_addr = _mv_addr(self._mm)   # native backpressure park
        self._lock = threading.Lock()
        self._db_fd: Optional[int] = None   # receiver's doorbell FIFO
        self._first = True
        self._fast = _native_ring()
        try:
            self._db_fd = os.open(os.path.join(inbox, "doorbell"),
                                  os.O_WRONLY | os.O_NONBLOCK)
        except OSError:
            pass   # no doorbell (older inbox / test rig): receiver spins

    def _frame(self, header: dict, payload: bytes):
        hdr = dss.pack(header)
        body = struct.pack("<II", len(hdr) + len(payload), len(hdr))
        need = 8 + len(hdr) + len(payload)
        if need > self.capacity // 2:
            raise FrameTooBig(f"{need}B frame vs {self.capacity}B ring")
        return body, hdr, need

    def _publish(self, body, hdr, payload) -> None:
        """Write one frame and publish it (call with self._lock held and
        space verified)."""
        self._write(body)
        self._write(hdr)
        if payload:
            self._write(payload)
        # publish AFTER the data is in place (x86 TSO store order)
        self._ctr[_OFF_HEAD // 8] = self._head
        self._ring_doorbell(bool(self._ctr[_OFF_SLEEP // 8]))

    @staticmethod
    def _check_send_timeout(waited: float, timeout: float) -> None:
        """A receiver that died without close() leaves the ring full
        forever — the timeout surfaces that as an error (the tcp path
        gets the equivalent from the kernel via RST)."""
        if timeout and waited > timeout:
            raise ConnectionError(
                f"btl/shm: ring full for {waited:.0f}s — receiver "
                f"appears dead (btl_shm_send_timeout)")

    @classmethod
    def _backoff(cls, waited: float, delay: float, timeout: float
                 ) -> tuple[float, float]:
        """One backpressure tick: the receiver is behind; yield then
        sleep, bounded."""
        cls._check_send_timeout(waited, timeout)
        time.sleep(delay)
        return waited + delay, min(delay + 2e-5, 1e-3)

    def _wait_space(self, waited: float, delay: float, timeout: float
                    ) -> tuple[float, float]:
        """One backpressure park: GIL-released native wait for the
        receiver's tail counter to move at all (the caller's loop
        re-checks whether the freed space suffices), falling back to
        the python yield/sleep tick.  Same timeout contract either
        way."""
        ex = _native_park_lib()
        if ex is None or self._ctr_addr is None:
            return self._backoff(waited, delay, timeout)
        self._check_send_timeout(waited, timeout)
        t0 = time.monotonic()
        ex.ompi_tpu_arena_wait_change(
            self._ctr_addr + _OFF_TAIL, int(self._ctr[_OFF_TAIL // 8]),
            _PARK_SPINS, _PARK_SLICE_NS)
        return waited + (time.monotonic() - t0), delay

    def _ring_doorbell(self, armed: bool) -> None:
        """Wake a sleeping receiver (or announce a brand-new ring: the
        very first frame always rings — a sleeping receiver must
        discover it)."""
        if (self._first or armed) and self._db_fd is not None:
            self._first = False
            try:
                os.write(self._db_fd, b"\x01")
            except (BlockingIOError, BrokenPipeError, OSError):
                pass

    def _send_fast(self, header: dict, payload, block: bool) -> bool:
        """One fused C call per frame: encode the header straight into
        the mapped ring + publish (fastdss.ring_send).  Returns False
        when nonblocking and full; raises FrameTooBig / ConnectionError
        like the python path.  Headers the C codec cannot encode fall
        back to the python framing (wire format is identical)."""
        fast = self._fast
        fallback = False
        with self._lock:
            delay, waited = 0.0, 0.0
            timeout = float(var_registry.get("btl_shm_send_timeout") or 0)
            while True:
                try:
                    self._head, ring_db = fast.ring_send(
                        self._mm, self._head, header, payload)
                except fast.RingFull:
                    if not block:
                        return False
                    waited, delay = self._wait_space(waited, delay,
                                                     timeout)
                    continue
                except fast.Unsupported:
                    fallback = True   # exotic header: python framing,
                    break             # OUTSIDE the (non-reentrant) lock
                except fast.FrameTooBig as e:
                    raise FrameTooBig(str(e)) from None
                break
        if fallback:
            return self._send_py(header, payload, block)
        self._ring_doorbell(bool(ring_db))
        return True

    def _send_py(self, header: dict, payload, block: bool) -> bool:
        body, hdr, need = self._frame(header, payload)
        with self._lock:
            delay, waited = 0.0, 0.0
            timeout = float(var_registry.get("btl_shm_send_timeout") or 0)
            while True:
                tail = self._ctr[_OFF_TAIL // 8]
                if self._head - tail + need <= self.capacity:
                    break
                if not block:
                    return False
                waited, delay = self._wait_space(waited, delay, timeout)
            self._publish(body, hdr, payload)
        return True

    def send(self, header: dict, payload) -> None:
        """Deliver one frame.  ``payload`` is any bytes-like object —
        a zero-copy memoryview of the sender's user buffer (the PML's
        plan-collapsed fast path) is published straight into the ring:
        the ONE copy on the whole send path is the ring write itself."""
        if self._fast is not None:
            self._send_fast(header, payload, block=True)
        else:
            self._send_py(header, payload, block=True)

    def try_send_eager(self, tag: int, cid: int, seq: int, dt: str,
                       elems: int, shp: tuple, payload) -> bool:
        """Nonblocking plain-eager publish with the header BUILT IN C
        (fastdss.ring_send_fast) — no dict, no python codec; the
        receiver's engine fast-scans the same seven fields.  False when
        the ring is full NOW (caller falls back to the header path);
        requires the native engine (callers check)."""
        with self._lock:
            try:
                self._head, ring_db = self._fast.ring_send_fast(
                    self._mm, self._head, tag, cid, seq, dt, elems, shp,
                    payload)
            except self._fast.RingFull:
                return False
        self._ring_doorbell(bool(ring_db))
        return True

    def try_send(self, header: dict, payload) -> bool:
        """Nonblocking send (≈ btl sendi, btl.h:926): publish the frame iff
        the ring has room NOW; False ⇒ the caller takes the queued path.
        Still raises FrameTooBig for frames no amount of draining fits.
        ``payload`` may be any bytes-like object (see :meth:`send`)."""
        if self._fast is not None:
            return self._send_fast(header, payload, block=False)
        return self._send_py(header, payload, block=False)

    def _write(self, data) -> None:
        data = memoryview(data).cast("B")
        pos = self._head % self.capacity
        first = min(len(data), self.capacity - pos)
        self._mm[_HDR + pos:_HDR + pos + first] = data[:first]
        if first < len(data):
            self._mm[_HDR:_HDR + len(data) - first] = data[first:]
        self._head += len(data)

    def close(self) -> None:
        if self._db_fd is not None:
            try:
                os.close(self._db_fd)
            except OSError:
                pass
            self._db_fd = None
        try:
            self._ctr.release()
        except (BufferError, ValueError):
            pass
        self._seg.detach()


class ShmRingReader:
    """The receiver's end: maps a discovered ring and drains frames."""

    def __init__(self, path: str, peer: int) -> None:
        from ompi_tpu.core import shmseg

        self.peer = peer
        self._seg = shmseg.attach(path)
        self._mm = self._seg.buf
        if struct.unpack_from("<I", self._mm, _OFF_MAGIC)[0] != _MAGIC:
            self._seg.detach()
            raise OSError(f"bad ring magic in {path}")
        self._ctr = self._mm[:_HDR].cast("Q")
        self.capacity = self._ctr[_OFF_CAP // 8]
        self._tail = self._ctr[_OFF_TAIL // 8]
        self._seg.unlink()  # mapping survives; crash cleanup is automatic
        self._fast = _native_ring()
        self._ctr_addr = _mv_addr(self._mm)   # head word the park watches

    def poll(self, on_frame: OnFrame, limit: int = 64) -> int:
        """Drain up to ``limit`` frames; returns how many were delivered."""
        fast = self._fast
        n = 0
        while fast is not None and n < limit:
            # fused decode: header is unpacked straight from the mapped
            # ring (fastdss.ring_recv), tail release-stored in C
            try:
                out = fast.ring_recv(self._mm, self._tail)
            except fast.Unsupported:
                # a header tag only the python codec knows: drain the
                # rest of this batch through the python path
                fast = None
                break
            except ValueError as e:
                # corrupt frame: the C decoder did NOT advance the tail
                # (nothing trustworthy to advance by) — retrying would
                # livelock on the same bytes forever.  The stream is
                # unrecoverable; discard everything published and
                # surface the fault loudly (the python path would have
                # decoded garbage instead — this is the stricter cure).
                head = int(self._ctr[_OFF_HEAD // 8])
                dropped = head - self._tail
                self._tail = head
                self._ctr[_OFF_TAIL // 8] = self._tail
                raise OSError(
                    f"btl/shm: corrupt ring from peer {self.peer} "
                    f"({e}); {dropped} pending bytes discarded") from None
            if out is None:
                return n
            header, payload, self._tail = out
            on_frame(self.peer, header, payload)
            n += 1
        if n >= limit:
            return n
        while n < limit:
            head = self._ctr[_OFF_HEAD // 8]
            avail = head - self._tail
            if avail == 0 or avail > self.capacity:
                # nothing published (or a state no sane writer produces —
                # never walk past the published region)
                break
            total, hdr_len = struct.unpack("<II", self._read(8))
            blob = self._read(total)
            header = dss.unpack(blob[:hdr_len], n=1)[0]
            on_frame(self.peer, header, blob[hdr_len:])
            self._ctr[_OFF_TAIL // 8] = self._tail
            n += 1
        return n

    def _read(self, n: int) -> bytes:
        pos = self._tail % self.capacity
        first = min(n, self.capacity - pos)
        # bytes() copy: _mm is a memoryview into the live ring — the
        # returned data must own its bytes (the slot is recycled once the
        # tail advances)
        out = bytes(self._mm[_HDR + pos:_HDR + pos + first])
        if first < n:
            out += bytes(self._mm[_HDR:_HDR + (n - first)])
        self._tail += n
        return out

    def has_data(self) -> bool:
        avail = self._ctr[_OFF_HEAD // 8] - self._tail
        return 0 < avail <= self.capacity

    def set_sleeping(self, flag: bool) -> None:
        self._ctr[_OFF_SLEEP // 8] = 1 if flag else 0

    def close(self) -> None:
        try:
            self._ctr.release()
        except (BufferError, ValueError):
            pass
        self._seg.detach()


class ShmBTL:
    """Shared-memory BTL: one inbox dir per rank, lazy per-pair rings."""

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        self.rank = rank
        self.on_frame = on_frame
        # OMPI_TPU_FAKE_HOST gives ranks a simulated host identity (set by
        # the sim plm): ranks on different sim-hosts must NOT shm-reach
        # each other, so the cross-host data path runs for real in tests
        from ompi_tpu.core.sysinfo import host_identity

        self.hostname = host_identity()
        self.inbox = tempfile.mkdtemp(prefix="otpu-shm-", dir=_shm_dir())
        os.mkfifo(os.path.join(self.inbox, "doorbell"))
        # read end first (a writer's nonblocking open needs a reader)
        self._db_fd = os.open(os.path.join(self.inbox, "doorbell"),
                              os.O_RDONLY | os.O_NONBLOCK)
        self._writers: dict[int, ShmRingWriter] = {}
        self._readers: dict[int, ShmRingReader] = {}
        # optional fused drain: reader → frames-delivered, installed by
        # the PML when its compiled matching engine is live.  When set,
        # EVERY ring read goes through it (the hook serializes reads
        # under the PML lock, which also lets a blocked receiver drain
        # its own rings — receiver-pull progress)
        self.drain_hook = None
        # >0 ⇒ a blocked receiver is actively pulling: the poller backs
        # off (sleep, don't spin) instead of fighting the waiter for the
        # GIL and the PML lock on every frame
        self.pull_depth = 0
        self._peer_pid: dict[int, Optional[int]] = {}
        self._alive_until: dict[int, float] = {}   # liveness-probe cache
        self._unreachable: set[int] = set()
        self._alias: dict[int, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # spinning only pays when the sender runs on another core; on a
        # 1-2 core host every spin iteration steals the sender's quantum
        self._spin = (int(var_registry.get("btl_shm_spin") or 0)
                      if (os.cpu_count() or 1) > 2 else 0)
        self._poller = threading.Thread(
            target=self._poll_loop, name=f"btl-shm-{rank}", daemon=True)
        self._poller.start()

    @property
    def address(self) -> str:
        """The business-card fragment: host identity + inbox + pid (the
        pid lets writers detect a dead receiver — an orphaned ring accepts
        writes 'successfully' forever)."""
        return f"{self.hostname}|{self.inbox}|{os.getpid()}"

    def set_alias(self, peer: int, my_id: int) -> None:
        with self._lock:
            self._alias[peer] = my_id

    @staticmethod
    def _parse_card(card: str) -> tuple[str, str, Optional[int]]:
        parts = card.split("|")
        host, inbox = parts[0], parts[1] if len(parts) > 1 else ""
        pid = int(parts[2]) if len(parts) > 2 and parts[2].isdigit() else None
        return host, inbox, pid

    def can_reach(self, card: str) -> bool:
        """Same host (by name) and the inbox is visible on my filesystem —
        ≈ the BTL reachability query (btl.h add_procs) vader answers with
        same-node-ness."""
        host, inbox, _ = self._parse_card(card)
        return host == self.hostname and os.path.isdir(inbox)

    def connect(self, peer: int, card: str) -> bool:
        """Create my ring in the peer's inbox; False ⇒ use another BTL."""
        with self._lock:
            if peer in self._writers:
                return True
            if peer in self._unreachable:
                return False
            if not self.can_reach(card):
                self._unreachable.add(peer)
                return False
            my_id = self._alias.get(peer, self.rank)
            host, inbox, pid = self._parse_card(card)
            try:
                self._writers[peer] = ShmRingWriter(
                    inbox, my_id,
                    int(var_registry.get("btl_shm_ring_size")))
            except OSError as e:
                _log.verbose(1, "btl/shm: cannot reach %d (%s); tcp fallback",
                             peer, e)
                self._unreachable.add(peer)
                return False
            self._peer_pid[peer] = pid
            return True

    def probe_alive(self, peer: int,
                    card: Optional[str] = None) -> Optional[bool]:
        """Pid-liveness probe, time-bounded and cache-SHARED with the
        send path (``_check_alive``): the kill(2) syscall runs at most
        once per peer per 50ms no matter how many layers ask.  ``card``
        (the peer's shm business-card segment) supplies the pid when no
        ring was ever connected — the coll/shm arena probes writers it
        may never have exchanged a PML frame with.  Returns None when the
        pid is unknowable, True/False otherwise."""
        pid = self._peer_pid.get(peer)
        if pid is None and card:
            host, _inbox, cpid = self._parse_card(card)
            if host == self.hostname and cpid is not None:
                # a different host's pid namespace would alias — only a
                # same-host card's pid is probeable
                pid = cpid
                self._peer_pid.setdefault(peer, pid)
        if pid is None:
            return None
        if pid == os.getpid():
            return True
        now = time.monotonic()
        if now < self._alive_until.get(peer, 0.0):
            return True
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            pass   # alive under another uid
        self._alive_until[peer] = now + 0.05
        return True

    def _check_alive(self, peer: int) -> None:
        """Send-path arm of the probe: raise instead of answering (death
        detection is delayed by at most the cache bound — the park/heal
        layer absorbs that)."""
        if self.probe_alive(peer) is False:
            raise PeerDeadError(
                f"btl/shm: rank {peer} (pid {self._peer_pid.get(peer)}) "
                f"is gone — dropping the orphaned ring") from None

    def drop_peer(self, peer: int) -> None:
        """Forget a peer's (stale) ring so the next send reconnects from
        its current card (respawn/rebind path)."""
        with self._lock:
            self._unreachable.discard(peer)
            self._peer_pid.pop(peer, None)
            self._alive_until.pop(peer, None)
            w = self._writers.pop(peer, None)
        if w is not None:
            w.close()

    def _trace_publish(self, peer: int, payload) -> None:
        """Counter + instant for a frame that DID enter a ring — called
        only after a successful publish, so the pvar never counts frames
        a FrameTooBig/dead-peer failure kept out."""
        trace_mod.count("btl_shm_publish_total")
        if trace_mod.active:
            trace_mod.instant("btl", "shm_publish", rank=self.rank,
                              peer=peer, nbytes=len(payload))

    def send(self, peer: int, header: dict, payload=b"") -> None:
        """Deliver one frame (``payload``: any bytes-like, zero-copy
        buffer views included); raises FrameTooBig for oversized frames,
        PeerDeadError for a dead receiver, and KeyError if connect() was
        never called for this peer."""
        self._check_alive(peer)
        self._writers[peer].send(header, payload)
        self._trace_publish(peer, payload)

    def try_send(self, peer: int, header: dict, payload=b"") -> bool:
        """Nonblocking delivery on the caller's thread; False when the
        ring is full or unconnected (caller falls back to the send
        worker).  FrameTooBig/PeerDeadError propagate — no queueing fixes
        those."""
        w = self._writers.get(peer)
        if w is None:
            return False
        self._check_alive(peer)
        if not w.try_send(header, payload):
            return False
        self._trace_publish(peer, payload)
        return True

    def try_send_eager(self, peer: int, tag: int, cid: int, seq: int,
                      dt: str, elems: int, shp: tuple, payload) -> bool:
        """Header-free eager publish (see ShmRingWriter.try_send_eager);
        False ⇒ unconnected / no native engine / ring full."""
        w = self._writers.get(peer)
        if w is None or w._fast is None:
            return False
        self._check_alive(peer)
        if not w.try_send_eager(tag, cid, seq, dt, elems, shp, payload):
            return False
        self._trace_publish(peer, payload)
        return True

    # -- receive side ------------------------------------------------------

    def _scan_inbox(self) -> int:
        """Attach newly appeared rings; returns how many were attached."""
        try:
            names = os.listdir(self.inbox)
        except OSError:
            return 0
        attached = 0
        for name in names:
            if not name.startswith("ring_"):
                continue
            try:
                peer = int(name.split("_", 1)[1])
            except ValueError:
                continue
            path = os.path.join(self.inbox, name)
            try:
                reader = ShmRingReader(path, peer)
            except OSError:
                continue
            with self._lock:
                self._readers[peer] = reader
            attached += 1
        return attached

    def _poll_loop(self) -> None:
        import select

        idle = 0
        last_scan = time.monotonic()
        while not self._stop.is_set():
            if self.pull_depth:
                # a blocked receiver is draining on its own thread —
                # stay out of its way (it covers every frame, punts
                # included); wake periodically for new-ring discovery
                time.sleep(0.002)
                self._scan_inbox()
                idle = 0
                continue
            with self._lock:
                readers = list(self._readers.values())
            n = 0
            hook = self.drain_hook
            for r in readers:
                try:
                    # NOTE: an exception out of on_frame consumes the frame
                    # (tail already advanced) — same loss semantics as a tcp
                    # reader thread dying mid-delivery; the log below is the
                    # only trace, so keep it loud
                    if hook is not None:
                        n += hook(r)   # fused drain traces in the PML
                    else:
                        _t0 = (trace_mod.begin()
                               if trace_mod.active
                               or trace_mod.hist_active else 0)
                        got = r.poll(self.on_frame)
                        if got:
                            trace_mod.count("btl_shm_drained_total", got)
                            if _t0 and trace_mod.hist_active:
                                trace_mod.record_hist(
                                    "btl_shm_drain_ns",
                                    time.monotonic_ns() - _t0)
                            if _t0 and trace_mod.active:
                                trace_mod.complete(
                                    "btl", "shm_drain", _t0,
                                    rank=self.rank, peer=r.peer,
                                    frames=got)
                        n += got
                except Exception as e:   # a bad frame must not kill polling
                    _log.error("btl/shm poll from %d failed: %r", r.peer, e)
            if n:
                idle = 0
                # sustained traffic must not starve new-peer discovery: a
                # fresh ring's doorbell is only read while sleeping
                if time.monotonic() - last_scan > 0.05:
                    self._scan_inbox()
                    last_scan = time.monotonic()
                continue
            idle += 1
            parked = self._native_park(readers)
            if parked is not None:
                if parked:
                    # a head moved during the GIL-released park: drain
                    # immediately (the whole idle window ran without
                    # touching the interpreter once)
                    trace_mod.count("btl_shm_native_drains_total")
                    idle = 0
                    continue
                # slice expired with nothing published: fall through to
                # the doorbell arm (kernel-precise idle, zero CPU)
            elif idle <= self._spin:   # spin window: drain bursts cheaply
                time.sleep(0)
                continue
            # arm the doorbell: set every ring's sleep flag, re-check for
            # frames published between the flag store and now (classic
            # missed-wakeup guard), then block on the FIFO.  A ring that
            # appeared during the scan counts as a wakeup too — it is not
            # in the armed snapshot, so its doorbell was already consumed
            # (or never sent) and sleeping on it would strand its frames
            # until the select timeout.
            for r in readers:
                r.set_sleeping(True)
            last_scan = time.monotonic()
            if self._scan_inbox() or any(r.has_data() for r in readers):
                for r in readers:
                    r.set_sleeping(False)
                idle = 0
                continue
            try:
                select.select([self._db_fd], [], [], 0.05)
                while True:       # drain accumulated doorbell bytes
                    try:
                        if not os.read(self._db_fd, 4096):
                            break
                    except BlockingIOError:
                        break
            except OSError:
                pass
            for r in readers:
                r.set_sleeping(False)
            idle = 0

    def _native_park(self, readers) -> Optional[bool]:
        """One GIL-released park across every attached ring's head
        counter (a time.sleep(0) spin here fights every other thread
        for the interpreter — the exact interference ROADMAP item 1
        measured).  True ⇒ some ring published during the park, False
        ⇒ slice expired idle, None ⇒ no native executor (python spin
        window applies)."""
        ex = _native_park_lib()
        if ex is None or not readers:
            return None
        n = len(readers)
        ctrs = (ctypes.c_void_p * n)()
        tails = (ctypes.c_uint64 * n)()
        for i, r in enumerate(readers):
            if r._ctr_addr is None:
                return None
            ctrs[i] = r._ctr_addr
            tails[i] = r._tail
        got = ex.ompi_tpu_ring_wait_any(
            ctypes.addressof(ctrs), ctypes.addressof(tails), n,
            _PARK_SPINS, _PARK_SLICE_NS)
        return got >= 0

    def reader_list(self) -> list["ShmRingReader"]:
        """Snapshot of the attached rings (receiver-pull callers)."""
        with self._lock:
            return list(self._readers.values())

    def close(self) -> None:
        self._stop.set()
        self._poller.join(timeout=2.0)
        with self._lock:
            for w in self._writers.values():
                w.close()
            for r in self._readers.values():
                r.close()
            self._writers.clear()
            self._readers.clear()
        try:
            os.close(self._db_fd)
        except OSError:
            pass
        try:
            for name in os.listdir(self.inbox):
                os.unlink(os.path.join(self.inbox, name))
            os.rmdir(self.inbox)
        except OSError:
            pass
