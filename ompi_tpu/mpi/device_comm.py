"""DeviceCommunicator — the coll/xla + btl/tpu path: collectives on
HBM-resident buffers, lowered to XLA collectives over an ICI mesh.

This is BASELINE.json's north star realized TPU-first.  Where the reference
stages device buffers through host bounce buffers and runs the CPU algorithms
(ompi/mca/coll/cuda/coll_cuda_allreduce.c:30-69), here a communicator IS a
set of mesh axes: its collectives trace to ``lax.psum`` / ``psum_scatter`` /
``all_gather`` / ``all_to_all`` / ``ppermute``, compile into the surrounding
jit program, and move data purely over ICI with zero host copies.  "Ranks"
are devices; a sub-communicator is a subset of mesh axes (so comm "split by
color" along hardware dimensions costs nothing — it is how the mesh is
addressed).

Two usage modes:

- **traced** (the hot path): call the methods inside ``shard_map``/``jit``
  over the communicator's axes.  Everything is compiled; XLA overlaps and
  fuses the collectives with surrounding compute.
- **driver**: ``comm.run(fn, *arrays)`` wraps ``shard_map`` with
  fully-sharded in/out specs for quick use and tests.

The host algorithm inventory maps as (SURVEY.md §2.6):
  allreduce ring/recursive-doubling → psum (XLA picks the ICI algorithm)
  reduce_scatter ring               → psum_scatter
  allgather bruck/ring              → all_gather
  alltoall pairwise                 → all_to_all
  sendrecv ring shifts              → ppermute
  barrier                           → optimization_barrier + ppermute token
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ompi_tpu.mpi.constants import MPIException
from ompi_tpu.mpi.op import MAX, MIN, SUM, Op

__all__ = ["DeviceCommunicator", "device_world"]


class DeviceCommunicator:
    """A communicator over one or more mesh axes.

    ``axes`` is an ordered tuple of axis names; the rank is the row-major
    flat index over those axes (matching MPI rank order for a cartesian
    communicator, ≈ MPI_Cart_create semantics).
    """

    def __init__(self, mesh, axes: Optional[Sequence[str]] = None,
                 name: str = "device") -> None:
        import jax

        self.mesh = mesh
        self.axes: tuple[str, ...] = tuple(axes if axes is not None
                                           else mesh.axis_names)
        for ax in self.axes:
            if ax not in mesh.axis_names:
                raise MPIException(f"axis {ax!r} not in mesh {mesh.axis_names}")
        self.name = name
        self._jax = jax

    # -- shape -------------------------------------------------------------

    @property
    def size(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.axes]))

    @property
    def axis_sizes(self) -> tuple[int, ...]:
        return tuple(int(self.mesh.shape[a]) for a in self.axes)

    def rank(self):
        """Traced: my flat rank over the axes (row-major)."""
        from jax import lax

        r = lax.axis_index(self.axes[0])
        for ax in self.axes[1:]:
            r = r * self.mesh.shape[ax] + lax.axis_index(ax)
        return r

    def coords(self):
        """Traced: my coordinates along each axis (≈ MPI_Cart_coords)."""
        from jax import lax

        return tuple(lax.axis_index(ax) for ax in self.axes)

    def sub(self, axes: Sequence[str], name: Optional[str] = None
            ) -> "DeviceCommunicator":
        """Sub-communicator over a subset of my axes (≈ MPI_Cart_sub: free
        the other dimensions). Zero-cost: just re-addresses the mesh."""
        return DeviceCommunicator(self.mesh, axes,
                                  name or f"{self.name}.sub{tuple(axes)}")

    @property
    def _ax(self):
        """Axis argument for lax collectives (name or tuple of names)."""
        return self.axes if len(self.axes) > 1 else self.axes[0]

    # -- collectives (traced) ---------------------------------------------

    def allreduce(self, x, op: Op = SUM):
        """≈ MPI_Allreduce → fused XLA collective (psum/pmax/pmin), falling
        back to all_gather + ordered tree fold for ops without one."""
        from jax import lax

        if op is SUM or op.jax_reduce_name == "psum":
            return lax.psum(x, self._ax)
        if op is MAX:
            return lax.pmax(x, self._ax)
        if op is MIN:
            return lax.pmin(x, self._ax)
        return self._allreduce_generic(x, op)

    def _allreduce_generic(self, x, op: Op):
        """Any associative op: all_gather then rank-ordered fold (compiled;
        fine for small payloads, which is what exotic ops are in practice)."""
        import jax.numpy as jnp

        from jax import lax

        stacked = lax.all_gather(x, self._ax, tiled=False)
        stacked = stacked.reshape((self.size,) + x.shape)
        # rank-ordered left fold (MPI's non-commutative contract)
        acc = stacked[0]
        for r in range(1, self.size):
            acc = op.device(acc, stacked[r])
        return acc

    def reduce(self, x, op: Op = SUM, root: int = 0):
        """≈ MPI_Reduce. SPMD note: every device computes the value (psum is
        already allreduce on ICI); non-roots receive zeros to keep the MPI
        shape contract while letting XLA DCE unused branches."""
        import jax.numpy as jnp

        full = self.allreduce(x, op)
        return jnp.where(self.rank() == root, full,
                         jnp.zeros_like(full))

    def bcast(self, x, root: int = 0):
        """≈ MPI_Bcast: select root's contribution via masked psum."""
        import jax.numpy as jnp

        from jax import lax

        contrib = jnp.where(self.rank() == root, x, jnp.zeros_like(x))
        return lax.psum(contrib, self._ax)

    def reduce_scatter(self, x, op: Op = SUM, axis: int = 0):
        """≈ MPI_Reduce_scatter → psum_scatter (the ring lives in XLA/ICI)."""
        from jax import lax

        if op is not SUM:
            # psum_scatter is sum-only; generic path reduces then slices
            full = self.allreduce(x, op)
            return _my_block(self, full, axis)
        return lax.psum_scatter(x, self._ax, scatter_dimension=axis,
                                tiled=True)

    def allgather(self, x, axis: int = 0):
        """≈ MPI_Allgather → all_gather, concatenated along `axis`."""
        from jax import lax

        return lax.all_gather(x, self._ax, axis=axis, tiled=True)

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        """≈ MPI_Alltoall → all_to_all over the axes."""
        from jax import lax

        return lax.all_to_all(x, self._ax, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)

    def gather(self, x, root: int = 0, axis: int = 0):
        """≈ MPI_Gather: allgather + zero on non-roots (see reduce note)."""
        import jax.numpy as jnp

        full = self.allgather(x, axis=axis)
        return jnp.where(self.rank() == root, full, jnp.zeros_like(full))

    def scatter(self, x, root: int = 0, axis: int = 0):
        """≈ MPI_Scatter: bcast root's buffer, slice my block."""
        return _my_block(self, self.bcast(x, root), axis)

    def scan(self, x, op: Op = SUM):
        """≈ MPI_Scan (inclusive prefix): allgather + masked ordered fold."""
        import jax.numpy as jnp

        from jax import lax

        stacked = lax.all_gather(x, self._ax, tiled=False)
        stacked = stacked.reshape((self.size,) + x.shape)
        if op is SUM:
            prefix = jnp.cumsum(stacked, axis=0)
            return prefix[self.rank()]
        acc = stacked[0]
        outs = [acc]
        for r in range(1, self.size):
            acc = op.device(acc, stacked[r])
            outs.append(acc)
        return jnp.stack(outs)[self.rank()]

    def barrier(self, token=None):
        """SPMD barrier: a zero-byte psum forces cross-device sync ordering.
        Returns a token to thread through data dependencies."""
        import jax.numpy as jnp

        from jax import lax

        t = token if token is not None else jnp.zeros((), jnp.int32)
        return lax.psum(t, self._ax) * 0

    # -- point-to-point as permutation (the TPU-native shape of send/recv) -

    def shift(self, x, displacement: int = 1, axis: Optional[str] = None):
        """Cyclic ring shift (≈ MPI_Cart_shift + Sendrecv): every device
        sends to (i+displacement) mod n along `axis` → one ICI hop."""
        from jax import lax

        ax = axis or self.axes[-1]
        n = self.mesh.shape[ax]
        perm = [(i, (i + displacement) % n) for i in range(n)]
        return lax.ppermute(x, ax, perm)

    def permute(self, x, perm: Sequence[tuple[int, int]],
                axis: Optional[str] = None):
        """General (src, dst) permutation → lax.ppermute. Pairs not covered
        receive zeros (lax semantics; matches one-sided put into a zeroed
        window)."""
        from jax import lax

        return lax.ppermute(x, axis or self.axes[-1], list(perm))

    def sendrecv(self, x, dest_disp: int, source_disp: Optional[int] = None,
                 axis: Optional[str] = None):
        """Cyclic exchange by *displacement* (SPMD: every device passes the
        same arguments, so peers are displacements, not absolute ranks —
        exactly MPI_Cart_shift + MPI_Sendrecv semantics).  ``source_disp``,
        if given, must be the matching -dest_disp pattern; anything else is
        not a permutation and is rejected."""
        from jax import lax

        ax = axis or self.axes[-1]
        n = int(self.mesh.shape[ax])
        off = dest_disp % n
        if source_disp is not None and (source_disp % n) != (-dest_disp) % n:
            raise MPIException(
                f"sendrecv: source_disp {source_disp} does not match "
                f"dest_disp {dest_disp} (need source ≡ -dest mod {n} for a "
                f"cyclic pattern; use permute() for general patterns)")
        perm = [(i, (i + off) % n) for i in range(n)]
        return lax.ppermute(x, ax, perm)

    # -- driver-mode helper ------------------------------------------------

    def run(self, fn: Callable, *arrays, out_specs: Any = None):
        """Run fn(self, *shards) under shard_map over my axes, splitting each
        input along axis 0. Convenience for tests/small jobs; real programs
        write their own shard_map/jit with explicit specs."""
        import jax
        from jax.sharding import PartitionSpec as P

        axes = self.axes
        spec = P(axes if len(axes) > 1 else axes[0])
        in_specs = tuple(spec for _ in arrays)
        out_sp = out_specs if out_specs is not None else spec

        @functools.partial(
            jax.shard_map, mesh=self.mesh, in_specs=in_specs,
            out_specs=out_sp, check_vma=False)
        def shmapped(*shards):
            return fn(self, *shards)

        return jax.jit(shmapped)(*arrays)

    def __repr__(self) -> str:
        return (f"DeviceCommunicator({self.name}, axes={self.axes}, "
                f"size={self.size})")


def _my_block(comm: DeviceCommunicator, full, axis: int):
    """Slice this rank's equal block along `axis` (traced)."""
    from jax import lax

    n = comm.size
    if full.shape[axis] % n:
        raise MPIException(
            f"dimension {axis} ({full.shape[axis]}) not divisible by "
            f"communicator size {n}")
    block = full.shape[axis] // n
    start = comm.rank() * block
    sizes = list(full.shape)
    sizes[axis] = block
    starts = [0] * full.ndim
    starts[axis] = start
    return lax.dynamic_slice(full, starts, sizes)


def device_world(mesh=None, axes=None) -> DeviceCommunicator:
    """The device-side COMM_WORLD: all chips of the mesh (default: one mesh
    over every local device)."""
    if mesh is None:
        import jax
        from jax.sharding import Mesh

        devs = np.array(jax.devices())
        mesh = Mesh(devs, axis_names=("world",))
    return DeviceCommunicator(mesh, axes, name="DEVICE_WORLD")
