"""DeviceCommunicator — the coll/xla + btl/tpu path: collectives on
HBM-resident buffers, lowered to XLA collectives over an ICI mesh.

This is BASELINE.json's north star realized TPU-first.  Where the reference
stages device buffers through host bounce buffers and runs the CPU algorithms
(ompi/mca/coll/cuda/coll_cuda_allreduce.c:30-69), here a communicator IS a
set of mesh axes: its collectives trace to ``lax.psum`` / ``psum_scatter`` /
``all_gather`` / ``all_to_all`` / ``ppermute``, compile into the surrounding
jit program, and move data purely over ICI with zero host copies.  "Ranks"
are devices; a sub-communicator is a subset of mesh axes (so comm "split by
color" along hardware dimensions costs nothing — it is how the mesh is
addressed).

Two usage modes:

- **traced** (the hot path): call the methods inside ``shard_map``/``jit``
  over the communicator's axes.  Everything is compiled; XLA overlaps and
  fuses the collectives with surrounding compute.
- **driver**: ``comm.run(fn, *arrays)`` wraps ``shard_map`` with
  fully-sharded in/out specs for quick use and tests.

The host algorithm inventory maps as (SURVEY.md §2.6):
  allreduce ring/recursive-doubling → psum (XLA picks the ICI algorithm)
  reduce_scatter ring               → psum_scatter
  allgather bruck/ring              → all_gather
  alltoall pairwise                 → all_to_all
  sendrecv ring shifts              → ppermute
  barrier                           → optimization_barrier + ppermute token
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ompi_tpu.mpi.constants import MPIException
from ompi_tpu.mpi.op import MAX, MIN, SUM, Op

__all__ = ["DeviceCommunicator", "device_world"]

from ompi_tpu.core.config import VarType, register_var, var_registry

register_var("coll", "device_generic_large_bytes", VarType.SIZE, 1 << 20,
             "per-shard byte size at/above which generic-op device "
             "collectives (allreduce with exotic ops, scan, exscan) use "
             "the O(shard)-memory ppermute prefix forms instead of the "
             "allgather+fold forms (which allocate n x shard on every "
             "device — fine for control payloads, OOM for model-sized "
             "ones; round-3 verdict weak #4)")


class DeviceCommunicator:
    """A communicator over one or more mesh axes.

    ``axes`` is an ordered tuple of axis names; the rank is the row-major
    flat index over those axes (matching MPI rank order for a cartesian
    communicator, ≈ MPI_Cart_create semantics).
    """

    def __init__(self, mesh, axes: Optional[Sequence[str]] = None,
                 name: str = "device") -> None:
        import jax

        self.mesh = mesh
        self.axes: tuple[str, ...] = tuple(axes if axes is not None
                                           else mesh.axis_names)
        for ax in self.axes:
            if ax not in mesh.axis_names:
                raise MPIException(f"axis {ax!r} not in mesh {mesh.axis_names}")
        self.name = name
        self._jax = jax
        # driver-mode compiled-program cache: (method, static args, avals)
        # → jitted callable.  Without it every driver-mode collective pays
        # a fresh shard_map trace + jit dispatch setup (round-2 weak #5).
        self._method_cache: dict = {}

    # -- shape -------------------------------------------------------------

    @property
    def size(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.axes]))

    @property
    def axis_sizes(self) -> tuple[int, ...]:
        return tuple(int(self.mesh.shape[a]) for a in self.axes)

    def rank(self):
        """Traced: my flat rank over the axes (row-major)."""
        from jax import lax

        r = lax.axis_index(self.axes[0])
        for ax in self.axes[1:]:
            r = r * self.mesh.shape[ax] + lax.axis_index(ax)
        return r

    def coords(self):
        """Traced: my coordinates along each axis (≈ MPI_Cart_coords)."""
        from jax import lax

        return tuple(lax.axis_index(ax) for ax in self.axes)

    def sub(self, axes: Sequence[str], name: Optional[str] = None
            ) -> "DeviceCommunicator":
        """Sub-communicator over a subset of my axes (≈ MPI_Cart_sub: free
        the other dimensions). Zero-cost: just re-addresses the mesh."""
        return DeviceCommunicator(self.mesh, axes,
                                  name or f"{self.name}.sub{tuple(axes)}")

    @property
    def _ax(self):
        """Axis argument for lax collectives (name or tuple of names)."""
        return self.axes if len(self.axes) > 1 else self.axes[0]

    # -- collectives (traced) ---------------------------------------------

    def allreduce(self, x, op: Op = SUM):
        """≈ MPI_Allreduce → fused XLA collective (psum/pmax/pmin), falling
        back to all_gather + ordered tree fold for ops without one."""
        from jax import lax

        if op is SUM or op.jax_reduce_name == "psum":
            return lax.psum(x, self._ax)
        if op is MAX:
            return lax.pmax(x, self._ax)
        if op is MIN:
            return lax.pmin(x, self._ax)
        return self._allreduce_generic(x, op)

    def _large(self, x) -> bool:
        """Large enough that n×shard materialization is the wrong plan."""
        try:
            nbytes = int(np.prod(x.shape)) * x.dtype.itemsize
        except Exception:  # noqa: BLE001 — unshaped: treat as small
            return False
        return (len(self.axes) == 1
                and nbytes >= int(
                    var_registry.get("coll_device_generic_large_bytes")))

    def _hillis_scan(self, x, op: Op):
        """Inclusive rank-ordered prefix fold in O(shard) memory:
        ⌈log2 n⌉ ppermute hops (Hillis-Steele).  Valid for any
        associative op — every combine joins two rank-contiguous
        segments left-to-right, so non-commutative ops keep MPI's
        rank-order contract.  The O(shard) dual of the allgather+fold
        forms (which allocate n×shard everywhere)."""
        import jax.numpy as jnp

        from jax import lax

        n = self.size
        ax = self.axes[0]
        me = lax.axis_index(ax)
        acc = x
        d = 1
        while d < n:
            # segment ending at rank me-d slides right by d; ppermute
            # zero-fills ranks with no source, and the mask keeps the
            # prefix of ranks < d untouched
            shifted = lax.ppermute(
                acc, ax, [(i, i + d) for i in range(n - d)])
            acc = jnp.where(me >= d, op.device(shifted, acc), acc)
            d <<= 1
        return acc

    def _allreduce_generic(self, x, op: Op):
        """Any associative op.  Small payloads: all_gather + rank-ordered
        fold (simple, one collective).  Large payloads: the O(shard)
        prefix form — rank n-1's inclusive scan IS the full ordered
        fold; a masked-psum bcast delivers it everywhere."""
        import jax.numpy as jnp

        from jax import lax

        if self._large(x):
            total_on_last = self._hillis_scan(x, op)
            return self.bcast(total_on_last, root=self.size - 1)
        stacked = lax.all_gather(x, self._ax, tiled=False)
        stacked = stacked.reshape((self.size,) + x.shape)
        # rank-ordered left fold (MPI's non-commutative contract)
        acc = stacked[0]
        for r in range(1, self.size):
            acc = op.device(acc, stacked[r])
        return acc

    def reduce(self, x, op: Op = SUM, root: int = 0):
        """≈ MPI_Reduce. SPMD note: every device computes the value (psum is
        already allreduce on ICI); non-roots receive zeros to keep the MPI
        shape contract while letting XLA DCE unused branches."""
        import jax.numpy as jnp

        full = self.allreduce(x, op)
        return jnp.where(self.rank() == root, full,
                         jnp.zeros_like(full))

    def bcast(self, x, root: int = 0):
        """≈ MPI_Bcast: select root's contribution via masked psum."""
        import jax.numpy as jnp

        from jax import lax

        contrib = jnp.where(self.rank() == root, x, jnp.zeros_like(x))
        return lax.psum(contrib, self._ax)

    def reduce_scatter(self, x, op: Op = SUM, axis: int = 0):
        """≈ MPI_Reduce_scatter → psum_scatter (the ring lives in XLA/ICI)."""
        from jax import lax

        if op is not SUM:
            # psum_scatter is sum-only; generic path reduces then slices
            full = self.allreduce(x, op)
            return _my_block(self, full, axis)
        return lax.psum_scatter(x, self._ax, scatter_dimension=axis,
                                tiled=True)

    def allgather(self, x, axis: int = 0):
        """≈ MPI_Allgather → all_gather, concatenated along `axis`."""
        from jax import lax

        return lax.all_gather(x, self._ax, axis=axis, tiled=True)

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        """≈ MPI_Alltoall → all_to_all over the axes."""
        from jax import lax

        return lax.all_to_all(x, self._ax, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)

    def alltoall_stacked(self, x, axis: Optional[str] = None):
        """Leading-dim exchange (tiled=False all_to_all): x's axis 0 must
        equal the mesh axis size; entry j of the result is what device j
        sent me.  The dispatch shape expert/pipeline parallelism uses."""
        from jax import lax

        return lax.all_to_all(x, axis or self.axes[-1], split_axis=0,
                              concat_axis=0, tiled=False)

    def gather(self, x, root: int = 0, axis: int = 0):
        """≈ MPI_Gather: allgather + zero on non-roots (see reduce note).

        Memory contract: the SPMD output is n×shard on EVERY device
        (shard_map outputs are one static shape; the root-only n× buffer
        of host MPI does not exist on this substrate).  For model-sized
        payloads use reduce_scatter/allgather shapes instead — gather is
        a control-plane collective here."""
        import jax.numpy as jnp

        full = self.allgather(x, axis=axis)
        return jnp.where(self.rank() == root, full, jnp.zeros_like(full))

    def scatter(self, x, root: int = 0, axis: int = 0):
        """≈ MPI_Scatter: bcast root's buffer, slice my block."""
        return _my_block(self, self.bcast(x, root), axis)

    def scan(self, x, op: Op = SUM):
        """≈ MPI_Scan (inclusive prefix).  Small: allgather + masked
        ordered fold (one collective).  Large: O(shard)-memory
        Hillis-Steele over ⌈log2 n⌉ ppermute hops."""
        import jax.numpy as jnp

        from jax import lax

        if self._large(x):
            return self._hillis_scan(x, op)
        stacked = lax.all_gather(x, self._ax, tiled=False)
        stacked = stacked.reshape((self.size,) + x.shape)
        if op is SUM:
            prefix = jnp.cumsum(stacked, axis=0)
            return prefix[self.rank()]
        acc = stacked[0]
        outs = [acc]
        for r in range(1, self.size):
            acc = op.device(acc, stacked[r])
            outs.append(acc)
        return jnp.stack(outs)[self.rank()]

    def exscan(self, x, op: Op = SUM):
        """≈ MPI_Exscan (exclusive prefix): rank r gets op-fold of ranks
        < r; rank 0 gets zeros (MPI leaves it undefined — zeros is the
        identity-friendly choice).  Large payloads: the inclusive
        Hillis-Steele prefix shifted right one rank (one extra hop)."""
        import jax.numpy as jnp

        from jax import lax

        if self._large(x):
            incl = self._hillis_scan(x, op)
            n = self.size
            ax = self.axes[0]
            shifted = lax.ppermute(
                incl, ax, [(i, i + 1) for i in range(n - 1)])
            me = lax.axis_index(ax)
            return jnp.where(me == 0, jnp.zeros_like(x), shifted)
        stacked = lax.all_gather(x, self._ax, tiled=False)
        stacked = stacked.reshape((self.size,) + x.shape)
        if op is SUM:
            prefix = jnp.cumsum(stacked, axis=0)
            incl = prefix[self.rank()]
            return incl - x  # exclusive = inclusive − own contribution
        acc = jnp.zeros_like(stacked[0])
        outs = [acc]
        run = stacked[0]
        for r in range(1, self.size):
            outs.append(run)
            run = op.device(run, stacked[r])
        return jnp.stack(outs)[self.rank()]

    # -- alternative algorithm implementations (the decision layer's menu) -

    def allreduce_rs_ag(self, x, op: Op = SUM, axis: Optional[int] = None):
        """Bandwidth-optimal 2-phase allreduce: reduce_scatter then
        all_gather (≈ the reference's ring allreduce,
        coll_base_allreduce.c:339 — same 2·(n-1)/n bytes on the wire,
        expressed as the two XLA collectives so ICI runs both phases).
        Scatters along ``axis`` (default: first n-divisible dim; falls back
        to plain psum when no dim divides — shapes are static, so the
        choice compiles away)."""
        from jax import lax

        if op is not SUM:
            return self.allreduce(x, op)
        n = self.size
        if axis is None:
            axis = next((i for i, d in enumerate(x.shape) if d % n == 0),
                        None)
            if axis is None:
                return self.allreduce(x, op)
        scattered = lax.psum_scatter(x, self._ax, scatter_dimension=axis,
                                     tiled=True)
        return lax.all_gather(scattered, self._ax, axis=axis, tiled=True)

    def allreduce_qint8(self, x, op: Op = SUM, block: int = 256):
        """Quantized 2-phase allreduce (≈ EQuARX, arxiv 2506.17615):
        int8 payloads with per-block f32 scales cut wire bytes ~4×.

        Phase 1 is the reduce-scatter expressed as an all_to_all of
        QUANTIZED chunks — each device dequantizes the n pieces of its
        chunk locally and sums in f32 (int8 representations under
        different scales cannot be summed on the wire).  Phase 2
        re-quantizes the reduced chunk and all_gathers it.  LOSSY
        (~0.2-0.5% rms for gradient-like data): never auto-selected —
        opt-in via ``--mca coll xla_allreduce_algorithm qint8``.
        """
        import jax.numpy as jnp
        from jax import lax

        if op is not SUM:
            return self.allreduce(x, op)
        n = self.size
        flat = x.reshape(-1)
        unit = n * block
        padded = -(-flat.shape[0] // unit) * unit
        if padded != flat.shape[0]:
            flat = jnp.pad(flat, (0, padded - flat.shape[0]))
        chunk = padded // n                       # my phase-1 ownership

        def quant(v):                             # (..., block) blocks
            b = v.reshape(*v.shape[:-1], v.shape[-1] // block, block)
            b32 = b.astype(jnp.float32)
            scale = jnp.max(jnp.abs(b32), axis=-1, keepdims=True) / 127.0
            scale = jnp.where(scale == 0, 1.0, scale)
            q = jnp.clip(jnp.round(b32 / scale), -127, 127).astype(jnp.int8)
            return q, scale

        def dequant(q, scale):
            return (q.astype(jnp.float32) * scale).reshape(
                *q.shape[:-2], q.shape[-2] * block)

        # phase 1: quantized chunks to their owners, local dequant-sum
        q, s = quant(flat.reshape(n, chunk))
        q = lax.all_to_all(q, self._ax, split_axis=0, concat_axis=0,
                           tiled=False)
        s = lax.all_to_all(s, self._ax, split_axis=0, concat_axis=0,
                           tiled=False)
        reduced = dequant(q, s).sum(axis=0)       # (chunk,) f32
        # phase 2: re-quantize the reduced chunk, gather everywhere
        q2, s2 = quant(reduced)
        q2 = lax.all_gather(q2, self._ax, axis=0, tiled=False)
        s2 = lax.all_gather(s2, self._ax, axis=0, tiled=False)
        out = dequant(q2, s2).reshape(-1)[: x.size]
        return out.reshape(x.shape).astype(x.dtype)

    def allreduce_segmented(self, x, op: Op = SUM,
                            segment_elems: int = 1 << 20):
        """Segmented 2-phase allreduce (≈ the reference's segmented ring,
        coll_base_allreduce.c:615): the buffer is processed in fixed
        segments via lax.scan, bounding the per-step collective working
        set — the form very large buffers want when a monolithic psum
        would stage the whole array through collective scratch."""
        import jax.numpy as jnp

        from jax import lax

        if op is not SUM:
            return self.allreduce(x, op)
        n = self.size
        flat = x.reshape(-1)
        seg = max(n, min(segment_elems, flat.shape[0]))
        seg -= seg % n                       # scatter needs n-divisible
        if seg <= 0 or flat.shape[0] <= seg:
            return self.allreduce_rs_ag(x, op)
        nseg, rem = divmod(flat.shape[0], seg)
        head, tail = flat[: nseg * seg], flat[nseg * seg:]

        def step(_, chunk):
            scattered = lax.psum_scatter(chunk, self._ax,
                                         scatter_dimension=0, tiled=True)
            return None, lax.all_gather(scattered, self._ax, axis=0,
                                        tiled=True)

        _, out = lax.scan(step, None, head.reshape(nseg, seg))
        parts = [out.reshape(-1)]
        if rem:
            parts.append(lax.psum(tail, self._ax))
        return jnp.concatenate(parts).reshape(x.shape)

    def allgather_ring(self, x, axis: int = 0):
        """Explicit ring allgather over ppermute hops (≈
        coll_base_allgather.c:364).  n-1 neighbor hops; each hop moves 1/n
        of the result — the shape DCN-spanning axes prefer (one peer at a
        time) over the all-to-one fan-in XLA may pick for all_gather."""
        import jax.numpy as jnp

        from jax import lax

        n = self.size
        ax = self._ax
        if isinstance(ax, tuple):  # ring over the flattened multi-axis
            return self.allgather(x, axis=axis)  # fall back to native
        perm = [(i, (i + 1) % n) for i in range(n)]
        blocks = [x]
        cur = x
        for _ in range(n - 1):
            cur = lax.ppermute(cur, ax, perm)
            blocks.append(cur)
        # blocks[j] is the block of rank (my - j) mod n, so rank p's block
        # sits at index (my - p) mod n — the permutation is self-inverse
        my = self.rank()
        stacked = jnp.stack(blocks)                    # (n, ...)
        ordered = stacked[(my - jnp.arange(n)) % n]    # rank-ordered blocks
        return jnp.concatenate([ordered[i] for i in range(n)], axis=axis)

    def bcast_ring(self, x, root: int = 0):
        """Pipeline/chain broadcast via n-1 ppermute hops (≈
        coll_base_bcast.c:257 chain) — each hop touches one neighbor link
        instead of the masked-psum tree."""
        import jax.numpy as jnp

        from jax import lax

        n = self.size
        ax = self._ax
        if isinstance(ax, tuple):
            return self.bcast(x, root)
        perm = [(i, (i + 1) % n) for i in range(n)]
        cur = jnp.where(self.rank() == root, x, jnp.zeros_like(x))
        acc = cur
        for _ in range(n - 1):
            cur = lax.ppermute(cur, ax, perm)
            acc = acc + cur
        return acc.astype(x.dtype)

    # -- v-collectives (ragged → pad + static counts) ----------------------
    #
    # SPMD/XLA needs one static-shape program on every device, so ragged
    # counts are carried as a *static* per-rank tuple and buffers are
    # padded to max(counts); the valid prefix of each block is the payload
    # (≈ MPI_*v displacement arrays, with padding playing the role of
    # displacements).  Uniform counts (the common case reaching coll/xla
    # through the MPI API) lower to the dense collectives unchanged.

    def _counts(self, counts, x, axis: int) -> tuple[int, ...]:
        if counts is None:
            return (x.shape[axis],) * self.size
        counts = tuple(int(c) for c in counts)
        if len(counts) != self.size:
            raise MPIException(
                f"counts {counts} must have one entry per rank ({self.size})")
        return counts

    def allgatherv(self, x, counts=None, axis: int = 0):
        """≈ MPI_Allgatherv: x is my block padded to max(counts) along
        `axis` (exactly counts[r] valid rows on rank r); returns the
        concatenation of every rank's valid rows (static shape
        sum(counts))."""
        import jax.numpy as jnp

        from jax import lax

        counts = self._counts(counts, x, axis)
        if len(set(counts)) == 1 and counts[0] == x.shape[axis]:
            return self.allgather(x, axis=axis)     # dense fast path
        stacked = lax.all_gather(x, self._ax, tiled=False)
        stacked = stacked.reshape((self.size,) + x.shape)
        parts = [jnp.take(stacked[r], jnp.arange(c), axis=axis)
                 for r, c in enumerate(counts)]
        return jnp.concatenate(parts, axis=axis)

    def gatherv(self, x, counts=None, root: int = 0, axis: int = 0):
        """≈ MPI_Gatherv: allgatherv + zeros on non-roots (reduce note)."""
        import jax.numpy as jnp

        full = self.allgatherv(x, counts, axis=axis)
        return jnp.where(self.rank() == root, full, jnp.zeros_like(full))

    def scatterv(self, x, counts=None, root: int = 0, axis: int = 0):
        """≈ MPI_Scatterv: x holds sum(counts) rows along `axis` on every
        device (root's value is authoritative — it is broadcast); returns
        my block padded with zeros to max(counts) (counts[my] valid)."""
        import jax.numpy as jnp

        from jax import lax

        n = self.size
        if counts is None:
            return self.scatter(x, root, axis=axis)
        counts = tuple(int(c) for c in counts)
        if len(counts) != n:
            raise MPIException(
                f"counts {counts} must have one entry per rank ({n})")
        full = self.bcast(x, root)
        maxc = max(counts)
        offs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
        starts = jnp.asarray(offs[:-1])[self.rank()]
        cnt = jnp.asarray(np.array(counts, np.int32))[self.rank()]
        # pad the tail so a maxc-row slice at any offset stays in bounds,
        # then slice my window and zero rows past my count
        pad = [(0, 0)] * full.ndim
        pad[axis] = (0, maxc)
        fullp = jnp.pad(full, pad)
        start_vec = [0] * full.ndim
        start_vec[axis] = starts
        sizes = list(full.shape)
        sizes[axis] = maxc
        blk = lax.dynamic_slice(fullp, start_vec, sizes)
        shape = [1] * full.ndim
        shape[axis] = maxc
        mask = (jnp.arange(maxc) < cnt).reshape(shape)
        return jnp.where(mask, blk, jnp.zeros_like(blk))

    def alltoallv(self, x, send_counts=None, axis: int = 0):
        """≈ MPI_Alltoallv: x is (n, maxc, ...) — one padded segment per
        destination (send_counts[my][d] valid rows in segment d; static
        n×n matrix).  Returns (n, maxc', ...): one padded segment per
        source, maxc' = max over the transposed counts, zeros beyond the
        valid prefix."""
        import jax.numpy as jnp

        from jax import lax

        n = self.size
        if x.shape[0] != n:
            raise MPIException(
                f"alltoallv: leading dim {x.shape[0]} must equal "
                f"communicator size {n}")
        if send_counts is None:
            return self.alltoall(x, split_axis=0, concat_axis=0)
        m = np.asarray(send_counts, np.int64)
        if m.shape != (n, n):
            raise MPIException(
                f"alltoallv: send_counts must be {n}x{n}, got {m.shape}")
        # exchange padded segments: all_to_all over the destination dim
        out = lax.all_to_all(x, self._ax, split_axis=0, concat_axis=0,
                             tiled=True)
        out = out.reshape((n,) + x.shape[1:])
        # mask each received segment to its true (recv) count: segment s
        # holds send_counts[s][my] valid rows
        recv = jnp.asarray(m.T.astype(np.int32))[self.rank()]   # (n,)
        idx = jnp.arange(x.shape[1])
        shape = [n] + [1] * (x.ndim - 1)
        shape[1] = x.shape[1]
        mask = (idx[None, :] < recv[:, None]).reshape(shape)
        return jnp.where(mask, out, jnp.zeros_like(out))

    def barrier(self, token=None):
        """SPMD barrier: a zero-byte psum forces cross-device sync ordering.
        Returns a token to thread through data dependencies."""
        import jax.numpy as jnp

        from jax import lax

        t = token if token is not None else jnp.zeros((), jnp.int32)
        return lax.psum(t, self._ax) * 0

    # -- point-to-point as permutation (the TPU-native shape of send/recv) -

    def shift(self, x, displacement: int = 1, axis: Optional[str] = None):
        """Cyclic ring shift (≈ MPI_Cart_shift + Sendrecv): every device
        sends to (i+displacement) mod n along `axis` → one ICI hop."""
        from jax import lax

        ax = axis or self.axes[-1]
        n = self.mesh.shape[ax]
        perm = [(i, (i + displacement) % n) for i in range(n)]
        return lax.ppermute(x, ax, perm)

    def permute(self, x, perm: Sequence[tuple[int, int]],
                axis: Optional[str] = None):
        """General (src, dst) permutation → lax.ppermute. Pairs not covered
        receive zeros (lax semantics; matches one-sided put into a zeroed
        window)."""
        from jax import lax

        return lax.ppermute(x, axis or self.axes[-1], list(perm))

    def sendrecv(self, x, dest_disp: int, source_disp: Optional[int] = None,
                 axis: Optional[str] = None):
        """Cyclic exchange by *displacement* (SPMD: every device passes the
        same arguments, so peers are displacements, not absolute ranks —
        exactly MPI_Cart_shift + MPI_Sendrecv semantics).  ``source_disp``,
        if given, must be the matching -dest_disp pattern; anything else is
        not a permutation and is rejected."""
        from jax import lax

        ax = axis or self.axes[-1]
        n = int(self.mesh.shape[ax])
        off = dest_disp % n
        if source_disp is not None and (source_disp % n) != (-dest_disp) % n:
            raise MPIException(
                f"sendrecv: source_disp {source_disp} does not match "
                f"dest_disp {dest_disp} (need source ≡ -dest mod {n} for a "
                f"cyclic pattern; use permute() for general patterns)")
        perm = [(i, (i + off) % n) for i in range(n)]
        return lax.ppermute(x, ax, perm)

    # -- one-sided (remote DMA — ≈ btl.h:970/1007 put/get) -----------------
    #
    # Unlike everything above, these are NOT collectives: bytes move only
    # src→dst over ICI via a pallas make_async_remote_copy kernel
    # (ops/remote_dma).  The other devices run the same compiled SPMD
    # program but issue no traffic.

    def _flat_axis(self, what: str) -> str:
        if len(self.axes) != 1 or len(self.mesh.axis_names) != 1:
            raise MPIException(
                f"{what}: one-sided remote DMA addresses devices by their "
                f"logical index, which requires a flat single-axis mesh "
                f"(got axes {self.axes} of mesh {self.mesh.axis_names}); "
                f"use device_world(make_mesh(devices=...))")
        return self.axes[0]

    def put(self, win, value, src: int, dst: int):
        """Traced one-sided put: device ``src`` writes ``value`` into
        ``dst``'s window shard; returns the new window.  Completes before
        the kernel returns (implicit quiet per op)."""
        from ompi_tpu.ops.remote_dma import window_put

        return window_put(win, value, src, dst, self._flat_axis("put"))

    def get(self, win, src: int, dst: int):
        """Traced one-sided get: device ``dst`` fetches ``src``'s window
        shard (everyone else sees its own shard)."""
        from ompi_tpu.ops.remote_dma import window_get

        return window_get(win, src, dst, self._flat_axis("get"))

    # -- driver-mode helper ------------------------------------------------

    def run(self, fn: Callable, *arrays, out_specs: Any = None):
        """Run fn(self, *shards) under shard_map over my axes, splitting each
        input along axis 0. Convenience for tests/small jobs; real programs
        write their own shard_map/jit with explicit specs."""
        import jax
        from jax.sharding import PartitionSpec as P

        axes = self.axes
        spec = P(axes if len(axes) > 1 else axes[0])
        in_specs = tuple(spec for _ in arrays)
        out_sp = out_specs if out_specs is not None else spec

        @functools.partial(
            jax.shard_map, mesh=self.mesh, in_specs=in_specs,
            out_specs=out_sp, check_vma=False)
        def shmapped(*shards):
            return fn(self, *shards)

        return jax.jit(shmapped)(*arrays)

    def run_method(self, method: str, *arrays, margs: tuple = (),
                   mkw: tuple = (), out_specs: Any = None,
                   donate: tuple = ()):
        """Driver-mode dispatch of one named collective, cached: the
        shard_map+jit program is built once per (method, static args,
        input avals) and reused — a driver barrier/allreduce costs a dict
        lookup + dispatch, not a retrace (round-2 weak #5).  ``donate``
        names array positions whose buffers the caller hands over (e.g. a
        window being replaced by the op's result)."""
        import jax

        from jax.sharding import PartitionSpec as P

        key = (method, margs, mkw,
               tuple((a.shape, str(getattr(a, "dtype", "?")))
                     for a in arrays),
               out_specs if out_specs is None else str(out_specs),
               donate)
        cached = self._method_cache.get(key)
        if cached is None:
            kw = dict(mkw)
            axes = self.axes
            spec = P(axes if len(axes) > 1 else axes[0])
            in_specs = tuple(spec for _ in arrays)
            out_sp = out_specs if out_specs is not None else spec

            @functools.partial(
                jax.shard_map, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_sp, check_vma=False)
            def shmapped(*shards):
                return getattr(self, method)(*shards, *margs, **kw)

            cached = jax.jit(shmapped, donate_argnums=donate)
            self._method_cache[key] = cached
        return cached(*arrays)

    def __repr__(self) -> str:
        return (f"DeviceCommunicator({self.name}, axes={self.axes}, "
                f"size={self.size})")


def _my_block(comm: DeviceCommunicator, full, axis: int):
    """Slice this rank's equal block along `axis` (traced)."""
    from jax import lax

    n = comm.size
    if full.shape[axis] % n:
        raise MPIException(
            f"dimension {axis} ({full.shape[axis]}) not divisible by "
            f"communicator size {n}")
    block = full.shape[axis] // n
    start = comm.rank() * block
    sizes = list(full.shape)
    sizes[axis] = block
    starts = [0] * full.ndim
    starts[axis] = start
    return lax.dynamic_slice(full, starts, sizes)


def device_world(mesh=None, axes=None) -> DeviceCommunicator:
    """The device-side COMM_WORLD: all chips of the mesh (default: one mesh
    over every local device)."""
    if mesh is None:
        import jax
        from jax.sharding import Mesh

        devs = np.array(jax.devices())
        mesh = Mesh(devs, axis_names=("world",))
    return DeviceCommunicator(mesh, axes, name="DEVICE_WORLD")
