"""MPMD dispatch shim for spawn_multiple (≈ the reference's multi-app-context
job: orterun a.out : b.out builds one orte_job_t with several app contexts,
each rank exec'ing its context's argv).

Launched as every rank of a spawn_multiple child job; execs this rank's argv
from the OMPI_TPU_MPMD_TABLE environment table, inheriting the launcher's
rank/pmix environment so the target program's init() sees the full world.
"""

import json
import os
import sys


def main() -> None:
    table = json.loads(os.environ["OMPI_TPU_MPMD_TABLE"])
    rank = int(os.environ["OMPI_TPU_RANK"])
    argv, env = table[rank]
    os.environ.update(env)  # this command block's env (spawn_multiple envs[i])
    os.execvp(argv[0], argv)


if __name__ == "__main__":
    main()
