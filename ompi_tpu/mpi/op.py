"""Reduction operations: the op table + user-defined ops.

≈ ompi/op (op.h:139,386 and the per-(op × type) function table in
ompi/mca/op/base/op_base_functions.c).  Each Op carries BOTH a host
implementation (numpy ufunc) and a device implementation (jax) so the same Op
object works in host collectives and inside jit-compiled device collectives —
the dual the reference approximates with its op MCA framework for
SIMD-accelerated overrides (ompi/mca/op/example).

MAXLOC/MINLOC operate on the (val, loc) pair types, as in MPI.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ompi_tpu.mpi.constants import MPIException

__all__ = ["Op", "SUM", "PROD", "MAX", "MIN", "LAND", "LOR", "LXOR",
           "BAND", "BOR", "BXOR", "MAXLOC", "MINLOC", "REPLACE", "NO_OP",
           "create_op", "reduce_local", "op_commutative"]


class Op:
    """A reduction operator with host and device callables.

    ``host(a, b)`` reduces two numpy arrays elementwise; ``device(a, b)``
    does the same for jax arrays inside a trace.  ``commutative`` gates
    algorithm choice (ring allreduce requires commutativity, as in
    coll_tuned_decision_fixed.c:65-87).
    """

    def __init__(self, name: str, host: Callable, device: Optional[Callable],
                 commutative: bool = True,
                 jax_reduce_name: Optional[str] = None) -> None:
        self.name = name
        self.host = host
        self._device = device
        self.commutative = commutative
        # name of the fused XLA collective, e.g. "psum" — lets coll/xla use
        # the native fused collective instead of pairwise application
        self.jax_reduce_name = jax_reduce_name

    def device(self, a: Any, b: Any) -> Any:
        if self._device is None:
            raise MPIException(
                f"op {self.name} has no device implementation; reduce on host")
        return self._device(a, b)

    def __call__(self, a, b):
        return self.host(a, b)

    def __repr__(self) -> str:
        return f"Op({self.name})"


def _pair_extreme(cmp):
    """MAXLOC/MINLOC on structured (val, loc) arrays: pick extreme value,
    lowest loc on ties (the MPI rule)."""

    def host(a: np.ndarray, b: np.ndarray) -> np.ndarray:
        take_b = cmp(b["val"], a["val"]) | (
            (b["val"] == a["val"]) & (b["loc"] < a["loc"]))
        return np.where(take_b, b, a)

    return host


def _jax_op(fn_name):
    def device(a, b):
        import jax.numpy as jnp

        return getattr(jnp, fn_name)(a, b)

    return device


SUM = Op("sum", np.add, _jax_op("add"), jax_reduce_name="psum")
PROD = Op("prod", np.multiply, _jax_op("multiply"))
MAX = Op("max", np.maximum, _jax_op("maximum"), jax_reduce_name="pmax")
MIN = Op("min", np.minimum, _jax_op("minimum"), jax_reduce_name="pmin")
LAND = Op("land", np.logical_and, _jax_op("logical_and"))
LOR = Op("lor", np.logical_or, _jax_op("logical_or"))
LXOR = Op("lxor", np.logical_xor, _jax_op("logical_xor"))
BAND = Op("band", np.bitwise_and, _jax_op("bitwise_and"))
BOR = Op("bor", np.bitwise_or, _jax_op("bitwise_or"))
BXOR = Op("bxor", np.bitwise_xor, _jax_op("bitwise_xor"))
MAXLOC = Op("maxloc", _pair_extreme(np.greater), None)
MINLOC = Op("minloc", _pair_extreme(np.less), None)
REPLACE = Op("replace", lambda a, b: b, lambda a, b: b, commutative=False)
NO_OP = Op("no_op", lambda a, b: a, lambda a, b: a, commutative=False)


def create_op(fn: Callable, commutative: bool = False,
              device_fn: Optional[Callable] = None, name: str = "user") -> Op:
    """MPI_Op_create: user-defined reduction (host fn mandatory; pass
    device_fn — a jax-traceable function — to use it in device collectives)."""
    return Op(name, fn, device_fn, commutative=commutative)


def reduce_local(inbuf: Any, inoutbuf: np.ndarray, op: Op) -> np.ndarray:
    """≈ MPI_Reduce_local (reduce_local.c): inoutbuf = op(inbuf, inoutbuf),
    in place, no communication.  MPI argument order: inbuf is the FIRST
    operand (matters for non-commutative ops)."""
    a = np.asarray(inbuf)
    if a.shape != inoutbuf.shape:
        raise MPIException(
            f"reduce_local: shape mismatch {a.shape} vs {inoutbuf.shape}",
            error_class=2)
    inoutbuf[...] = op.host(a, inoutbuf)
    return inoutbuf


def op_commutative(op: Op) -> bool:
    """≈ MPI_Op_commutative."""
    return bool(op.commutative)
