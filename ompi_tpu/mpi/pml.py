"""PML — point-to-point messaging logic: matching, protocols, progress.

≈ ompi/mca/pml/ob1: MPI send/recv semantics over the BTL —
- tag/source matching with wildcards, posted-recv + unexpected queues
  (≈ pml_ob1_recvfrag.c:143-173),
- eager vs rendezvous protocol selection by message size
  (≈ pml_ob1_sendreq.h:382-413),
- fragmentation/pipelining of large transfers (≈ the RDMA pipeline).

Threading model (replaces the reference's opal_progress polling): BTL reader
threads ONLY read and match; all payload writes go through a single send
worker thread per process, so readers can never block on socket backpressure
— the classic two-sided rendezvous deadlock (both readers stuck in sendall)
is structurally impossible.

MPI ordering guarantee (per sender-receiver pair, per communicator, in tag
order of posting) holds because each direction of a pair is one TCP stream
processed by one reader, and the send worker is FIFO.
"""

from __future__ import annotations

import collections
import itertools
import math
import queue
import threading
import time
import weakref
from typing import Any, Optional

import numpy as np

from ompi_tpu.core import output
from ompi_tpu.core.buffer import BufferKind, BufferLocationError, classify
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.mpi import datatype as dt_mod
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.btl import BtlEndpoint
from ompi_tpu.mpi.constants import (
    ANY_SOURCE, ANY_TAG, ERR_PROC_FAILED, ERR_TRUNCATE, PROC_NULL,
    MPIException,
)
from ompi_tpu.mpi.datatype import Datatype
from ompi_tpu.mpi.request import (CompletedRequest, PersistentRequest,
                                  Request, Status)

__all__ = ["pml_framework", "PmlOb1", "RecvRequest", "Message",
           "MESSAGE_NO_PROC"]


def _reject_device(buf: Any, what: str) -> None:
    """Device/traced buffers must NEVER silently host-stage through the PML
    (the reference's coll/cuda bounce-buffer anti-pattern this design
    forbids).  They belong on the device path: a comm with a bound
    DeviceCommunicator (comm.bind_device), or lax collectives inside jit."""
    kind = classify(buf)
    if kind is not BufferKind.HOST:
        raise BufferLocationError(
            f"pml.{what}: got a {kind.value} buffer; the host PML would "
            f"stage it through host memory. Use the device path instead "
            f"(comm.bind_device(DeviceCommunicator(...)) routes collectives "
            f"over XLA/ICI; for p2p use DeviceCommunicator.shift/permute "
            f"inside jit), or np.asarray() the buffer explicitly if host "
            f"staging is intended.")

_log = output.get_stream("pml")

# 1-2 core hosts flip the receiver-pull spin style (see _progress_wait)
import os as _os_mod  # noqa: E402

_SMALL_HOST = (_os_mod.cpu_count() or 1) <= 2

pml_framework = Framework("pml", "point-to-point messaging logic")

register_var("pml", "eager_limit", VarType.SIZE, 64 * 1024,
             "max payload bytes sent eagerly (larger goes rendezvous)")
register_var("pml", "retry_window", VarType.DOUBLE, 30.0,
             "seconds a transiently-unroutable frame (peer dead or "
             "mid-respawn) is retried before the send fails (0 = fail "
             "fast); ≈ the failover PML's retransmit bound")
register_var("pml", "heal_max_interval", VarType.DOUBLE, 1.0,
             "cap on the exponential park-and-heal retry backoff; also "
             "bounds how stale the dead-peer fast-fail can be (a send "
             "to a detector-declared-dead rank fails within "
             "rml_heartbeat_timeout + this, not the full retry window)")
register_var("pml", "frag_size", VarType.SIZE, 1 << 20,
             "fragment size for rendezvous pipelines")
register_var("pml", "native_match", VarType.BOOL, True,
             "run the matching engine (posted/unexpected queues, wire-seq "
             "gate, held frames) in the compiled extension "
             "(_native/fastdss.c Engine — ob1's recvfrag matcher in C); "
             "off, or a failed native build, → the pure-python matcher")


class RecvRequest(Request):
    def __init__(self, buf: Optional[np.ndarray], datatype: Optional[Datatype],
                 count: Optional[int], source: int, tag: int, cid: int) -> None:
        super().__init__(kind="recv")
        self.buf = buf
        self.datatype = datatype  # None → take element dtype from the wire
        self.count = count        # None → no truncation check (alloc to fit)
        self.source = source
        self.tag = tag
        self.cid = cid
        self.rid = -1  # receiver-side id for rendezvous
        self._pml = None  # set by PmlOb1.irecv; enables real cancel
        # post time (monotonic): the hang doctor's pending-recv age
        self.t_posted = time.monotonic()
        # set BEFORE delivery can complete the request: the status.source
        # value _deliver should report instead of the wire peer (a
        # communicator's group rank when it differs from the world rank).
        # A post-completion translation callback would race the waiter.
        self.source_override: Optional[int] = None

    def cancel(self) -> None:
        """≈ MPI_Cancel on a recv: dequeue the posted request if (and only
        if) nothing has matched it yet; a matched/completed recv proceeds
        (MPI's 'cancel either succeeds or the operation succeeds')."""
        pml = self._pml
        if pml is None or self.done():
            return
        with pml._lock:
            if pml._eng is not None:
                if not pml._eng.cancel(self.cid, self):
                    return  # already matched — delivery wins
            else:
                m = pml._matching.get(self.cid)
                if m is None:
                    return
                try:
                    m.posted.remove(self)
                except ValueError:
                    return  # already matched — delivery wins
        self.cancelled = True
        self.status.set_cancelled(True)  # MPI_Test_cancelled sees it
        self.complete(None)


class Message:
    """≈ MPI_Message: one matched-and-detached incoming message
    (ompi/mpi/c/mprobe.c:1, imrecv.c:1).  Once mprobe/improbe returns a
    handle, the message can no longer match any other recv or probe;
    exactly one mrecv/imrecv consumes it.  This is the only thread-safe
    probe-then-receive with wildcards: the match and the detach happen
    atomically under the PML lock."""

    __slots__ = ("pml", "peer", "hdr", "payload", "consumed")

    def __init__(self, pml, peer: int, hdr: dict, payload) -> None:
        self.pml = pml
        self.peer = peer
        self.hdr = hdr
        self.payload = payload
        self.consumed = False

    @property
    def no_proc(self) -> bool:
        return self.pml is None


#: ≈ MPI_MESSAGE_NO_PROC — what a matched probe of PROC_NULL returns;
#: mrecv on it completes immediately with an empty buffer.
MESSAGE_NO_PROC = Message(None, -1, {}, b"")


_wire_memo: dict = {}  # np.dtype → wire spec (hot-path cache)


def _dtype_to_wire(dt: np.dtype):
    try:
        return _wire_memo[dt]
    except (KeyError, TypeError):
        pass
    if dt.fields:
        spec = dt.descr
    elif dt.kind == "V":
        # extended dtypes (bfloat16, float8_*) stringify as raw void
        # ('<V2'); their registered name ('bfloat16') reconstructs
        spec = dt.name
    else:
        spec = dt.str
    try:
        _wire_memo[dt] = spec
    except TypeError:
        pass
    return spec


_dtype_memo: dict[str, np.dtype] = {}  # hot-path cache (str specs only)


def _wire_to_dtype(spec) -> np.dtype:
    if isinstance(spec, str):
        dt = _dtype_memo.get(spec)
        if dt is not None:
            return dt
    if isinstance(spec, (list, tuple)):
        return np.dtype([tuple(f) for f in spec])
    if isinstance(spec, str) and not spec[:1].isalpha():
        dt = np.dtype(spec)
    else:
        # name form needs ml_dtypes registered for the extended types
        import ml_dtypes  # noqa: F401

        dt = np.dtype(spec)
    _dtype_memo[spec] = dt
    return dt


class _SendState:
    """Sender-side bookkeeping for sends awaiting a peer event (rendezvous
    CTS, sync-mode ack, ready-mode nack)."""

    def __init__(self, req: Request, peer: int, payload,
                 on_done=None) -> None:
        self.req = req
        self.peer = peer
        self.payload = payload   # bytes or zero-copy memoryview of user buf
        self.on_done = on_done   # e.g. bsend-pool release
        self.fl = 0              # flow id (tracing): rides the rndv_send span
        # creation time (monotonic): the hang doctor's pending-send age
        self.t_posted = time.monotonic()


class _RecvState:
    """Receiver-side rendezvous accumulation.

    ``direct=True`` ⇒ ``data`` is a uint8 view of the user's posted buffer
    and fragments land in place — no intermediate copy (the reference
    pipelines straight into the receive convertor the same way,
    pml_ob1_recvreq.c).  Otherwise ``data`` is a staging bytearray that
    ``_deliver`` unpacks through the datatype engine.
    """

    def __init__(self, req: RecvRequest, size: int, src_hdr: dict,
                 peer: int, direct: bool = False) -> None:
        self.req = req
        self.direct = direct
        if direct:
            self.data = req.buf.reshape(-1).view(np.uint8)[:size]
        else:
            self.data = bytearray(size)
        self.received = 0
        self.src_hdr = src_hdr
        self.peer = peer
        # flight-recorder span: CTS sent → last fragment landed
        self.trace_t0 = trace_mod.begin() if trace_mod.active else 0


class BsendPool:
    """The attached MPI_Buffer_attach pool (per process, ≈ ompi/mpi/c/
    buffer_attach.c + pml bsend accounting).  Byte-counted, not an
    allocator: payloads are Python objects; the pool enforces the MPI
    contract that buffered sends beyond the attached capacity fail."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self.capacity = 0
        self.used = 0

    def attach(self, nbytes: int) -> None:
        with self._cv:
            if self.capacity:
                raise MPIException(
                    "a bsend buffer is already attached", error_class=1)
            self.capacity = int(nbytes)

    def detach(self) -> int:
        """Blocks until pending buffered sends drain (MPI semantics), then
        returns the detached capacity."""
        with self._cv:
            self._cv.wait_for(lambda: self.used == 0)
            cap, self.capacity = self.capacity, 0
            return cap

    def reserve(self, nbytes: int) -> None:
        with self._cv:
            if self.used + nbytes > self.capacity:
                raise MPIException(
                    f"bsend of {nbytes}B exceeds attached buffer "
                    f"({self.used}/{self.capacity}B in use); "
                    f"MPI_Buffer_attach more", error_class=1)
            self.used += nbytes

    def release(self, nbytes: int) -> None:
        with self._cv:
            self.used -= nbytes
            if self.used == 0:
                self._cv.notify_all()


def buffer_attach(nbytes: int) -> None:
    """≈ MPI_Buffer_attach — attaches to this process's (world) PML.
    The pool is per-PML so in-process multi-rank harnesses keep ranks'
    buffers independent, exactly like separate MPI processes."""
    _world_pml().bsend_pool.attach(nbytes)


def buffer_detach() -> int:
    """≈ MPI_Buffer_detach — blocks until buffered sends complete."""
    return _world_pml().bsend_pool.detach()


def _world_pml() -> "PmlOb1":
    from ompi_tpu.mpi import runtime

    world = runtime.COMM_WORLD
    if world is None or not hasattr(world, "pml"):
        raise MPIException(
            "buffer_attach/detach need an initialized runtime "
            "(ompi_tpu.init()); in harness code use comm.pml.bsend_pool")
    return world.pml


class _WireWatch(Request):
    """Tracks the wire write of a frame whose *logical* completion comes
    from a later peer event (sack for sync/ready, CTS→data for rndv).
    Success is a no-op; a transport failure must tear down the pending
    send state and fail the real request — otherwise the caller's wait()
    hangs forever on a dead connection."""

    def __init__(self, pml: "PmlOb1", sid: int) -> None:
        super().__init__(kind="wire")
        self._pml = pml
        self._sid = sid

    def complete(self, result: Any = None) -> None:
        pass  # the real request completes on sack / after rndv data

    def fail(self, exc: BaseException) -> None:
        with self._pml._lock:
            state = self._pml._send_states.pop(self._sid, None)
        if state is not None:
            if state.on_done:
                state.on_done()
            state.req.fail(exc)


#: flow-id namespace stride: ids are ``rank * stride + local counter`` —
#: globally unique without coordination (a rank emitting 2^40 frames in
#: one trace window would wrap the ring thousands of times over first)
_FLOW_STRIDE = 1 << 40


class _Matching:
    """Per-communicator matching engine (posted + unexpected queues)."""

    def __init__(self) -> None:
        self.posted: collections.deque[RecvRequest] = collections.deque()
        self.unexpected: collections.deque[tuple[int, dict, bytes]] = \
            collections.deque()


def _hdr_matches(req: RecvRequest, peer: int, hdr: dict) -> bool:
    if req.source != ANY_SOURCE and req.source != peer:
        return False
    if req.tag == ANY_TAG:
        # ANY_TAG never matches the reserved negative tag space (internal
        # collective traffic) — same guard as the reference's ob1 matching;
        # without it a user wildcard recv posted before a barrier would
        # steal the barrier's control frames
        return hdr["tag"] >= 0
    return req.tag == hdr["tag"]


# request-lifecycle events (≈ the PERUSE spec, ompi/peruse/peruse.h:55-76:
# queue/xfer event hooks on the matching engine) — listeners receive
# (event, info_dict); pml/coll/osc monitoring components subscribe here
EVT_SEND_POST = "send_post"        # isend issued
EVT_RECV_POST = "recv_post"        # irecv posted
EVT_MATCH = "match"                # incoming frame matched a posted recv
EVT_UNEXPECTED = "unexpected"      # incoming frame queued unmatched
EVT_DELIVER = "deliver"            # payload delivered, request complete
EVT_PEER_REVIVED = "peer_revived"  # a peer's new incarnation adopted —
# the hook message-log replay (ckpt/msglog auto_replay) recovers sends
# that died with the old incarnation's transport


class PmlOb1:
    """The default PML: matching + eager/rendezvous over the BTL."""

    def __init__(self, rank: int) -> None:
        import os

        self.rank = rank
        self.endpoint = BtlEndpoint(rank, self._on_frame)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)  # probe waiters
        self._matching: dict[int, _Matching] = {}
        self._send_states: dict[int, _SendState] = {}
        self._recv_states: dict[int, _RecvState] = {}
        self._ids = itertools.count(1)
        self._seq: dict[tuple[int, int], int] = {}
        self._recv_seq: dict[tuple[int, int], int] = {}
        self._held: dict[tuple[int, int], dict[int, tuple]] = {}
        # errmgr/respawn epoch fencing: my incarnation number (restarted
        # ranks reject frames stamped for a previous life of theirs),
        # each peer's incarnation (learned from its rebind announce OR
        # from the "si" stamp on its first post-restart frame — whichever
        # transport wins the race), and a re-announce guard so a lost
        # rebind announce heals instead of dropping frames forever
        self.incarnation = int(os.environ.get("OMPI_TPU_RESTART") or 0)
        self._peer_epoch: dict[int, int] = {}   # what I stamp TOWARD peer
        self._peer_inc: dict[int, int] = {}     # peer's own incarnation
        self._reannounce_at: dict[int, float] = {}  # rate-limited heal
        # per-peer ordered frames awaiting a route heal (park-and-heal
        # retransmit; see _deliver_frame) + MPI_T observability for the
        # FT path (≈ the monitoring pvar discipline for p2p counters)
        self._parked: dict[int, list] = {}
        self._route_gen: dict[int, int] = {}   # bumped per adopted incarnation
        self._queued: dict[int, int] = {}      # frames in _sendq per peer
        # header refs of frames still in _sendq, FIFO per peer: an adopt
        # must restamp these in queue order (parked first, then these) so
        # an isend issued after the adopt draws a LATER seq than every
        # frame queued before it — queue order and seq order stay aligned
        self._inqueue: dict[int, collections.deque] = {}
        self._healing: dict[int, float] = {}   # peers with a live healer
        # → that healer's current backoff interval (seconds)
        self._qlock = threading.Lock()         # _queued has its own lock:
        # _enqueue_frame runs from handlers that already hold self._lock
        from ompi_tpu.mpi.mpit import Pvar, PvarClass, pvar_registry

        self.pvar_parked = pvar_registry.register_or_get(Pvar(
            f"pml_parked_frames_rank{rank}", PvarClass.COUNTER, "frames",
            "frames parked for a route heal (peer dead or mid-respawn)"))
        self.pvar_healed = pvar_registry.register_or_get(Pvar(
            f"pml_healed_frames_rank{rank}", PvarClass.COUNTER, "frames",
            "parked frames delivered after their peer's route healed"))
        self.pvar_fenced = pvar_registry.register_or_get(Pvar(
            f"pml_fenced_frames_rank{rank}", PvarClass.COUNTER, "frames",
            "pre-restart frames dropped by the incarnation fence"))
        self.pvar_heal_ticks = pvar_registry.register_or_get(Pvar(
            "pml_heal_ticks_total", PvarClass.COUNTER, "ticks",
            "park-and-heal retry attempts across all ranks in this "
            "process (soak runs read this as retry pressure)"))
        # user-level fault tolerance sidecar (ompi_tpu.mpi.ft.PmlFT):
        # revoked cids, failure detector, FT control-frame dispatch.
        # None until the first FT API call / FT frame / runtime attach —
        # the hot paths pay one attribute check.
        self.ft = None
        # memchecker gate read ONCE (off-by-default debug feature — the
        # hot path must not pay a registry lookup per message; toggle it
        # before creating communicators, like the reference's build flag)
        from ompi_tpu.core import memchecker

        self._memcheck = memchecker.enabled()
        # compiled matching engine: owns posted/unexpected queues + the
        # wire-seq gate when available; every call happens under
        # self._lock (the engine replaces the structures that lock
        # guarded, it does not add its own)
        self._eng = None
        self._fast = None
        if var_registry.get("pml_native_match"):
            from ompi_tpu import _native

            fast = _native.fastdss()
            if fast is not None and hasattr(fast, "Engine"):
                self._eng = fast.Engine()
                self._fast = fast
        self._sendq: "queue.Queue[Optional[tuple]]" = queue.Queue()
        # every posted recv, weakly (engine-agnostic: the native matching
        # engine owns the real posted queue) — what the hang doctor's
        # pending_summary walks; completed requests filter out on done()
        self._doctor_recvs: "weakref.WeakSet[RecvRequest]" = \
            weakref.WeakSet()
        self._listeners: list = []   # peruse/monitoring subscribers
        self._events: "collections.deque[tuple]" = collections.deque()
        self.bsend_pool = BsendPool()  # per-PML, like every other send state
        self._worker = threading.Thread(
            target=self._send_loop, name=f"pml-send-{rank}", daemon=True)
        self._worker.start()
        self._closed = False
        if self._eng is not None and self.endpoint.proc_btl is not None:
            # same-address-space fast lane: peers deliver into my engine
            self.endpoint.proc_btl.on_fast = self._on_frame_fast
        if self._eng is not None and self.endpoint.shm_btl is not None:
            # fused shm drain: ring decode + matching in one C call per
            # batch; also enables receiver-pull progress (_progress_wait)
            self.endpoint.shm_btl.drain_hook = self._drain_shm
        if self.endpoint.tcp_btl is not None:
            # zero-copy rndv landing: the tcp poller asks for the
            # plan-registered destination of an in-flight "data" frame
            # and lands payload bytes straight into it
            self.endpoint.tcp_btl.recv_sink = self._rndv_sink
            self.endpoint.tcp_btl.recv_sink_done = self._rndv_sink_done

    # -- event hooks (PERUSE equivalent) -----------------------------------
    #
    # _emit only enqueues; _drain_events dispatches OUTSIDE the PML lock so
    # listeners may safely call back into the PML (and a racing
    # remove_listener can't skip a concurrent subscriber: dispatch iterates
    # a snapshot).  Every path that can enqueue drains before returning.

    def add_listener(self, cb) -> None:
        """Subscribe cb(event, info) to request-lifecycle events."""
        self._listeners.append(cb)

    def remove_listener(self, cb) -> None:
        self._listeners.remove(cb)

    def _emit(self, event: str, **info) -> None:
        self._events.append((event, info))

    def _drain_events(self) -> None:
        while self._events:
            try:
                event, info = self._events.popleft()
            except IndexError:
                return
            for cb in list(self._listeners):
                cb(event, info)

    # -- wiring ------------------------------------------------------------

    @property
    def address(self) -> str:
        return self.endpoint.address

    def set_peers(self, peers: dict[int, str]) -> None:
        self.endpoint.set_peers(peers)

    def announce_rebind(self, peers: dict[int, str]) -> None:
        """Respawned-rank hello (errmgr/respawn): tell every peer my NEW
        business card so they drop stale routes and reset the wire-seq
        space toward me (≈ endpoint re-establishment in the reference's
        failover pml, pml/bfo).  Rides the send worker like every other
        control frame — safe to call from BTL reader threads; a failed
        send is retried by the rate-limited heal in _on_frame."""
        for peer in peers:
            self._enqueue_frame(peer,
                             {"t": "rebind", "card": self.address,
                              "inc": self.incarnation}, b"", None)

    def close(self) -> None:
        self._closed = True
        if self.ft is not None:
            self.ft.close()   # detector watcher + gossip beater
        self._sendq.put(None)
        self._worker.join(timeout=2.0)
        self.endpoint.close()

    def _matching_for(self, cid: int) -> _Matching:
        m = self._matching.get(cid)
        if m is None:
            m = self._matching[cid] = _Matching()
        return m

    def pending_summary(self, limit: int = 64) -> dict:
        """Pending point-to-point state for the hang doctor's capture:
        posted recvs (peer/tag/cid/age), sends awaiting a peer event
        (rendezvous CTS, sync ack), in-flight rendezvous receives,
        unexpected-queue depth and parked/queued frame counts.  Runs on
        the doctor responder thread — dict walks under the PML lock,
        no blocking work."""
        now = time.monotonic()
        recvs: list[dict] = []
        sends: list[dict] = []
        rndv: list[dict] = []
        with self._lock:
            for req in list(self._doctor_recvs):
                if req.done():
                    continue
                recvs.append({
                    "src": req.source, "tag": req.tag, "cid": req.cid,
                    "age_s": round(now - req.t_posted, 3)})
                if len(recvs) >= limit:
                    break
            for st in list(self._send_states.values()):
                if st.req is not None and st.req.done():
                    continue
                payload = st.payload
                nbytes = (getattr(payload, "nbytes", None)
                          or (len(payload) if payload is not None else 0))
                sends.append({
                    "peer": st.peer, "bytes": int(nbytes),
                    "age_s": round(now - st.t_posted, 3)})
                if len(sends) >= limit:
                    break
            for st in list(self._recv_states.values()):
                if st.req is not None and st.req.done():
                    continue
                rndv.append({
                    "peer": st.peer, "bytes": len(st.data),
                    "received": st.received})
                if len(rndv) >= limit:
                    break
            unexpected = sum(len(m.unexpected)
                             for m in self._matching.values())
            parked = {p: len(v) for p, v in self._parked.items() if v}
        with self._qlock:
            queued = {p: n for p, n in self._queued.items() if n}
        return {"recvs": recvs, "sends": sends, "rndv": rndv,
                "unexpected": unexpected, "parked": parked,
                "queued": queued}

    # -- send side ---------------------------------------------------------

    def isend(self, buf: Any, peer: int, tag: int, cid: int,
              datatype: Optional[Datatype] = None,
              count: Optional[int] = None, mode: str = "standard") -> Request:
        """mode ∈ standard | sync (ssend) | ready (rsend) | buffered (bsend)
        — the four MPI send modes (≈ pml.h:211 MCA_PML_BASE_SEND_*)."""
        if mode not in ("standard", "sync", "ready", "buffered"):
            raise MPIException(
                f"unknown send mode {mode!r} (standard/sync/ready/buffered)")
        ft = self.ft
        if ft is not None:
            # ULFM fail fast: a revoked cid or a detector-declared-dead
            # peer raises NOW (ERR_REVOKED / ERR_PROC_FAILED), not after
            # the 30 s park-and-heal retry window expires
            ft.check_send(peer, cid)
        # compiled fast lane (same-address-space peers): a plain eager
        # contiguous send delivers straight into the peer's posted buffer
        # through its engine — no header object at all on the hot path
        if (mode == "standard"
                and self._eng is not None
                and peer != self.rank
                and not self._listeners
                and self.incarnation == 0
                and datatype is None and count is None
                and isinstance(buf, np.ndarray)
                and buf.flags["C_CONTIGUOUS"]
                and not self._memcheck):
            req = self._isend_fast(buf, peer, tag, cid)
            if req is not None:
                return req
        _reject_device(buf, "isend")
        if self._memcheck:
            from ompi_tpu.core import memchecker

            memchecker.check_send(buf, "isend")
        arr = np.asarray(buf)
        if datatype is None:
            datatype = dt_mod.from_numpy(arr.dtype)
        if count is None:
            count = arr.size // max(1, datatype.elements_per_item)
        # validate BEFORE the plan gate: the zero-copy branch must reject
        # an uncommitted datatype exactly like the staged pack would —
        # the commit error cannot appear or vanish based on whether the
        # layout happens to collapse to one run
        datatype._validate_packing(count, "pack")
        plan = datatype.pack_plan(count)
        nbytes = plan.total
        # zero-copy path: a send whose pack plan collapses to ONE run rides
        # a memoryview of the user's array — no sender-side staging copy
        # (the MPI contract forbids touching the buffer until completion
        # anyway; ≈ pml_ob1_sendreq.h:382-413 sending from the user iovec).
        # This covers contiguous prefixes (count*size < arr.nbytes) and
        # single-run derived layouts, not just whole-buffer sends.
        # Buffered mode always copies: the user may reuse immediately.
        if (mode != "buffered" and plan.single_run
                and arr.flags["C_CONTIGUOUS"]
                and plan.start + plan.total <= arr.nbytes):
            payload = arr.reshape(-1).view(np.uint8).data[
                plan.start:plan.start + plan.total]
            trace_mod.count("pml_zero_copy_sends_total")
        else:
            # non-contiguous: stage through the compiled plan walk into a
            # reusable uint8 buffer (pack_into — no intermediate bytes)
            staged = np.empty(plan.total, np.uint8)
            datatype.pack_into(arr, count, staged)
            payload = staged.data
            trace_mod.count("pml_packed_sends_total")
        req = Request(kind="send")
        on_done = None
        if mode == "buffered":
            # reserve BEFORE allocating a wire seq: a failed reserve must
            # not burn a sequence number (the peer would hold back every
            # later frame waiting for it)
            self.bsend_pool.reserve(len(payload))
            on_done = (lambda n=len(payload):  # noqa: E731
                       self.bsend_pool.release(n))
        with self._lock:
            seq_key = (peer, cid)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
            epoch = self._peer_epoch.get(peer, 0)
            # frames parked OR still queued for this peer: inline would
            # overtake them — everything rides the worker's ordered path
            can_inline = (peer not in self._parked
                          and not self._queued.get(peer, 0))
        hdr = {"tag": tag, "cid": cid, "seq": seq,
               "dt": _dtype_to_wire(datatype.base_np),
               "elems": len(payload) // datatype.base_np.itemsize,
               "shp": list(arr.shape)}
        if epoch:  # frames for a revived peer carry its incarnation
            hdr["ep"] = epoch
        if self.incarnation:  # revived senders stamp their own life number
            hdr["si"] = self.incarnation
        # cross-rank trace correlation: with the flight recorder armed,
        # every eager/rndv frame carries a globally-unique flow id — the
        # send-side span and the matching recv-side span both record it,
        # and tools/trace_export.py turns each pair into a Perfetto flow
        # arrow (send→recv), making inter-rank waits visible in the
        # merged timeline.  Cost when tracing is off: one attribute check.
        fl = 0
        _fl_t0 = 0
        if trace_mod.active:
            hdr["fl"] = fl = self.rank * _FLOW_STRIDE + next(self._ids)
            # the (trace_id, span_id) pair: trace_id scopes the flow id
            # to ONE job's trace — merged timelines from a shared
            # TMPDIR (or a DVM serving many jobs) must not stitch
            # arrows between flows of different jobs that happened to
            # draw the same fl
            hdr["tc"] = trace_mod.trace_id()
            _fl_t0 = trace_mod.begin()
        # eager completion latency (histogram plane, timeline-independent)
        _h_t0 = time.monotonic_ns() if trace_mod.hist_active else 0
        if self._listeners:
            self._emit(EVT_SEND_POST, peer=peer, tag=tag, cid=cid,
                       nbytes=len(payload))
        eager = len(payload) <= var_registry.get("pml_eager_limit")
        if eager and mode in ("sync", "ready"):
            # matched-ack protocol: the frame carries a sync id; the peer
            # acks on match (sync) or nacks when nothing was posted (ready)
            sid = next(self._ids)
            hdr.update(t="eager", sid=sid, sm=mode[0])  # sm: "s" | "r"
            with self._lock:
                self._send_states[sid] = _SendState(req, peer, None, on_done)
            # inline wire write when possible (completion still via sack)
            if not (can_inline
                    and self.endpoint.try_send_inline(peer, hdr, payload)):
                self._enqueue_frame(peer, hdr, payload,
                                    _WireWatch(self, sid))
            if _h_t0 and trace_mod.hist_active:
                trace_mod.record_hist("pml_eager_send_ns",
                                      time.monotonic_ns() - _h_t0)
            if fl and trace_mod.active:
                trace_mod.complete("pml", "eager_send", _fl_t0,
                                   rank=self.rank, peer=peer,
                                   nbytes=len(payload), fl=fl,
                                   tc=trace_mod.trace_id())
        elif eager:
            hdr["t"] = "eager"
            # sendi fast path (≈ pml_ob1_isend.c:89-119): the frame goes
            # out on this thread — no send-worker handoff, which on small
            # hosts is the dominant per-message cost
            if can_inline and self.endpoint.try_send_inline(peer, hdr,
                                                            payload):
                if mode == "buffered":
                    on_done()
                req.complete(None)
            elif mode == "buffered":
                wire = Request(kind="send")
                wire.add_completion_callback(lambda _r: on_done())
                self._enqueue_frame(peer, hdr, payload, wire)
                req.complete(None)  # local completion
            else:
                self._enqueue_frame(peer, hdr, payload, req)
            if _h_t0 and trace_mod.hist_active:
                trace_mod.record_hist("pml_eager_send_ns",
                                      time.monotonic_ns() - _h_t0)
            if fl and trace_mod.active:
                trace_mod.complete("pml", "eager_send", _fl_t0,
                                   rank=self.rank, peer=peer,
                                   nbytes=len(payload), fl=fl,
                                   tc=trace_mod.trace_id())
        else:
            sid = next(self._ids)
            hdr.update(t="rndv", size=len(payload), sid=sid)
            if mode == "ready":
                hdr["sm"] = "r"  # peer nacks instead of queueing unexpected
            state_req = req
            if mode == "buffered":
                wire = Request(kind="send")
                wire.add_completion_callback(lambda _r: on_done())
                state_req = wire
                req.complete(None)  # local completion; pool holds the copy
            with self._lock:
                state = _SendState(
                    state_req, peer, payload,
                    None if mode == "buffered" else on_done)
                state.fl = fl  # rndv_send span (send worker) records it
                self._send_states[sid] = state
            self._enqueue_frame(peer, hdr, b"", _WireWatch(self, sid))
        self._drain_events()
        return req

    def _isend_fast(self, arr: np.ndarray, peer: int, tag: int,
                    cid: int) -> Optional[Request]:
        """Fast lane for plain eager contiguous sends: deliver through
        the same-address-space peer's compiled engine (proc BTL) with no
        header dict.  None ⇒ precondition missed, caller runs the
        general isend.  If the receiver punts (no posted contiguous
        buffer, out-of-order, listeners attached mid-flight) the frame
        falls back to the header path WITH the already-drawn seq — the
        wire order is unaffected."""
        if arr.nbytes > var_registry.get("pml_eager_limit"):
            return None
        ep = self.endpoint
        proc_ok = ep.proc_btl is not None and (
            peer in ep._proc_ok
            or (peer not in ep._proc_no and ep._proc_route(peer)))
        if not proc_ok:
            # cross-process: the lane still applies over shm rings
            if ep.shm_btl is None or not (
                    peer in ep._shm_ok or ep._shm_route(peer)):
                return None
        with self._lock:
            if (peer in self._parked or self._queued.get(peer, 0)
                    or self._peer_epoch.get(peer, 0)):
                return None
            seq_key = (peer, cid)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
        payload = arr.reshape(-1).view(np.uint8).data
        trace_mod.count("pml_zero_copy_sends_total")
        req = Request(kind="send")
        dt = _dtype_to_wire(arr.dtype)
        if proc_ok and ep.proc_btl.send_fast(peer, tag, cid, seq, payload,
                                             dt, arr.size, arr.shape):
            req.complete(None)
            return req
        if (not proc_ok and isinstance(dt, str)
                and self.endpoint.shm_btl is not None):
            # cross-process same-host: publish with the C-built header
            try:
                if self.endpoint.shm_btl.try_send_eager(
                        peer, tag, cid, seq, dt, arr.size, arr.shape,
                        payload):
                    req.complete(None)
                    return req
            except Exception:  # noqa: BLE001 — dead peer/oversize: the
                pass           # header path surfaces it properly
        # receiver declined the fast path — same frame, header route
        hdr = {"tag": tag, "cid": cid, "seq": seq, "dt": dt,
               "elems": arr.size, "shp": list(arr.shape), "t": "eager"}
        if self.endpoint.try_send_inline(peer, hdr, payload):
            req.complete(None)
        else:
            self._enqueue_frame(peer, hdr, payload, req)
        return req

    def _on_frame_fast(self, peer: int, tag: int, cid: int, seq: int,
                       payload, dt, elems: int, shp) -> bool:
        """Receiver half of the fast lane (installed as the proc BTL's
        on_fast hook).  False ⇒ sender must re-send via the header
        path — the engine consumed NOTHING."""
        eng = self._eng
        if eng is None or self.incarnation:
            return False   # post-restart fencing needs the header path
        with self._lock:
            acts = eng.incoming_fast(peer, tag, cid, seq, payload,
                                     dt, elems, shp)
            if acts is None:
                return False
            for act in acts:
                self._apply_action(act)
        self._drain_events()
        return True

    def issend(self, buf, peer, tag, cid, **kw) -> Request:
        """≈ MPI_Issend: completes only once the matching recv is posted."""
        return self.isend(buf, peer, tag, cid, mode="sync", **kw)

    def ibsend(self, buf, peer, tag, cid, **kw) -> Request:
        """≈ MPI_Ibsend: completes locally against the attached buffer."""
        return self.isend(buf, peer, tag, cid, mode="buffered", **kw)

    def irsend(self, buf, peer, tag, cid, **kw) -> Request:
        """≈ MPI_Irsend: erroneous unless the recv is already posted — the
        peer nacks and the request fails."""
        return self.isend(buf, peer, tag, cid, mode="ready", **kw)

    def send(self, buf: Any, peer: int, tag: int, cid: int, **kw) -> None:
        self.isend(buf, peer, tag, cid, **kw).wait()

    # -- recv side ---------------------------------------------------------

    def irecv(self, buf: Optional[np.ndarray], source: int, tag: int,
              cid: int, datatype: Optional[Datatype] = None,
              count: Optional[int] = None) -> RecvRequest:
        if buf is not None:
            _reject_device(buf, "irecv")
            buf = np.asarray(buf)
            if self._memcheck:
                from ompi_tpu.core import memchecker

                memchecker.prepare_recv(buf, "irecv")
            if datatype is None:
                datatype = dt_mod.from_numpy(buf.dtype)
            if count is None:
                count = buf.size // max(1, datatype.elements_per_item)
        # buf=None with datatype/count=None is the allocate-on-match path:
        # the element dtype travels in the wire header
        req = RecvRequest(buf, datatype, count, source, tag, cid)
        req.rid = next(self._ids)
        req._pml = self
        ft = self.ft
        if ft is not None:
            ft.check_cid(cid)   # revoked comm: fail before posting
            ft.track_recv(req)  # a later revoke/peer-death can poison it
        if self._listeners:
            self._emit(EVT_RECV_POST, peer=source, tag=tag, cid=cid)
        with self._lock:
            # under the PML lock: pending_summary() iterates this set
            # under the same lock, and a WeakSet is not safe against a
            # concurrent add mid-iteration
            self._doctor_recvs.add(req)
            if self._eng is not None:
                barr = None
                if (buf is not None and datatype is not None
                        and datatype.is_contiguous
                        and buf.flags["C_CONTIGUOUS"]):
                    barr = buf   # registered for native fast delivery
                hit = self._eng.post(
                    cid, req.source, req.tag, req, barr,
                    datatype.base_np.itemsize if datatype is not None
                    else 1,
                    count * datatype.size
                    if (count is not None and datatype is not None)
                    else -1)
                if hit is not None:
                    peer, hdr, payload = hit
                    if self._listeners:
                        self._emit(EVT_MATCH, peer=peer, tag=hdr["tag"],
                                   cid=hdr["cid"])
                    self._match(req, peer, hdr, payload)
            else:
                m = self._matching_for(cid)
                # try the unexpected queue first, in arrival order
                for i, (peer, hdr, payload) in enumerate(m.unexpected):
                    if _hdr_matches(req, peer, hdr):
                        del m.unexpected[i]
                        if self._listeners:
                            self._emit(EVT_MATCH, peer=peer,
                                       tag=hdr["tag"], cid=hdr["cid"])
                        self._match(req, peer, hdr, payload)
                        break
                else:
                    m.posted.append(req)
        if (ft is not None and source >= 0 and not req.done()
                and ft.detector.is_dead(source, poll=False)):
            # named-source recv from a corpse that left no matching
            # message behind: it can never complete — ULFM semantics say
            # ERR_PROC_FAILED now, not a hang
            ft._fail_recv(req, MPIException(
                f"rank {source} has failed", error_class=ERR_PROC_FAILED))
        self._drain_events()
        return req

    def recv(self, buf: Optional[np.ndarray], source: int, tag: int, cid: int,
             datatype: Optional[Datatype] = None, count: Optional[int] = None,
             status: Optional[Status] = None) -> np.ndarray:
        req = self.irecv(buf, source, tag, cid, datatype, count)
        out = self._progress_wait(req)
        if status is not None:
            status.__dict__.update(req.status.__dict__)
        return out

    def _progress_wait(self, req: Request):
        """Receiver-pull progress (≈ opal_progress running in the waiting
        thread): while blocked on a recv, THIS thread drains its own shm
        rings through the engine — the frame that completes the request
        is matched and copied here, with no poller-thread futex handoff
        on the critical path.  Only engages when shm rings exist (frames
        from another process): for in-process peers the sender's thread
        delivers directly, and a GIL-holding spin would steal exactly
        the cycles it is waiting for (measured, see request.py)."""
        shm = self.endpoint.shm_btl
        if self._eng is None or shm is None or req.done():
            return self._tcp_pull_wait(req)
        readers = shm.reader_list()
        if not readers:
            return self._tcp_pull_wait(req)
        # spin style by core count: on a 1-2 core host the frame we are
        # waiting for is PRODUCED by the process we'd be starving, so
        # yield every iteration (stay runnable, let the sender run — the
        # doorbell path would pay a double futex wake: doorbell→poller→
        # event→us); on bigger hosts yield rarely (a sched_yield per
        # iteration invites the kernel to deschedule us right when the
        # frame lands)
        yield_every = _SMALL_HOST
        shm.pull_depth += 1   # poller backs off while we drain
        try:
            spins = 0
            while not req.done():
                progressed = 0
                for r in readers:
                    try:
                        progressed += self._drain_shm(r)
                    except OSError as e:  # corrupt ring already recovered
                        _log.error("receiver-pull drain: %r", e)
                if progressed:
                    spins = 0
                    continue
                spins += 1
                if spins > 4000:   # a few ms of spinning, then sleep
                    break
                if yield_every:
                    time.sleep(0)
                if not spins % 64:
                    readers = shm.reader_list()   # new rings mid-wait
                    if not yield_every:
                        time.sleep(0)
        finally:
            shm.pull_depth -= 1
        return req.wait()

    def _tcp_pull_wait(self, req: Request):
        """Receiver-pull over the native tcp plane: while blocked, THIS
        thread runs the poller's bounded service pass (btl progress()),
        so the frame that completes the request is parsed, matched and
        copied here — no poller wake, no completion-event handoff.
        Each pass is one GIL-released poll slice; request state (FT
        failure included — fail() flips done()) is re-checked between
        slices.  Falls back to the event wait the moment the native
        plane declines (var off, closing, no connections yet): the
        parked poller thread is always running as the backstop."""
        ep = self.endpoint
        tcp = ep.tcp_btl
        # tcp-only endpoints: with proc or shm lanes present the frame
        # may arrive off-plane, and a poll slice here would only delay
        # seeing that completion
        if (tcp is None or not getattr(tcp, "_native_ok", False)
                or ep.proc_btl is not None or ep.shm_btl is not None
                or not var_registry.get("btl_tcp_pull")):
            return req.wait()
        tcp.pull_depth += 1
        try:
            while not req.done():
                if not tcp.progress():
                    break
        finally:
            tcp.pull_depth -= 1
        return req.wait()

    def _drain_shm(self, reader) -> int:
        """The shm BTL's drain hook: decode + seq-gate + match a batch of
        ring frames in one C call under the PML lock.  Control frames
        (cts/sack/rebind/…) and respawn-stamped data frames come back as
        punts and re-enter the full _on_frame after the lock drops — a
        ring never mixes incarnations, so fast frames and punted ones
        cannot be reordered against each other within a stream."""
        eng = self._eng
        punts = None
        _t0 = trace_mod.begin() if trace_mod.active else 0
        try:
            with self._lock:
                new_tail, n, acts = eng.drain_ring(
                    reader.peer, reader._mm, reader._tail, 64)
                reader._tail = new_tail
                for act in acts:
                    if act[0] == "frame":
                        if punts is None:
                            punts = []
                        punts.append(act)
                    else:
                        self._apply_action(act)
        except self._fast.Unsupported:
            # a header tag only the python codec knows: drain this batch
            # through the python framing path instead (same counter +
            # span accounting as the fused path — frames delivered here
            # must not read as lost in the publish/drain pvar pair)
            n = reader.poll(self._on_frame)
            if n:
                trace_mod.count("btl_shm_drained_total", n)
                if _t0 and trace_mod.active:
                    trace_mod.complete("pml", "shm_drain_batch", _t0,
                                       rank=self.rank, peer=reader.peer,
                                       frames=n)
            return n
        except ValueError as e:
            # corrupt stream: same recovery as ShmRingReader.poll —
            # nothing trustworthy to advance by; discard and surface
            head = int(reader._ctr[0])
            dropped = head - reader._tail
            reader._tail = head
            reader._ctr[1] = head
            raise OSError(
                f"btl/shm: corrupt ring from peer {reader.peer} "
                f"({e}); {dropped} pending bytes discarded") from None
        if punts:
            for _k, hdr, payload in punts:
                self._on_frame(reader.peer, hdr, payload)
        if n:
            trace_mod.count("btl_shm_drained_total", n)
            if _t0 and trace_mod.active:
                trace_mod.complete("pml", "shm_drain_batch", _t0,
                                   rank=self.rank, peer=reader.peer,
                                   frames=n)
            self._drain_events()
        return n

    # -- probe -------------------------------------------------------------

    def iprobe(self, source: int, tag: int, cid: int) -> Optional[Status]:
        ft = self.ft
        if ft is not None:
            ft.check_cid(cid)
        with self._lock:
            return self._iprobe_locked(source, tag, cid)

    def probe(self, source: int, tag: int, cid: int,
              timeout: Optional[float] = None) -> Status:
        ft = self.ft
        if ft is not None:
            ft.check_cid(cid)
        # deadline computed ONCE: every unexpected frame notifies the cv,
        # so restarting the full timeout per wakeup would never expire
        # under unrelated traffic
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                st = self._iprobe_locked(source, tag, cid)
                if st is not None:
                    return st
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    raise TimeoutError("probe timed out")
                self._cv.wait(timeout=left)

    def _iprobe_locked(self, source: int, tag: int, cid: int) -> Optional[Status]:
        if self._eng is not None:
            hit = self._eng.iprobe(cid, source, tag)
            if hit is None:
                return None
            peer, hdr = hit
            st = Status()
            st.source = peer
            st.tag = hdr["tag"]
            st.count = hdr.get("elems", hdr.get("size", 0))
            st.count_bytes = hdr.get("size")
            return st
        probe = RecvRequest(None, dt_mod.BYTE, 0, source, tag, cid)
        for peer, hdr, payload in self._matching_for(cid).unexpected:
            if _hdr_matches(probe, peer, hdr):
                st = Status()
                st.source = peer
                st.tag = hdr["tag"]
                st.count = hdr.get("elems", hdr.get("size", len(payload)))
                st.count_bytes = hdr.get("size", len(payload))
                return st
        return None

    # -- matched probe (≈ ompi/mpi/c/mprobe.c, improbe.c, mrecv.c) ---------

    def improbe(self, source: int, tag: int,
                cid: int) -> Optional[tuple[Message, Status]]:
        """Match-and-detach: the matched frame leaves the unexpected
        queue atomically under the PML lock, so a racing recv or probe in
        another thread can never see it — the race MPI_Mprobe exists to
        close (a plain probe's status can be stolen by another thread's
        wildcard recv before this thread posts its own)."""
        ft = self.ft
        if ft is not None:
            ft.check_cid(cid)
        with self._lock:
            return self._improbe_locked(source, tag, cid)

    def _improbe_locked(self, source: int, tag: int,
                        cid: int) -> Optional[tuple[Message, Status]]:
        if self._eng is not None:
            hit = self._eng.improbe(cid, source, tag)
            if hit is None:
                return None
            peer, hdr, payload = hit
            return self._detach_message(peer, hdr, payload)
        probe = RecvRequest(None, dt_mod.BYTE, 0, source, tag, cid)
        m = self._matching_for(cid)
        for i, (peer, hdr, payload) in enumerate(m.unexpected):
            if _hdr_matches(probe, peer, hdr):
                del m.unexpected[i]
                return self._detach_message(peer, hdr, payload)
        return None

    def _detach_message(self, peer: int, hdr: dict,
                        payload) -> tuple[Message, Status]:
        """With self._lock held: finish a match-and-detach on an
        unexpected frame just removed from the queue."""
        if hdr.get("sm") == "s":
            # matching happens HERE: a sync-mode sender completes
            # at match time (the MPI ssend contract — the recv
            # has "started"), not when mrecv later drains it
            self._enqueue_frame(
                peer, {"t": "sack", "sid": hdr["sid"]}, b"", None)
            hdr = {k: v for k, v in hdr.items()
                   if k not in ("sm", "sid")}
        st = Status()
        st.source = peer
        st.tag = hdr["tag"]
        st.count = hdr.get("elems", hdr.get("size", len(payload)))
        st.count_bytes = hdr.get("size", len(payload))
        return Message(self, peer, hdr, payload), st

    def mprobe(self, source: int, tag: int, cid: int,
               timeout: Optional[float] = None) -> tuple[Message, Status]:
        ft = self.ft
        if ft is not None:
            ft.check_cid(cid)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                out = self._improbe_locked(source, tag, cid)
                if out is not None:
                    return out
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    raise TimeoutError("mprobe timed out")
                self._cv.wait(timeout=left)

    def imrecv(self, buf: Optional[np.ndarray], message: Message,
               datatype: Optional[Datatype] = None,
               count: Optional[int] = None,
               status_source: Optional[int] = None) -> RecvRequest:
        """Receive the detached message; consumes the handle.  Eager
        payloads deliver immediately; a detached rendezvous replies with
        its CTS now, exactly as a matching irecv would have.
        ``status_source``: value to report as status.source instead of
        the wire peer (the comm layer passes the group rank)."""
        if message.no_proc:
            req = RecvRequest(None, dt_mod.BYTE, 0, -1, -1, -1)
            req.status.source = PROC_NULL
            req.status.tag = ANY_TAG
            req.status.count = 0
            req.complete(np.empty(0, dtype=np.uint8))
            return req
        if message.consumed:
            raise MPIException("message handle was already received")
        message.consumed = True
        if buf is not None:
            _reject_device(buf, "imrecv")
            buf = np.asarray(buf)
            if self._memcheck:
                from ompi_tpu.core import memchecker

                memchecker.prepare_recv(buf, "imrecv")
            if datatype is None:
                datatype = dt_mod.from_numpy(buf.dtype)
            if count is None:
                count = buf.size // max(1, datatype.elements_per_item)
        req = RecvRequest(buf, datatype, count, message.peer,
                          message.hdr["tag"], message.hdr["cid"])
        req.rid = next(self._ids)
        req._pml = self
        if status_source is not None:
            req.source_override = status_source
        if self._listeners:  # balanced post/match pair, like irecv's path
            self._emit(EVT_RECV_POST, peer=message.peer,
                       tag=message.hdr["tag"], cid=message.hdr["cid"])
            self._emit(EVT_MATCH, peer=message.peer,
                       tag=message.hdr["tag"], cid=message.hdr["cid"])
        with self._lock:
            self._match(req, message.peer, message.hdr, message.payload)
        self._drain_events()
        return req

    def mrecv(self, buf: Optional[np.ndarray], message: Message,
              datatype: Optional[Datatype] = None,
              count: Optional[int] = None,
              status: Optional[Status] = None) -> np.ndarray:
        req = self.imrecv(buf, message, datatype, count)
        out = req.wait()
        if status is not None:
            status.__dict__.update(req.status.__dict__)
        return out

    # -- frame handling (reader threads; NEVER blocking-send here) ---------

    def _adopt_incarnation(self, peer: int, inc: int) -> None:
        """With self._lock held: reset the wire-seq space toward a peer
        whose new incarnation we just learned about (idempotent; called
        from the rebind frame AND from the 'si' stamp on data frames, so
        a data frame outrunning the rebind across transports still lands
        in the fresh seq space instead of the stale one)."""
        if self._peer_inc.get(peer, 0) >= inc:
            return
        self._peer_inc[peer] = inc
        # single choke point for "this peer came back as a new life"
        # (reached from the rebind frame AND the si-stamp fast path);
        # frames sent into the dead incarnation's ring are gone — the
        # event lets a sender-side message log replay them (_emit only
        # enqueues; dispatch happens outside this lock)
        self._emit(EVT_PEER_REVIVED, peer=peer, incarnation=inc)
        # frames toward the revived peer must carry ep >= its incarnation
        # (its receiver fences lower epochs) — learned here even when the
        # 'si' stamp outran the rebind frame that also updates the card
        self._peer_epoch[peer] = max(self._peer_epoch.get(peer, 0), inc)
        for key in [k for k in self._seq if k[0] == peer]:
            del self._seq[key]
        for key in [k for k in self._recv_seq if k[0] == peer]:
            del self._recv_seq[key]
        for key in [k for k in self._held if k[0] == peer]:
            del self._held[key]
        if self._eng is not None:   # the engine owns the recv-side gate
            self._eng.reset_peer(peer)
        # re-stamp parked frames NOW, under the same lock that reset the
        # counters: they are the oldest traffic to the new incarnation and
        # must hold the FRONT of the fresh seq space — a later isend
        # drawing seq 0 before the heal flush restamped would deliver
        # newer data first (non-overtaking violation).  The generation
        # bump tells an in-flight heal delivery that its (stale-stamped)
        # copy was fenced by the receiver and the frame must be re-sent.
        self._route_gen[peer] = self._route_gen.get(peer, 0) + 1
        for hdr, _payload, _req in self._parked.get(peer, []):
            self._restamp_if_stale(peer, hdr)
        # ...then the frames still sitting in the send queue, in FIFO
        # order (they are younger than every parked frame — parked frames
        # left the queue earlier).  Without this, a frame queued before
        # the adopt would draw its fresh seq only at delivery time, AFTER
        # a newer isend already took an earlier seq: non-overtaking
        # violated in the respawn race window.
        with self._qlock:
            for qhdr in self._inqueue.get(peer, ()):
                self._restamp_if_stale(peer, qhdr)

    def note_peer_si(self, peer: int, si: int) -> tuple[bool, bool]:
        """Reader-thread half of the incarnation fence, shared by the
        PML data path and the FT control path so the two planes cannot
        drift: fence a frame sent by a DEAD life of ``peer``, adopt a
        newer life.  Returns ``(fenced, adopted)`` — ``fenced``: drop
        the frame (stale si, or an unstamped frame from a peer whose
        reincarnation we already adopted); ``adopted``: ``si`` is a new
        life (the adopt TRANSITION — the caller should treat the frame
        as revival evidence via ``ft.peer_reincarnated``, outside this
        lock).  The lock is taken only when incarnations are in play
        (an si stamp, or this peer's already-adopted revival): one rank
        reviving must not put every other peer's frames on the hot PML
        lock."""
        if not (si or peer in self._peer_inc):
            return False, False
        with self._lock:
            known = self._peer_inc.get(peer, 0)
            if si < known:
                return True, False
            if si:
                self._adopt_incarnation(peer, si)
                return False, si > known
        return False, False

    def _restamp_if_stale(self, peer: int, hdr: dict) -> None:
        """With self._lock held: a seq-carrying frame stamped for an older
        incarnation of ``peer`` gets a fresh seq + the current epoch (its
        old stamp would be fenced by the revived receiver).  Idempotent —
        a frame whose epoch already matches is left alone."""
        epoch = self._peer_epoch.get(peer, 0)
        if "seq" not in hdr or not epoch or hdr.get("ep", 0) == epoch:
            return
        key = (peer, hdr["cid"])
        hdr["seq"] = self._seq.get(key, 0)
        self._seq[key] = hdr["seq"] + 1
        hdr["ep"] = epoch

    def _heal_reannounce(self, peer: int) -> None:
        """Fence-heal half of the incarnation protocol, shared by the
        PML data fence and the FT control fence: a peer stamping frames
        for our dead life never processed our rebind — re-announce (via
        the send worker; rate-limited to one per second per peer so a
        chatty stale sender cannot flood) instead of fencing it out
        forever."""
        now = time.monotonic()
        with self._lock:
            need = now >= self._reannounce_at.get(peer, 0.0)
            if need:
                self._reannounce_at[peer] = now + 1.0
        if need:
            self.announce_rebind({peer: ""})

    def _on_frame(self, peer: int, hdr: dict, payload: bytes) -> None:
        t = hdr["t"]
        if t in ("eager", "rndv"):
            if hdr.get("ep", 0) < self.incarnation:
                # a frame addressed to a previous life of this rank (it
                # was queued before the sender processed our rebind) —
                # lost with the old incarnation, like any in-flight data
                # at the failure point; holding it would park it forever.
                # Re-announce (rate-limited, via the send worker — a
                # blocking send would stall this reader thread) so a lost
                # rebind announce heals instead of fencing the peer out.
                _log.verbose(1, "dropping pre-restart frame from %d "
                             "(ep %d < %d)", peer, hdr.get("ep", 0),
                             self.incarnation)
                self.pvar_fenced.inc()
                self._heal_reannounce(peer)
                return
            si = hdr.get("si", 0)
            if si:
                # (si-gated: unstamped data frames ride the seq/ep
                # machinery instead of the incarnation fence)
                fenced, adopted = self.note_peer_si(peer, si)
                if fenced:
                    return  # residual frame from a dead incarnation
                # an si-stamped data frame can outrun the rebind frame
                # across transports: it is the same revival evidence, so
                # it must also un-declare a locally-held death (and
                # reset gossip clocks) BEFORE the drain below spawns the
                # msglog auto-replay — the detector would otherwise fail
                # the replay's sends, and the one-shot revive event
                # would be lost for good.  Only on the adopt TRANSITION:
                # a revived sender stamps si on every frame for the rest
                # of the job, and paying two more lock acquisitions per
                # frame on this reader thread would tax steady-state
                # traffic forever (a same-life false local declare still
                # heals via the reap / next detector poll)
                if adopted and self.ft is not None:
                    self.ft.peer_reincarnated(peer, si)
            with self._lock:
                if self._eng is not None:
                    # seq gate + matching in the compiled engine; the
                    # protocol actions come back for this thread to run
                    for act in self._eng.incoming(peer, hdr, payload):
                        self._apply_action(act)
                else:
                    # per-(peer, cid) sequence enforcement: TCP + one
                    # reader already guarantee order, but a non-FIFO BTL
                    # (shm rings, multi-rail) must not break matching
                    # order — frames arriving early are held back
                    key = (peer, hdr["cid"])
                    seq, expected = hdr["seq"], self._recv_seq.get(key, 0)
                    if seq != expected:
                        # held frames outlive the sender's call: own the
                        # bytes (a zero-copy self-BTL payload aliases the
                        # user buffer)
                        if isinstance(payload, memoryview):
                            payload = bytes(payload)
                        self._held.setdefault(key, {})[seq] = (hdr, payload)
                        return
                    self._match_incoming(peer, hdr, payload)
                    nxt = expected + 1
                    held = self._held.get(key)
                    while held and nxt in held:
                        h2, p2 = held.pop(nxt)
                        self._match_incoming(peer, h2, p2)
                        nxt += 1
                    self._recv_seq[key] = nxt
            self._drain_events()
        elif t == "cts":
            with self._lock:
                state = self._send_states.pop(hdr["sid"], None)
            if state is not None:
                self._sendq.put(("rndv_data", state, hdr["rid"]))
        elif t == "data":
            self._on_data(hdr, payload)
        elif t == "sack":   # sync/ready send matched on the peer
            with self._lock:
                state = self._send_states.pop(hdr["sid"], None)
            if state is not None:
                if state.on_done:
                    state.on_done()
                state.req.complete(None)
        elif t == "rebind":  # peer was respawned; adopt its new identity
            with self._lock:
                self.endpoint.rebind(peer, hdr["card"])
                # restart the wire-sequence space toward the revived peer
                # (idempotent with the 'si' fast path — whichever frame
                # arrives first wins).  Frames already stamped with old
                # seqs (sitting in the send queue) carry ep < the peer's
                # new incarnation and are DROPPED by its receiver —
                # without the epoch fence they would park forever.
                inc = hdr.get("inc", 1)
                known = self._peer_inc.get(peer, 0)
                self._peer_epoch[peer] = inc
                self._adopt_incarnation(peer, inc)
            # direct revival evidence for the failure detector: under
            # selfheal the runtime's dead window can be shorter than a
            # poll period, so the rebind frame itself must un-declare —
            # and it must do so BEFORE the event dispatch below spawns
            # the msglog auto-replay, whose sends would otherwise race
            # a still-held local death mark.  Only on the adopt
            # TRANSITION, like the si paths: rebind frames are also the
            # rate-limited fence-heal re-announce of an ESTABLISHED
            # life, and an in-flight re-announce from a life that has
            # since been declared hung must not cancel that (newer)
            # suspicion — nor its stale-gated wedge-escape retry
            if inc > known and self.ft is not None:
                self.ft.peer_reincarnated(peer, inc)
            # the adopt enqueued EVT_PEER_REVIVED — dispatch NOW (outside
            # the lock, per the listener contract): a blocked survivor
            # may never issue another call that would drain, and the
            # msglog auto-replay hanging off this event is what unblocks
            # the revived peer
            self._drain_events()
        elif t == "rnack":  # ready send found no posted recv
            with self._lock:
                state = self._send_states.pop(hdr["sid"], None)
            if state is not None:
                if state.on_done:
                    state.on_done()
                state.req.fail(MPIException(
                    "rsend: no matching receive was posted at the peer",
                    error_class=4))
        elif t == "ft":  # ULFM control plane (revoke / agree / gossip)
            from ompi_tpu.mpi import ft as ft_mod

            ft_mod.pml_ft(self).on_ft_frame(peer, hdr)
            # the FT plane may have adopted a revived peer's incarnation
            # (si stamp outrunning the rebind frame): dispatch the
            # enqueued EVT_PEER_REVIVED now so msglog auto-replay runs
            self._drain_events()
        else:
            _log.error("unknown frame type %r from %d", t, peer)

    def _apply_action(self, act: tuple) -> None:
        """With self._lock held: execute one engine action — the
        protocol step the compiled matcher handed back."""
        kind = act[0]
        if kind == "match":
            _, req, peer, hdr, payload = act
            if self._listeners:
                self._emit(EVT_MATCH, peer=peer, tag=hdr["tag"],
                           cid=hdr["cid"])
            self._match(req, peer, hdr, payload)
        elif kind == "unexpected":
            _, peer, hdr = act
            self._cv.notify_all()
            if self._listeners:
                self._emit(EVT_UNEXPECTED, peer=peer,
                           tag=hdr["tag"], cid=hdr["cid"])
        elif kind == "done":
            # native fast delivery: payload already memcpy'd into the
            # posted buffer — only status + completion remain
            _, req, peer, tag, count, nbytes = act
            if self._listeners:
                self._emit(EVT_MATCH, peer=peer, tag=tag, cid=req.cid)
                self._emit(EVT_DELIVER, peer=peer, tag=tag, cid=req.cid,
                           nbytes=nbytes)
            ov = req.source_override
            req.status.source = peer if ov is None else ov
            req.status.tag = tag
            req.status.count = count
            req.status.count_bytes = nbytes
            req.complete(req.buf)
        elif kind == "adeliver":
            # fast-lane frame matched an allocate-on-match recv: build
            # the array from the C-owned bytes via the normal deliver
            _, req, peer, tag, payload, dtspec, shp = act
            if self._listeners:
                self._emit(EVT_MATCH, peer=peer, tag=tag, cid=req.cid)
            # the synthetic header must carry cid: _deliver's
            # EVT_DELIVER emit reads hdr["cid"] when listeners are
            # attached (a listener-bearing receiver crashed here when a
            # listenerless same-address-space peer fast-sent to an
            # allocate-on-match recv)
            self._deliver(req, peer,
                          {"tag": tag, "cid": req.cid, "dt": dtspec,
                           "shp": list(shp)},
                          payload)
        elif kind == "rnack":  # ready-mode send found no posted recv
            _, peer, hdr = act
            self._enqueue_frame(peer, {"t": "rnack", "sid": hdr["sid"]},
                                b"", None)
        else:
            _log.error("unknown engine action %r", kind)

    def _match_incoming(self, peer: int, hdr: dict, payload: bytes) -> None:
        """Called with self._lock held: match one in-order frame."""
        m = self._matching_for(hdr["cid"])
        req = None
        for i, cand in enumerate(m.posted):
            if _hdr_matches(cand, peer, hdr):
                del m.posted[i]
                req = cand
                break
        if req is None:
            if hdr.get("sm") == "r":  # ready-mode: erroneous, nack sender
                self._enqueue_frame(peer,
                                 {"t": "rnack", "sid": hdr["sid"]}, b"",
                                 None)
                return
            # zero-copy self-BTL payloads alias the sender's live buffer —
            # an unexpected frame must own its bytes (the sender is free to
            # modify once its request completes)
            if isinstance(payload, memoryview):
                payload = bytes(payload)
            m.unexpected.append((peer, hdr, payload))
            self._cv.notify_all()
            if self._listeners:
                self._emit(EVT_UNEXPECTED, peer=peer,
                           tag=hdr["tag"], cid=hdr["cid"])
        else:
            if self._listeners:
                self._emit(EVT_MATCH, peer=peer, tag=hdr["tag"],
                           cid=hdr["cid"])
            self._match(req, peer, hdr, payload)

    def _match(self, req: RecvRequest, peer: int, hdr: dict,
               payload: bytes) -> None:
        """Called with self._lock held. Eager: deliver now. Rndv: send CTS."""
        if hdr["t"] == "eager":
            if "sm" in hdr:  # sync/ready sender waits for the matched-ack
                self._enqueue_frame(peer,
                                 {"t": "sack", "sid": hdr["sid"]}, b"",
                                 None)
            self._deliver(req, peer, hdr, payload)
        else:  # rndv
            # fragments land directly in the user buffer when it is posted,
            # plan-collapsed (one run from offset 0 — contiguous layouts
            # and single-run derived types alike), and large enough (no
            # intermediate staging buffer)
            direct = False
            if (req.buf is not None and req.datatype is not None
                    and req.buf.flags["C_CONTIGUOUS"]
                    and req.buf.nbytes >= hdr["size"]):
                if req.datatype.committed:
                    # Uncommitted types fall to the staged path, whose
                    # unpack fails the request with the same error the
                    # send side raises — for ANY count spelling.
                    # Decide from the commit-warmed count=1 plan (cached,
                    # O(1)) — building the count-N plan (or touching
                    # is_contiguous, which materializes the segment
                    # descriptor of affine types) would run a potentially
                    # multi-MB expansion on the reader thread UNDER the
                    # PML lock, only to be discarded when the answer is
                    # False.  N items collapse iff one item does AND
                    # items abut (extent == size), or count == 1.
                    p1 = req.datatype.pack_plan(1)
                    one_ok = p1.single_run and p1.start == 0
                    if req.count is not None:
                        direct = (one_ok
                                  and (req.count == 1
                                       or req.datatype.extent
                                       == req.datatype.size)
                                  and req.count * req.datatype.size
                                  >= hdr["size"])
                    else:
                        direct = (one_ok and req.datatype.extent
                                  == req.datatype.size)
            self._recv_states[req.rid] = _RecvState(
                req, hdr["size"], hdr, peer, direct=direct)
            # CTS is a tiny control frame; safe to enqueue (never inline-send
            # from a reader thread)
            self._enqueue_frame(peer,
                             {"t": "cts", "sid": hdr["sid"], "rid": req.rid},
                             b"", None)

    def _rndv_sink(self, hdr: dict, nbytes: int):
        """btl/tcp zero-copy landing hook: hand the poller the
        destination slice for an in-flight "data" frame's payload, or
        None (⇒ the btl stages the bytes and delivers normally)."""
        if hdr.get("t") != "data":
            return None
        with self._lock:
            state = self._recv_states.get(hdr.get("rid"))
            if state is None or not state.direct:
                return None
            off = hdr.get("off", 0)
            if (not isinstance(off, int) or off < 0
                    or off + nbytes > len(state.data)):
                return None   # malformed offset: staged path bounds it
            return state.data[off:off + nbytes]

    def _rndv_sink_done(self, hdr: dict, nbytes: int) -> None:
        """Completion half of _rndv_sink: the payload already sits in
        the user buffer, so account for it without a copy."""
        self._on_data(hdr, b"", landed=nbytes)

    def _on_data(self, hdr: dict, payload: bytes,
                 landed: Optional[int] = None) -> None:
        nbytes = len(payload) if landed is None else landed
        with self._lock:
            state = self._recv_states.get(hdr["rid"])
            if state is None:
                return
            off = hdr["off"]
            if landed is None:
                if state.direct:
                    state.data[off:off + nbytes] = \
                        np.frombuffer(payload, np.uint8)
                else:
                    state.data[off:off + nbytes] = payload
            state.received += nbytes
            done = state.received >= len(state.data)
            if done:
                del self._recv_states[hdr["rid"]]
        if done:
            if state.trace_t0 and trace_mod.active:
                _fl = state.src_hdr.get("fl", 0)
                _tc = state.src_hdr.get("tc")
                trace_mod.complete(
                    "pml", "rndv_recv", state.trace_t0, rank=self.rank,
                    peer=state.peer, nbytes=len(state.data),
                    direct=state.direct,
                    **({"fl": _fl} if _fl else {}),
                    **({"tc": _tc} if _tc is not None else {}))
            if state.direct:
                self._complete_direct(state)
            else:
                self._deliver(state.req, state.peer, state.src_hdr,
                              bytes(state.data))
            self._drain_events()

    def _complete_direct(self, state: _RecvState) -> None:
        """Fragments already landed in the user buffer; just finish."""
        req, hdr = state.req, state.src_hdr
        nbytes = len(state.data)
        if self._listeners:
            self._emit(EVT_DELIVER, peer=state.peer, tag=hdr["tag"],
                       cid=hdr["cid"], nbytes=nbytes)
        req.status.source = state.peer
        req.status.tag = hdr["tag"]
        req.status.count = nbytes // req.datatype.base_np.itemsize
        req.status.count_bytes = nbytes
        req.complete(req.buf)

    def _deliver(self, req: RecvRequest, peer: int, hdr: dict,
                 payload: bytes) -> None:
        """Unpack payload into the request's buffer and complete it."""
        # flow correlation: the recv half of an eager frame's arrow (the
        # rndv path records fl on its rndv_recv span instead)
        _fl = (hdr.get("fl", 0)
               if trace_mod.active and hdr.get("t") == "eager" else 0)
        _fl_t0 = trace_mod.begin() if _fl else 0
        datatype = req.datatype
        if datatype is not None and req.count is not None:
            expected = req.count * datatype.size
            if len(payload) > expected:
                req.status.source = peer
                req.status.tag = hdr["tag"]
                req.fail(MPIException(
                    f"message truncated: {len(payload)}B arrived, recv "
                    f"posted for {expected}B", error_class=ERR_TRUNCATE))
                return
        if req.buf is None:
            elem_np = (datatype.base_np if datatype is not None
                       else _wire_to_dtype(hdr["dt"]))
            n_elems = len(payload) // elem_np.itemsize
            out = np.frombuffer(
                bytearray(payload[:n_elems * elem_np.itemsize]),
                dtype=elem_np)
            # allocate-on-match receives recover the sender's array shape
            # from the header (predefined contiguous dtypes only; derived
            # datatypes keep the flat element stream; 0-d sends stay 1-D —
            # recv() has always returned at least a 1-element vector)
            shp = hdr.get("shp")
            if (datatype is None and shp
                    and math.prod(shp) == n_elems):
                out = out.reshape(shp)
        else:
            out = req.buf
            items = len(payload) // max(1, datatype.size)
            try:
                datatype.unpack(payload, out, items)
            except MPIException as e:
                # unpack validation (uncommitted type, bad sizing) runs
                # on a BTL receive thread — route it to the waiting recv
                # instead of killing the reader / hanging the request
                req.status.source = peer
                req.status.tag = hdr["tag"]
                req.fail(e)
                return
        if self._listeners:
            self._emit(EVT_DELIVER, peer=peer, tag=hdr["tag"],
                       cid=hdr["cid"], nbytes=len(payload))
        ov = req.source_override
        req.status.source = peer if ov is None else ov
        req.status.tag = hdr["tag"]
        elem_size = (datatype.base_np.itemsize if datatype is not None
                     else _wire_to_dtype(hdr["dt"]).itemsize)
        req.status.count = len(payload) // elem_size
        req.status.count_bytes = len(payload)
        req.complete(out)
        if _fl and trace_mod.active:
            _tc = hdr.get("tc")
            trace_mod.complete("pml", "eager_recv", _fl_t0,
                               rank=self.rank, peer=peer,
                               nbytes=len(payload), fl=_fl,
                               **({"tc": _tc} if _tc is not None
                                  else {}))

    # -- send worker (the only thread that writes payloads) ----------------

    def _enqueue_frame(self, peer, hdr, payload, req) -> None:
        """Queue one frame for the send worker, tracking the per-peer
        in-queue count: inline sendi must not run while ANY frame for the
        peer is still queued, or it would overtake (the queued frame may
        be restamped into a later seq at delivery).  Uses its own lock —
        several callers already hold self._lock."""
        with self._qlock:
            self._queued[peer] = self._queued.get(peer, 0) + 1
            self._inqueue.setdefault(peer, collections.deque()).append(hdr)
            # the put stays inside _qlock so _inqueue's FIFO order matches
            # _sendq's consumption order (the worker popleft must see the
            # same hdr it just dequeued)
            self._sendq.put(("frame", peer, hdr, payload, req))

    def _send_loop(self) -> None:
        frag = var_registry.get("pml_frag_size")
        while True:
            job = self._sendq.get()
            if job is None:
                return
            try:
                if job[0] == "frame":
                    _, peer, hdr, payload, req = job
                    self._deliver_frame(peer, hdr, payload, req)
                elif job[0] == "rndv_data":
                    _, state, rid = job
                    data = state.payload
                    _t0 = (trace_mod.begin()
                           if trace_mod.active or trace_mod.hist_active
                           else 0)
                    offs = list(range(0, len(data), frag))
                    for i, off in enumerate(offs):
                        last = i == len(offs) - 1
                        out = self._deliver_frame(
                            state.peer,
                            {"t": "data", "rid": rid, "off": off},
                            data[off:off + frag],
                            state.req if last else None,
                            tracked=False)
                        if out == "failed":
                            # a hole in the stream: the request must FAIL,
                            # not complete on a later fragment
                            if not last:
                                self._fail_req(state.req, MPIException(
                                    "rendezvous fragment could not be "
                                    "delivered"))
                            break
                    if _t0 and trace_mod.hist_active:
                        trace_mod.record_hist(
                            "pml_rndv_send_ns",
                            time.monotonic_ns() - _t0)
                    if _t0 and trace_mod.active:
                        trace_mod.complete(
                            "pml", "rndv_send", _t0, rank=self.rank,
                            peer=state.peer, nbytes=len(data),
                            fragments=len(offs),
                            **({"fl": state.fl, "tc":
                                trace_mod.trace_id()}
                               if state.fl else {}))
            except Exception:  # noqa: BLE001 — the worker must survive
                _log.error("send worker: unexpected error\n%s",
                           __import__("traceback").format_exc())

    def _dequeue_tracking(self, peer, hdr) -> None:
        """With self._qlock held: retire one frame from the per-peer
        in-queue accounting (count + the restampable header list)."""
        n = self._queued.get(peer, 0)
        if n > 1:
            self._queued[peer] = n - 1
        else:
            self._queued.pop(peer, None)
        q = self._inqueue.get(peer)
        if q:
            head = q.popleft()
            if head is not hdr:  # defensive: FIFO invariant broken
                q.appendleft(head)
                try:
                    q.remove(hdr)
                except ValueError:
                    pass
            if not q:
                self._inqueue.pop(peer, None)

    def _deliver_frame(self, peer, hdr, payload, req, tracked=True) -> str:
        """Send-worker delivery with park-and-heal (≈ pml/bfo's failover
        retransmit): a frame that cannot be routed (peer dead or
        mid-respawn) parks in a per-peer ordered list; a healer retries
        within ``pml_retry_window``; once routes heal (the revived peer's
        rebind reset the seq space and re-stamped the parked frames) the
        healer flushes them in order.  Returns "sent" | "parked" |
        "failed" so multi-fragment callers can react to holes.

        ``tracked`` is False for rendezvous data fragments: they never
        passed through _enqueue_frame, so they must not decrement the
        per-peer queued count (which would let an inline sendi overtake
        frames that ARE still queued)."""
        with self._lock:
            # a frame stamped before an adopt (still queued while the
            # peer re-incarnated) carries a fenced epoch — restamp at
            # delivery, in queue order, so seqs stay monotone with the
            # frames the adopt already restamped in the parked list.
            # Restamp BEFORE retiring the frame from _inqueue (both under
            # self._lock) so an adopt either restamps it in the queue or
            # observes it already restamped — never neither.
            self._restamp_if_stale(peer, hdr)
            if tracked:
                with self._qlock:
                    self._dequeue_tracking(peer, hdr)
            if peer in self._parked:     # keep order behind parked frames
                self._parked[peer].append((hdr, payload, req))
                self.pvar_parked.inc()
                return "parked"
        try:
            self.endpoint.send(peer, hdr, payload)
        except ConnectionError as e:
            ft = self.ft
            if (ft is not None and hdr.get("t") != "ft"
                    and ft.detector.is_dead(peer, poll=False)):
                # the detector already declared the peer dead: parking
                # would only delay the inevitable ERR_PROC_FAILED by the
                # whole retry window
                self._fail_req(req, MPIException(
                    f"rank {peer} has failed ({e})",
                    error_class=ERR_PROC_FAILED))
                return "failed"
            window = float(var_registry.get("pml_retry_window") or 0)
            if window <= 0 or self._closed:
                self._fail_req(req, e)
                return "failed"
            _log.verbose(1, "route to %d failed (%s); parking %r for "
                         "up to %.0fs", peer, e,
                         {k: hdr[k] for k in ("t", "tag", "seq", "cid")
                          if k in hdr}, window)
            with self._lock:
                self._restamp_if_stale(peer, hdr)
                self._parked.setdefault(peer, []).append(
                    (hdr, payload, req))
            self.pvar_parked.inc()
            self._schedule_heal(peer, time.monotonic() + window)
            return "parked"
        except Exception as e:  # noqa: BLE001 — must not kill the loop
            self._fail_req(req, e)
            return "failed"
        self._complete_safely(req)
        return "sent"

    def _complete_safely(self, req) -> None:
        """Completion callbacks are user-extensible — an exception there
        must not kill the singleton send worker or a healer thread."""
        if req is None:
            return
        try:
            req.complete(None)
        except Exception:  # noqa: BLE001
            _log.error("send-completion callback raised\n%s",
                       __import__("traceback").format_exc())

    _HEAL_BASE_INTERVAL = 0.1

    def _schedule_heal(self, peer: int, deadline: float) -> None:
        # singleton healer per peer: two concurrent heal loops would
        # interleave their sends (the receiver's seq reorder absorbs it,
        # but there is no reason to create the race)
        with self._qlock:
            if peer in self._healing:
                return
            self._healing[peer] = self._HEAL_BASE_INTERVAL
        self._arm_heal(peer, deadline, self._HEAL_BASE_INTERVAL)

    def _arm_heal(self, peer: int, deadline: float,
                  interval: float) -> None:
        """One heal tick after ``interval`` (±15% jitter so a whole
        job's healers toward one dead rank don't fire in lockstep)."""
        import random

        delay = interval * random.uniform(0.85, 1.15)
        t = threading.Timer(delay, self._run_heal, args=(peer, deadline))
        t.daemon = True
        t.start()

    def _run_heal(self, peer: int, deadline: float) -> None:
        self.pvar_heal_ticks.inc()
        try:
            retry = self._heal_peer(peer, deadline)
        except Exception:  # noqa: BLE001 — healer must not die holding the guard
            _log.error("healer for %d raised\n%s", peer,
                       __import__("traceback").format_exc())
            retry = False
        if retry:
            # Chain the continuation WITHOUT leaving _healing: exactly
            # one healer chain may exist per peer.  Two concurrent loops
            # would both read parked[0] (duplicate frame on the wire)
            # and each pop one entry, silently dropping a never-sent
            # frame.  Exponential backoff + jitter, capped at
            # pml_heal_max_interval: most respawns heal in well under a
            # second, but a rank that stays down for its whole retry
            # window must not be probed 300 times.
            cap = float(var_registry.get("pml_heal_max_interval")
                        or self._HEAL_BASE_INTERVAL)
            with self._qlock:
                interval = self._healing.get(peer,
                                             self._HEAL_BASE_INTERVAL)
                nxt = min(max(interval * 2, self._HEAL_BASE_INTERVAL),
                          cap)
                self._healing[peer] = nxt
            self._arm_heal(peer, deadline, nxt)
            return
        with self._qlock:
            self._healing.pop(peer, None)
        # frames parked between the healer draining and the discard
        # need a new healer
        with self._lock:
            leftovers = bool(self._parked.get(peer))
        if leftovers:
            self._schedule_heal(peer, deadline)

    def _heal_peer(self, peer: int, deadline: float) -> bool:
        """Drain peer's parked frames.  Returns True when the caller
        (_run_heal) should chain another attempt after a backoff — the
        route is still down but the retry window is open."""
        ft = self.ft
        if ft is not None and ft.detector.is_dead(peer):
            # the runtime declared the peer dead mid-park: fail the
            # user-data frames NOW (ERR_PROC_FAILED), keep nothing —
            # except under respawn the peer may come back, but then the
            # detector never declared it (respawn revives before the
            # errmgr reports a death to the control plane)
            with self._lock:
                dead = self._parked.pop(peer, [])
            for _h, _p, r in dead:
                self._fail_req(r, MPIException(
                    f"rank {peer} has failed "
                    f"({ft.detector.reason(peer) or 'detector-declared'})",
                    error_class=ERR_PROC_FAILED))
            return False
        while True:
            with self._lock:
                parked = self._parked.get(peer)
                if not parked:
                    self._parked.pop(peer, None)
                    return False
                # seq re-stamping happened in _adopt_incarnation (under
                # the lock that reset the counters).  Serialize a COPY of
                # the header and remember the route generation: an adopt
                # racing this delivery restamps the in-list dict and the
                # stale copy is fenced by the receiver — the generation
                # check below detects that and re-sends instead of
                # completing a lost frame.
                hdr, payload, req = parked[0]
                wire_hdr = dict(hdr)
                gen = self._route_gen.get(peer, 0)
            try:
                self.endpoint.send(peer, wire_hdr, payload)
            except ConnectionError as e:
                _log.verbose(1, "heal tick for %d failed: %s", peer, e)
                if time.monotonic() > deadline or self._closed:
                    with self._lock:
                        dead = self._parked.pop(peer, [])
                    for _h, _p, r in dead:
                        self._fail_req(r, MPIException(
                            f"no route to rank {peer} within the retry "
                            f"window: {e}"))
                    return False
                return True
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    parked = self._parked.get(peer)
                    if parked and parked[0][2] is req:
                        parked.pop(0)
                self._fail_req(req, e)
                continue
            with self._lock:
                if self._route_gen.get(peer, 0) != gen:
                    # the peer re-incarnated mid-send: the copy we just
                    # delivered carried the fenced epoch — keep the frame
                    # (already restamped by the adopt) and go around
                    continue
                parked = self._parked.get(peer)
                if parked:
                    parked.pop(0)
            self.pvar_healed.inc()
            self._complete_safely(req)

    def _fail_req(self, req, e) -> None:
        if req is not None:
            try:
                req.fail(e if isinstance(e, MPIException)
                         else MPIException(f"send failed: {e}"))
            except Exception:  # noqa: BLE001 — callbacks may raise
                _log.error("send-failure callback raised\n%s",
                           __import__("traceback").format_exc())

    # -- partitioned point-to-point (≈ MPI_Psend_init/Precv_init, MPI-4
    #    §4.2: partitions of one bound buffer published independently) ----

    def _part_offset(self, direction: str, peer: int, tag: int,
                     cid: int, partitions: int) -> int:
        """The n-th psend_init toward (peer, tag, cid) pairs with the
        peer's n-th precv_init from me — a local per-endpoint counter
        realises MPI's init-order matching rule with zero traffic.
        The counter is CUMULATIVE in partitions, so every init owns a
        disjoint block of partition slots in the wire-tag space even
        when channels on the same key use different partition counts
        (both sides must init in the same order with the same counts —
        the pairing contract)."""
        with self._lock:
            chans = self.__dict__.setdefault("_part_chan", {})
            key = (direction, peer, tag, cid)
            off = chans.get(key, 0)
            chans[key] = off + partitions
            return off

    def cancel_recv(self, req) -> None:
        """Dequeue a posted recv so a late frame can no longer complete
        it (the Startall-rollback analog of the FT poisoning dequeue)."""
        with self._lock:
            if self._eng is not None:
                self._eng.cancel(req.cid, req)
            else:
                m = self._matching.get(req.cid)
                if m is not None:
                    try:
                        m.posted.remove(req)
                    except ValueError:
                        pass
        req.cancel()

    def psend_init(self, buf, peer: int, tag: int, cid: int,
                   partitions: int) -> "PartitionedSendRequest":
        return PartitionedSendRequest(
            self, buf, peer, tag, cid, partitions,
            offset=self._part_offset("send", peer, tag, cid, partitions))

    def precv_init(self, buf, peer: int, tag: int, cid: int,
                   partitions: int) -> "PartitionedRecvRequest":
        return PartitionedRecvRequest(
            self, buf, peer, tag, cid, partitions,
            offset=self._part_offset("recv", peer, tag, cid,
                                     partitions))


# ---------------------------------------------------------------------------
# partitioned requests (MPI-4 §4.2)
# ---------------------------------------------------------------------------

# partition messages ride the reserved internal tag space far below the
# collective/nbc/OSC/neighbor windows (which bottom out around -1891):
# wire tag = BASE - tag·STRIDE - (cumulative offset + partition), so
# distinct user tags own disjoint STRIDE-wide blocks and distinct inits
# on one (peer, tag, cid) own disjoint partition-slot ranges — no two
# live partitioned operations can ever share a wire tag, and Pready
# order never matters
_PART_WIRE_BASE = -1_000_000
_PART_TAG_STRIDE = 1 << 24      # partition slots per user tag


class _PartitionedBase(PersistentRequest):
    """Shared half of psend/precv: one bound C-contiguous buffer split
    into ``partitions`` flat views (``np.array_split`` boundaries — the
    trailing partitions may be one element shorter), each riding the
    PML as an ordinary zero-copy message on its own derived wire tag.
    Sender and receiver must init channels on a (peer, tag) pair in
    the same order with the same partition counts (the pairing
    contract).  ``peer is None`` ⇒ the PROC_NULL inert form
    (everything trivially completes).  Start/wait/Startall compose
    exactly like any other persistent request."""

    def __init__(self, pml, buf, peer: Optional[int], tag: int, cid: int,
                 partitions: int, offset: int = 0,
                 kind: str = "partitioned") -> None:
        n = int(partitions)
        if n < 1:
            raise MPIException(f"{kind}_init: partitions must be >= 1 "
                               f"(got {partitions})")
        if offset + n > _PART_TAG_STRIDE:
            raise MPIException(
                f"{kind}_init: partition-slot space for tag {tag} "
                f"exhausted ({_PART_TAG_STRIDE} cumulative partitions "
                f"per (peer, tag) pair)")
        arr = np.asarray(buf)
        if not arr.flags["C_CONTIGUOUS"]:
            raise MPIException(
                f"{kind}_init: partitioned operations need a "
                f"C-contiguous buffer (partitions are zero-copy views)")
        self._pml = pml
        self._peer = peer
        self._tag = tag
        self._cid = cid
        self._npart = n
        self._off = int(offset)
        self._arr = arr
        self._parts = np.array_split(arr.reshape(-1), n)
        self._plock = threading.Lock()
        self._op: Optional[Request] = None
        self._preqs: list = [None] * n
        self._ndone = 0
        self._fail: Optional[BaseException] = None
        super().__init__(self._activate, kind=kind)

    def _ptag(self, i: int) -> int:
        return (_PART_WIRE_BASE - self._tag * _PART_TAG_STRIDE
                - (self._off + i))

    def _check_started(self) -> None:
        ft = self._pml.ft
        if ft is not None:
            ft.check_cid(self._cid)
        trace_mod.count("pml_partitioned_starts_total")

    def _part_done(self, r: Request) -> None:
        op = self._op
        with self._plock:
            if getattr(r, "_exc", None) is not None \
                    and self._fail is None:
                self._fail = r._exc
            self._ndone += 1
            fire = self._ndone == self._npart
            fail = self._fail
        if fire and op is not None:
            if fail is not None:
                op.fail(fail)
            else:
                op.complete(self._result_value())

    def _result_value(self):
        return None


class PartitionedSendRequest(_PartitionedBase):
    """≈ MPI_Psend_init: start() activates (nothing moves), Pready(i)
    publishes partition i, wait() completes once every partition was
    readied AND sent.  Waiting with unready partitions raises (the MPI
    erroneous case, surfaced instead of hanging)."""

    def __init__(self, pml, buf, peer, tag, cid, partitions,
                 offset: int = 0) -> None:
        super().__init__(pml, buf, peer, tag, cid, partitions,
                         offset=offset, kind="psend")

    def _activate(self) -> Request:
        self._check_started()
        with self._plock:
            self._readied = [False] * self._npart
            self._preqs = [None] * self._npart
            self._ndone = 0
            self._fail = None
        if self._peer is None:       # PROC_NULL: trivially complete
            self._op = None
            return CompletedRequest(None, kind="psend")
        self._op = Request(kind="psend-op")
        return self._op

    def pready(self, partition: int) -> None:
        """≈ MPI_Pready: partition ``partition`` of the bound buffer is
        final — send it (a zero-copy view rides the PML now)."""
        i = int(partition)
        if not 0 <= i < self._npart:
            raise MPIException(
                f"Pready: partition {i} out of range [0, {self._npart})")
        if self._inner is None:
            raise MPIException(
                "Pready on an inactive partitioned send (start() first)")
        with self._plock:
            if self._readied[i]:
                raise MPIException(
                    f"Pready: partition {i} already readied this start")
            self._readied[i] = True
        trace_mod.count("pml_partitioned_pready_total")
        if self._peer is None:
            return
        req = self._pml.isend(self._parts[i], self._peer, self._ptag(i),
                              self._cid)
        with self._plock:
            self._preqs[i] = req
        req.add_completion_callback(self._part_done)

    def pready_range(self, low: int, high: int) -> None:
        """≈ MPI_Pready_range (inclusive bounds, like the binding)."""
        for i in range(int(low), int(high) + 1):
            self.pready(i)

    def pready_list(self, partitions) -> None:
        """≈ MPI_Pready_list."""
        for i in partitions:
            self.pready(i)

    def wait(self, timeout: Optional[float] = None):
        if self._inner is not None and not self._inner.done():
            with self._plock:
                unready = self._npart - sum(self._readied)
            if unready:
                raise MPIException(
                    f"wait on a partitioned send with {unready} unready "
                    f"partition(s) — Pready them first (erroneous per "
                    f"MPI-4 §4.2.2, surfaced instead of hanging)")
        return super().wait(timeout=timeout)


class PartitionedRecvRequest(_PartitionedBase):
    """≈ MPI_Precv_init: start() posts every partition's receive into
    its zero-copy view of the bound buffer; Parrived(i) polls one
    partition; wait() returns the filled buffer."""

    def __init__(self, pml, buf, peer, tag, cid, partitions,
                 offset: int = 0) -> None:
        super().__init__(pml, buf, peer, tag, cid, partitions,
                         offset=offset, kind="precv")
        if not self._arr.flags.writeable:
            raise MPIException("precv_init: receive buffer is read-only")

    def _result_value(self):
        return self._arr

    def _activate(self) -> Request:
        self._check_started()
        with self._plock:
            self._preqs = [None] * self._npart
            self._ndone = 0
            self._fail = None
        if self._peer is None:       # PROC_NULL: nothing will arrive
            self._op = None
            return CompletedRequest(self._arr, kind="precv")
        self._op = Request(kind="precv-op")
        for i in range(self._npart):
            req = self._pml.irecv(self._parts[i], self._peer,
                                  self._ptag(i), self._cid)
            with self._plock:
                self._preqs[i] = req
            req.add_completion_callback(self._part_done)
        return self._op

    def parrived(self, partition: int) -> bool:
        """≈ MPI_Parrived: has partition ``partition`` of the CURRENT
        operation landed?  True on an inactive request (the last
        operation delivered everything)."""
        i = int(partition)
        if not 0 <= i < self._npart:
            raise MPIException(
                f"Parrived: partition {i} out of range "
                f"[0, {self._npart})")
        if self._inner is None or self._peer is None:
            return True
        with self._plock:
            req = self._preqs[i]
        return req is not None and req.done()

    def cancel(self) -> None:
        with self._plock:
            reqs = [r for r in self._preqs if r is not None]
        for r in reqs:
            r.cancel()
        super().cancel()

    def _abandon(self) -> None:
        # Startall rollback: the posted partition irecvs must be
        # DEQUEUED, not just flagged — left behind they would be
        # FIFO-first on their wire tags and swallow the next
        # activation's partitions (wait() would then hang forever)
        with self._plock:
            reqs = [r for r in self._preqs if r is not None]
            self._preqs = [None] * self._npart
        for r in reqs:
            self._pml.cancel_recv(r)
        self._op = None
        super()._abandon()


@pml_framework.component
class Ob1Component(Component):
    """Default PML (named for its ancestor, ompi/mca/pml/ob1)."""

    NAME = "ob1"
    PRIORITY = 50

    def create(self, rank: int) -> PmlOb1:
        return PmlOb1(rank)
