"""PML — point-to-point messaging logic: matching, protocols, progress.

≈ ompi/mca/pml/ob1: MPI send/recv semantics over the BTL —
- tag/source matching with wildcards, posted-recv + unexpected queues
  (≈ pml_ob1_recvfrag.c:143-173),
- eager vs rendezvous protocol selection by message size
  (≈ pml_ob1_sendreq.h:382-413),
- fragmentation/pipelining of large transfers (≈ the RDMA pipeline).

Threading model (replaces the reference's opal_progress polling): BTL reader
threads ONLY read and match; all payload writes go through a single send
worker thread per process, so readers can never block on socket backpressure
— the classic two-sided rendezvous deadlock (both readers stuck in sendall)
is structurally impossible.

MPI ordering guarantee (per sender-receiver pair, per communicator, in tag
order of posting) holds because each direction of a pair is one TCP stream
processed by one reader, and the send worker is FIFO.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
from typing import Any, Optional

import numpy as np

from ompi_tpu.core import output
from ompi_tpu.core.buffer import BufferKind, BufferLocationError, classify
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.mpi import datatype as dt_mod
from ompi_tpu.mpi.btl import BtlEndpoint
from ompi_tpu.mpi.constants import ANY_SOURCE, ANY_TAG, ERR_TRUNCATE, MPIException
from ompi_tpu.mpi.datatype import Datatype
from ompi_tpu.mpi.request import Request, Status

__all__ = ["pml_framework", "PmlOb1", "RecvRequest"]


def _reject_device(buf: Any, what: str) -> None:
    """Device/traced buffers must NEVER silently host-stage through the PML
    (the reference's coll/cuda bounce-buffer anti-pattern this design
    forbids).  They belong on the device path: a comm with a bound
    DeviceCommunicator (comm.bind_device), or lax collectives inside jit."""
    kind = classify(buf)
    if kind is not BufferKind.HOST:
        raise BufferLocationError(
            f"pml.{what}: got a {kind.value} buffer; the host PML would "
            f"stage it through host memory. Use the device path instead "
            f"(comm.bind_device(DeviceCommunicator(...)) routes collectives "
            f"over XLA/ICI; for p2p use DeviceCommunicator.shift/permute "
            f"inside jit), or np.asarray() the buffer explicitly if host "
            f"staging is intended.")

_log = output.get_stream("pml")

pml_framework = Framework("pml", "point-to-point messaging logic")

register_var("pml", "eager_limit", VarType.SIZE, 64 * 1024,
             "max payload bytes sent eagerly (larger goes rendezvous)")
register_var("pml", "frag_size", VarType.SIZE, 1 << 20,
             "fragment size for rendezvous pipelines")


class RecvRequest(Request):
    def __init__(self, buf: Optional[np.ndarray], datatype: Optional[Datatype],
                 count: Optional[int], source: int, tag: int, cid: int) -> None:
        super().__init__(kind="recv")
        self.buf = buf
        self.datatype = datatype  # None → take element dtype from the wire
        self.count = count        # None → no truncation check (alloc to fit)
        self.source = source
        self.tag = tag
        self.cid = cid
        self.rid = -1  # receiver-side id for rendezvous


def _dtype_to_wire(dt: np.dtype):
    if dt.fields:
        return dt.descr
    # extended dtypes (bfloat16, float8_*) stringify as raw void ('<V2');
    # their registered name ('bfloat16') reconstructs correctly
    if dt.kind == "V":
        return dt.name
    return dt.str


def _wire_to_dtype(spec) -> np.dtype:
    if isinstance(spec, (list, tuple)):
        return np.dtype([tuple(f) for f in spec])
    if isinstance(spec, str) and not spec[:1].isalpha():
        return np.dtype(spec)
    # name form needs ml_dtypes registered for the extended types
    import ml_dtypes  # noqa: F401

    return np.dtype(spec)


class _SendState:
    """Sender-side rendezvous bookkeeping (awaiting CTS)."""

    def __init__(self, req: Request, peer: int, payload: bytes) -> None:
        self.req = req
        self.peer = peer
        self.payload = payload


class _RecvState:
    """Receiver-side rendezvous accumulation."""

    def __init__(self, req: RecvRequest, size: int, src_hdr: dict,
                 peer: int) -> None:
        self.req = req
        self.data = bytearray(size)
        self.received = 0
        self.src_hdr = src_hdr
        self.peer = peer


class _Matching:
    """Per-communicator matching engine (posted + unexpected queues)."""

    def __init__(self) -> None:
        self.posted: collections.deque[RecvRequest] = collections.deque()
        self.unexpected: collections.deque[tuple[int, dict, bytes]] = \
            collections.deque()


def _hdr_matches(req: RecvRequest, peer: int, hdr: dict) -> bool:
    if req.source != ANY_SOURCE and req.source != peer:
        return False
    if req.tag != ANY_TAG and req.tag != hdr["tag"]:
        return False
    return True


# request-lifecycle events (≈ the PERUSE spec, ompi/peruse/peruse.h:55-76:
# queue/xfer event hooks on the matching engine) — listeners receive
# (event, info_dict); pml/coll/osc monitoring components subscribe here
EVT_SEND_POST = "send_post"        # isend issued
EVT_RECV_POST = "recv_post"        # irecv posted
EVT_MATCH = "match"                # incoming frame matched a posted recv
EVT_UNEXPECTED = "unexpected"      # incoming frame queued unmatched
EVT_DELIVER = "deliver"            # payload delivered, request complete


class PmlOb1:
    """The default PML: matching + eager/rendezvous over the BTL."""

    def __init__(self, rank: int) -> None:
        self.rank = rank
        self.endpoint = BtlEndpoint(rank, self._on_frame)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)  # probe waiters
        self._matching: dict[int, _Matching] = {}
        self._send_states: dict[int, _SendState] = {}
        self._recv_states: dict[int, _RecvState] = {}
        self._ids = itertools.count(1)
        self._seq: dict[tuple[int, int], int] = {}
        self._sendq: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._listeners: list = []   # peruse/monitoring subscribers
        self._events: "collections.deque[tuple]" = collections.deque()
        self._worker = threading.Thread(
            target=self._send_loop, name=f"pml-send-{rank}", daemon=True)
        self._worker.start()
        self._closed = False

    # -- event hooks (PERUSE equivalent) -----------------------------------
    #
    # _emit only enqueues; _drain_events dispatches OUTSIDE the PML lock so
    # listeners may safely call back into the PML (and a racing
    # remove_listener can't skip a concurrent subscriber: dispatch iterates
    # a snapshot).  Every path that can enqueue drains before returning.

    def add_listener(self, cb) -> None:
        """Subscribe cb(event, info) to request-lifecycle events."""
        self._listeners.append(cb)

    def remove_listener(self, cb) -> None:
        self._listeners.remove(cb)

    def _emit(self, event: str, **info) -> None:
        self._events.append((event, info))

    def _drain_events(self) -> None:
        while self._events:
            try:
                event, info = self._events.popleft()
            except IndexError:
                return
            for cb in list(self._listeners):
                cb(event, info)

    # -- wiring ------------------------------------------------------------

    @property
    def address(self) -> str:
        return self.endpoint.address

    def set_peers(self, peers: dict[int, str]) -> None:
        self.endpoint.set_peers(peers)

    def close(self) -> None:
        self._closed = True
        self._sendq.put(None)
        self._worker.join(timeout=2.0)
        self.endpoint.close()

    def _matching_for(self, cid: int) -> _Matching:
        m = self._matching.get(cid)
        if m is None:
            m = self._matching[cid] = _Matching()
        return m

    # -- send side ---------------------------------------------------------

    def isend(self, buf: Any, peer: int, tag: int, cid: int,
              datatype: Optional[Datatype] = None,
              count: Optional[int] = None) -> Request:
        _reject_device(buf, "isend")
        arr = np.asarray(buf)
        if datatype is None:
            datatype = dt_mod.from_numpy(arr.dtype)
        if count is None:
            count = arr.size // max(1, datatype.elements_per_item)
        payload = datatype.pack(arr, count)
        req = Request(kind="send")
        with self._lock:
            seq_key = (peer, cid)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
        hdr = {"tag": tag, "cid": cid, "seq": seq,
               "dt": _dtype_to_wire(datatype.base_np),
               "elems": len(payload) // datatype.base_np.itemsize,
               "shp": list(arr.shape)}
        if self._listeners:
            self._emit(EVT_SEND_POST, peer=peer, tag=tag, cid=cid,
                       nbytes=len(payload))
        if len(payload) <= var_registry.get("pml_eager_limit"):
            hdr["t"] = "eager"
            self._sendq.put(("frame", peer, hdr, payload, req))
        else:
            sid = next(self._ids)
            hdr.update(t="rndv", size=len(payload), sid=sid)
            with self._lock:
                self._send_states[sid] = _SendState(req, peer, payload)
            self._sendq.put(("frame", peer, hdr, b"", None))
        self._drain_events()
        return req

    def send(self, buf: Any, peer: int, tag: int, cid: int, **kw) -> None:
        self.isend(buf, peer, tag, cid, **kw).wait()

    # -- recv side ---------------------------------------------------------

    def irecv(self, buf: Optional[np.ndarray], source: int, tag: int,
              cid: int, datatype: Optional[Datatype] = None,
              count: Optional[int] = None) -> RecvRequest:
        if buf is not None:
            _reject_device(buf, "irecv")
            buf = np.asarray(buf)
            if datatype is None:
                datatype = dt_mod.from_numpy(buf.dtype)
            if count is None:
                count = buf.size // max(1, datatype.elements_per_item)
        # buf=None with datatype/count=None is the allocate-on-match path:
        # the element dtype travels in the wire header
        req = RecvRequest(buf, datatype, count, source, tag, cid)
        req.rid = next(self._ids)
        if self._listeners:
            self._emit(EVT_RECV_POST, peer=source, tag=tag, cid=cid)
        with self._lock:
            m = self._matching_for(cid)
            # try the unexpected queue first, in arrival order
            for i, (peer, hdr, payload) in enumerate(m.unexpected):
                if _hdr_matches(req, peer, hdr):
                    del m.unexpected[i]
                    if self._listeners:
                        self._emit(EVT_MATCH, peer=peer, tag=hdr["tag"],
                                   cid=hdr["cid"])
                    self._match(req, peer, hdr, payload)
                    break
            else:
                m.posted.append(req)
        self._drain_events()
        return req

    def recv(self, buf: Optional[np.ndarray], source: int, tag: int, cid: int,
             datatype: Optional[Datatype] = None, count: Optional[int] = None,
             status: Optional[Status] = None) -> np.ndarray:
        req = self.irecv(buf, source, tag, cid, datatype, count)
        out = req.wait()
        if status is not None:
            status.__dict__.update(req.status.__dict__)
        return out

    # -- probe -------------------------------------------------------------

    def iprobe(self, source: int, tag: int, cid: int) -> Optional[Status]:
        with self._lock:
            return self._iprobe_locked(source, tag, cid)

    def probe(self, source: int, tag: int, cid: int,
              timeout: Optional[float] = None) -> Status:
        with self._cv:
            while True:
                st = self._iprobe_locked(source, tag, cid)
                if st is not None:
                    return st
                if not self._cv.wait(timeout=timeout):
                    raise TimeoutError("probe timed out")

    def _iprobe_locked(self, source: int, tag: int, cid: int) -> Optional[Status]:
        probe = RecvRequest(None, dt_mod.BYTE, 0, source, tag, cid)
        for peer, hdr, payload in self._matching_for(cid).unexpected:
            if _hdr_matches(probe, peer, hdr):
                st = Status()
                st.source = peer
                st.tag = hdr["tag"]
                st.count = hdr.get("elems", hdr.get("size", len(payload)))
                return st
        return None

    # -- frame handling (reader threads; NEVER blocking-send here) ---------

    def _on_frame(self, peer: int, hdr: dict, payload: bytes) -> None:
        t = hdr["t"]
        if t in ("eager", "rndv"):
            with self._lock:
                m = self._matching_for(hdr["cid"])
                req = None
                for i, cand in enumerate(m.posted):
                    if _hdr_matches(cand, peer, hdr):
                        del m.posted[i]
                        req = cand
                        break
                if req is None:
                    m.unexpected.append((peer, hdr, payload))
                    self._cv.notify_all()
                    if self._listeners:
                        self._emit(EVT_UNEXPECTED, peer=peer,
                                   tag=hdr["tag"], cid=hdr["cid"])
                else:
                    if self._listeners:
                        self._emit(EVT_MATCH, peer=peer, tag=hdr["tag"],
                                   cid=hdr["cid"])
                    self._match(req, peer, hdr, payload)
            self._drain_events()
        elif t == "cts":
            with self._lock:
                state = self._send_states.pop(hdr["sid"], None)
            if state is not None:
                self._sendq.put(("rndv_data", state, hdr["rid"]))
        elif t == "data":
            self._on_data(hdr, payload)
        else:
            _log.error("unknown frame type %r from %d", t, peer)

    def _match(self, req: RecvRequest, peer: int, hdr: dict,
               payload: bytes) -> None:
        """Called with self._lock held. Eager: deliver now. Rndv: send CTS."""
        if hdr["t"] == "eager":
            self._deliver(req, peer, hdr, payload)
        else:  # rndv
            self._recv_states[req.rid] = _RecvState(req, hdr["size"], hdr, peer)
            # CTS is a tiny control frame; safe to enqueue (never inline-send
            # from a reader thread)
            self._sendq.put(("frame", peer,
                             {"t": "cts", "sid": hdr["sid"], "rid": req.rid},
                             b"", None))

    def _on_data(self, hdr: dict, payload: bytes) -> None:
        with self._lock:
            state = self._recv_states.get(hdr["rid"])
            if state is None:
                return
            off = hdr["off"]
            state.data[off:off + len(payload)] = payload
            state.received += len(payload)
            done = state.received >= len(state.data)
            if done:
                del self._recv_states[hdr["rid"]]
        if done:
            self._deliver(state.req, state.peer, state.src_hdr,
                          bytes(state.data))
            self._drain_events()

    def _deliver(self, req: RecvRequest, peer: int, hdr: dict,
                 payload: bytes) -> None:
        """Unpack payload into the request's buffer and complete it."""
        datatype = req.datatype
        if datatype is not None and req.count is not None:
            expected = req.count * datatype.size
            if len(payload) > expected:
                req.status.source = peer
                req.status.tag = hdr["tag"]
                req.fail(MPIException(
                    f"message truncated: {len(payload)}B arrived, recv "
                    f"posted for {expected}B", error_class=ERR_TRUNCATE))
                return
        if req.buf is None:
            elem_np = (datatype.base_np if datatype is not None
                       else _wire_to_dtype(hdr["dt"]))
            n_elems = len(payload) // elem_np.itemsize
            out = np.frombuffer(
                bytearray(payload[:n_elems * elem_np.itemsize]),
                dtype=elem_np)
            # allocate-on-match receives recover the sender's array shape
            # from the header (predefined contiguous dtypes only; derived
            # datatypes keep the flat element stream; 0-d sends stay 1-D —
            # recv() has always returned at least a 1-element vector)
            shp = hdr.get("shp")
            if (datatype is None and shp
                    and int(np.prod(shp)) == n_elems):
                out = out.reshape(shp)
        else:
            out = req.buf
            items = len(payload) // max(1, datatype.size)
            datatype.unpack(payload, out, items)
        if self._listeners:
            self._emit(EVT_DELIVER, peer=peer, tag=hdr["tag"],
                       cid=hdr["cid"], nbytes=len(payload))
        req.status.source = peer
        req.status.tag = hdr["tag"]
        elem_size = (datatype.base_np.itemsize if datatype is not None
                     else _wire_to_dtype(hdr["dt"]).itemsize)
        req.status.count = len(payload) // elem_size
        req.complete(out)

    # -- send worker (the only thread that writes payloads) ----------------

    def _send_loop(self) -> None:
        frag = var_registry.get("pml_frag_size")
        while True:
            job = self._sendq.get()
            if job is None:
                return
            try:
                if job[0] == "frame":
                    _, peer, hdr, payload, req = job
                    self.endpoint.send(peer, hdr, payload)
                    if req is not None:
                        req.complete(None)
                elif job[0] == "rndv_data":
                    _, state, rid = job
                    data = state.payload
                    for off in range(0, len(data), frag):
                        self.endpoint.send(
                            state.peer,
                            {"t": "data", "rid": rid, "off": off},
                            data[off:off + frag])
                    state.req.complete(None)
            except Exception as e:
                req = job[4] if job[0] == "frame" else job[1].req
                if req is not None:
                    req.fail(e if isinstance(e, MPIException)
                             else MPIException(f"send failed: {e}"))


@pml_framework.component
class Ob1Component(Component):
    """Default PML (named for its ancestor, ompi/mca/pml/ob1)."""

    NAME = "ob1"
    PRIORITY = 50

    def create(self, rank: int) -> PmlOb1:
        return PmlOb1(rank)
