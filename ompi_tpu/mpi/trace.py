"""Flight recorder — per-rank time-resolved tracing + metrics export.

≈ the reference's PERUSE event hooks and the MPI_T pvar discipline, but
with the time axis the counters lack: a fixed-size, lock-cheap ring
buffer of timestamped spans/instants (monotonic ns, category, rank, peer,
tag/cid, nbytes, plan class) that every transport layer feeds —
PML matching/rendezvous, btl/shm ring publish+drain, coll algorithm
selection, osc epochs, io read/write, ckpt snapshot/replay, and the
datatype convertor's pack-plan classes.

Cost discipline:

- disabled (the default): every emit site is ONE module-attribute check
  (``if trace.active:``) — no recorder object, no clock read, no dict.
- counters (``trace.count``) are always on, like ``datatype.stats``: a
  plain dict increment, no lock — they make the zero-copy/pack-plan fast
  paths observable even when the timeline is off.
- enabled: one ``monotonic_ns`` read per instant, two per span, and a
  slot store into a preallocated ring (``itertools.count`` hands out
  indices atomically under the GIL; the ring wraps, oldest events lost
  first — a flight recorder, not a log).

Export, three ways:

- :func:`flush` / ``tools/trace_export.py`` — Chrome/Perfetto trace JSON
  (one pid per rank, one tid per category).
- :func:`metrics_snapshot` — the whole ``pvar_registry`` as a
  Prometheus-style text block.
- crash dump — ``runtime.abort()`` and the SIGTERM the errmgr's abort
  path fans out both land in :func:`crash_dump`, flushing the buffer to
  ``${TMPDIR}/ompi_tpu_trace_<jobid>_rank<r>.json`` before teardown, so
  failed runs are debuggable after the fact.

Enable with ``tpurun --trace`` or ``OMPI_TPU_TRACE=1`` (read at
``ompi_tpu.init()``), or programmatically via :func:`enable`.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import tempfile
import threading
import time
from contextlib import contextmanager
from types import FrameType
from typing import Any, Callable, Iterator, Optional

from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.mpi.mpit import Pvar, PvarClass, pvar_registry

__all__ = [
    "FlightRecorder", "enable", "disable", "enabled", "env_enabled",
    "instant", "begin", "complete", "span", "count", "counters",
    "counters_snapshot", "attach_pml", "flush", "crash_dump",
    "default_path", "metrics_snapshot", "metrics_values",
    "chrome_events", "ENV_FLAG", "push_period", "start_metrics_push",
    "stop_metrics_push", "record_hist", "hists", "hists_snapshot",
    "hist_values", "hist_bucket_index", "hist_quantile_ns",
    "refresh_hist_enable", "HIST_NBUCKETS", "HIST_VLEN", "HIST_MIN_EXP",
    "CollRecorder", "collrec", "coll_post", "coll_done", "coll_err",
    "coll_event", "coll_stuck", "collrec_tail", "collrec_sig",
    "collrec_kind_id", "collrec_kind_name", "COLLREC_KINDS",
    "COLLREC_TAIL", "push_now", "trace_id", "next_span_id",
    "drain_native_spans", "timeline_capture",
]

ENV_FLAG = "OMPI_TPU_TRACE"
#: external knob: ring capacity in events (default 65536)
ENV_EVENTS = "OMPI_TPU_TRACE_EVENTS"
#: set by the owning orted when the metrics uplink is armed: the UDP
#: ``host:port`` of the daemon's local collector — each rank's pvar
#: snapshot rides there, then TAG_METRICS up the orted tree
ENV_METRICS_URI = "OMPI_TPU_METRICS_URI"
#: external knob: minimum duration (ns) a native-plane park/batch span
#: must reach before the C side records it into its span ring (bounds
#: the drain volume; 0 records everything once the timeline is armed)
ENV_NATIVE_SPAN_MIN = "OMPI_TPU_TRACE_NATIVE_MIN_NS"

#: the timeline categories (→ one Chrome tid per category at export)
CATEGORIES = ("pml", "btl", "coll", "osc", "io", "ckpt", "datatype",
              "runtime", "errmgr")

register_var("trace", "metrics_push_period", VarType.DOUBLE, 0.0,
             "seconds between pvar-snapshot pushes from each rank to its "
             "owning orted's metrics collector (rides TAG_METRICS up the "
             "daemon tree to the HNP/DVM aggregate).  0 disables the "
             "uplink; values below 0.25 s are clamped to 0.25 s — a "
             "sub-quarter-second period would make the observability "
             "plane a measurable data-plane tax")

#: floor for trace_metrics_push_period (see the var description)
PUSH_PERIOD_FLOOR = 0.25


def push_period() -> float:
    """The effective metrics-push period: 0.0 when the uplink is off,
    else the var clamped to ``PUSH_PERIOD_FLOOR``."""
    try:
        period = float(var_registry.get("trace_metrics_push_period") or 0)
    except (TypeError, ValueError):
        return 0.0
    if period <= 0:
        return 0.0
    return max(PUSH_PERIOD_FLOOR, period)

# ---------------------------------------------------------------------------
# always-on counters (the pvar-backed fast-path observability)
# ---------------------------------------------------------------------------

_COUNTER_SPECS = (
    # pack-plan classes, bumped once per committed derived/struct datatype
    ("convertor_plan_single_total", "datatypes",
     "committed datatypes whose pack plan collapsed to one memcpy"),
    ("convertor_plan_strided_total", "datatypes",
     "committed datatypes compiling to a strided block walk"),
    ("convertor_plan_runs_total", "datatypes",
     "committed datatypes compiling to coalesced absolute runs"),
    ("convertor_plan_items_total", "datatypes",
     "committed datatypes too large to expand (per-item walk)"),
    # PML payload-path split: buffer views vs staged packs
    ("pml_zero_copy_sends_total", "messages",
     "sends whose payload rode a zero-copy view of the user buffer"),
    ("pml_packed_sends_total", "messages",
     "sends staged through the convertor pack path"),
    # shm data plane
    ("btl_shm_publish_total", "frames",
     "frames published into shared-memory rings"),
    ("btl_shm_drained_total", "frames",
     "frames drained from shared-memory rings"),
    # on-node collective arena (coll/shm)
    ("coll_shm_fanin_total", "phases",
     "arena fan-in phases run by coll/shm (reduce/allreduce/allgather "
     "slot publishes + barrier arrivals)"),
    ("coll_shm_fanout_total", "phases",
     "arena fan-out phases run by coll/shm (bcast/allreduce result "
     "distribution + hierarchical releases)"),
    ("coll_shm_fallback_total", "collectives",
     "coll/shm invocations delegated to coll/host (non-commutative op, "
     "payload above the arena cap, host-algorithm directive, or no "
     "usable arena)"),
    # ULFM fault-tolerance plane (mpi/ft.py)
    ("ft_rank_deaths_total", "ranks",
     "world ranks this process's failure detector declared dead"),
    ("ft_revokes_total", "communicators",
     "communicator cids poisoned by revocation (local or remote)"),
    ("ft_agrees_total", "agreements",
     "fault-tolerant agreements completed (Comm.agree / shrink)"),
    ("ft_shrinks_total", "communicators",
     "survivor communicators built by Comm.shrink"),
    # failure containment v2 (gossip heartbeats, agree GC, arena probes)
    ("ft_gossip_beats_total", "frames",
     "rank-plane gossip liveness beats sent (epoch + peer-view frames "
     "on the FT control plane; catches in-host hangs)"),
    ("ft_agree_gc_reclaimed_total", "states",
     "per-(cid, seq) agreement states reclaimed once every live "
     "member's acked-decision watermark passed them"),
    ("coll_shm_writer_dead_total", "ranks",
     "arena waits that detected a dead writer pid via the shared btl "
     "liveness probe (failure surfaced in ~coll_shm_probe_grace "
     "seconds instead of coll_shm_timeout)"),
    # self-healing ranks (errmgr selfheal + the rejoin fence)
    ("errmgr_selfheal_revives_total", "ranks",
     "ranks the errmgr selfheal policy reaped and revived in place "
     "(counted on the launcher/HNP process)"),
    ("errmgr_selfheal_escalations_total", "ranks",
     "selfheal ladder escalations: the revive arm gave up (budget "
     "exhausted, unrevivable rank, failed start) and the policy "
     "degraded to the notify/shrink rung — or to abort when no "
     "survivors could carry the job"),
    ("ft_fenced_frames_total", "frames",
     "stale-incarnation FT control frames dropped by the rejoin fence "
     "(sent by, or stamped for, a dead life of a revived rank)"),
    # persistent collectives (coll/persistent: bind-once plans)
    ("coll_persistent_binds_total", "plans",
     "persistent-collective plans compiled by *_init — rules decision, "
     "arena slots, hierarchy splits, and nbc rounds all frozen once"),
    ("coll_persistent_starts_total", "operations",
     "Start publishes of bound persistent-collective plans (the "
     "steady-state path that skips per-op dispatch entirely)"),
    ("coll_persistent_rebinds_total", "plans",
     "persistent plans re-compiled by rebind() after invalidation (a "
     "selfheal-revived member's slot pin went stale)"),
    # MPI-4 partitioned point-to-point (pml)
    ("pml_partitioned_starts_total", "operations",
     "partitioned send/recv activations (Start on a psend_init/"
     "precv_init request)"),
    ("pml_partitioned_pready_total", "partitions",
     "partitions published by Pready on active partitioned sends"),
    # GIL-free native data plane (_native/arena.c via ctypes)
    ("coll_shm_native_waits_total", "waits",
     "arena flag waits parked in the native GIL-released executor "
     "(bounded slices; the python FT contract re-runs between them)"),
    ("coll_shm_native_publishes_total", "publishes",
     "arena slot publishes (copy + release flag store, strided sources "
     "via the convertor plan shape) fused into one native call"),
    ("coll_shm_native_folds_total", "folds",
     "width-specialized native segment folds (reduce/allreduce root "
     "folds and segment-parallel reduce-scatter segments)"),
    ("btl_shm_native_drains_total", "sweeps",
     "btl/shm poller drain sweeps woken by the native GIL-released "
     "ring park instead of the python spin window"),
    # collective flight recorder + cross-rank hang doctor
    ("coll_stuck_events_total", "waits",
     "collective waits that exceeded coll_stuck_timeout and pushed a "
     "stuck event up the metrics uplink (the HNP doctor's watchdog "
     "trigger)"),
    ("coll_doctor_captures_total", "captures",
     "rank-side doctor state captures served (recorder tail + pending "
     "p2p + thread stacks, replied to the owning orted's TAG_DOCTOR "
     "query)"),
    # collective-capable rejoin (epoch-fenced rebuild after selfheal)
    ("coll_rejoin_total", "rebuilds",
     "epoch-fenced rebuilds of the coll/shm hierarchy (node/leader "
     "splits + arena) after a member's selfheal revive was adopted — "
     "the rejoin half that makes revives transparent to collective "
     "apps (persistent-plan auto-rebinds count separately under "
     "coll_persistent_rebinds_total)"),
    # GIL-free inter-node transport (btl/tcp native plane)
    ("btl_tcp_native_writes_total", "writes",
     "GIL-released sendmsg drain calls of the btl/tcp submission-ring "
     "writer (each pushes a whole per-peer backlog; compare against "
     "batched_frames for the coalescing ratio)"),
    ("btl_tcp_native_batched_frames_total", "frames",
     "frames drained through native submission-ring writes — divided "
     "by btl_tcp_native_writes_total this is the frames-per-syscall "
     "batching ratio the msgrate bench asserts on"),
    ("btl_tcp_native_parks_total", "parks",
     "GIL-released idle parks of the btl/tcp native plane (writer "
     "doorbell waits, receive-poller slices that expired empty, and "
     "sender ring-full backpressure waits — FT checks re-run between "
     "each)"),
    # telemetry self-metering: the observability plane measured by
    # itself (the ROADMAP item-6 fan-in data — what does the uplink
    # cost, and is the recorder silently losing evidence?)
    ("metrics_push_datagrams_total", "datagrams",
     "pvar-snapshot datagrams this rank pushed to its owning orted's "
     "UDP metrics collector (periodic cadence + out-of-cadence "
     "push_now triggers)"),
    ("metrics_push_bytes_total", "bytes",
     "serialized bytes of this rank's metrics-uplink datagrams — with "
     "metrics_push_datagrams_total this is the rank→orted hop's "
     "bytes/s, the first rung of the per-hop uplink cost ladder"),
    ("trace_native_spans_total", "spans",
     "native-plane park/batch spans drained from the arena/net span "
     "rings into the flight recorder (GIL-released sections made "
     "visible; gated on the timeline being armed)"),
)

#: plain-int counter store: dict increments, no lock — losses under
#: pathological thread races are acceptable for metrics (like the
#: reference's unlocked monitoring counters)
counters: dict[str, int] = {name: 0 for name, _u, _d in _COUNTER_SPECS}


def count(name: str, delta: int = 1) -> None:
    """Bump an always-on counter (must be a registered name)."""
    counters[name] += delta


def counters_snapshot() -> dict[str, int]:
    """Point-in-time copy of every always-on counter plus the convertor
    call stats — the provenance block bench.py embeds per record."""
    snap = dict(counters)
    from ompi_tpu.mpi import datatype as _dt

    snap["convertor_pack_calls_total"] = _dt.stats.pack_calls
    snap["convertor_unpack_calls_total"] = _dt.stats.unpack_calls
    snap["convertor_pack_bytes_total"] = _dt.stats.pack_bytes
    snap["convertor_unpack_bytes_total"] = _dt.stats.unpack_bytes
    return snap


for _name, _unit, _desc in _COUNTER_SPECS:
    pvar_registry.register_or_get(Pvar(
        _name, PvarClass.COUNTER, unit=_unit, description=_desc,
        read_fn=lambda _b, n=_name: counters[n]))


# ---------------------------------------------------------------------------
# latency histograms (the pvar family the counters lack a time axis for)
# ---------------------------------------------------------------------------
#
# Fixed log2 bucketing, HDR-style: bucket i holds durations whose
# nanosecond bit_length is MIN_EXP + i, i.e. dur < 2**(MIN_EXP+i) — the
# finite rungs span ~1 µs (2**10 ns) to ~16 s (2**34 ns), bucket 0
# absorbs the sub-µs underflow and the last bucket the overflow.  One
# plain-int vector per series (counts + a trailing observation sum, so
# the Prometheus render can emit honest ``_sum`` series and the
# straggler panel real wait-time shares, not midpoint estimates); the
# record path is one bit_length, one clamp, two list increments under
# the GIL — same unlocked-loss tolerance as the counters.
#
# Labeled series: ``record_hist(name, dur, labels='provider="shm"')``
# opens the sub-series ``name{provider="shm"}`` — the pvar NAME stays a
# declared ``_HIST_SPECS`` literal (the pvar-spec lint checker enforces
# both directions), only the label string is dynamic, and the DVM's
# scrape render folds the labels into the Prometheus series verbatim.

#: bucket 0 upper bound exponent: 2**10 ns ≈ 1 µs
HIST_MIN_EXP = 10
#: counts per series: 25 finite log2 rungs (le 2**10 … 2**34 ns) + overflow
HIST_NBUCKETS = 26
#: vector length: the counts plus the trailing observation sum (ns)
HIST_VLEN = HIST_NBUCKETS + 1

_HIST_SPECS = (
    ("coll_dispatch_ns", "nanoseconds",
     "blocking-collective latency at the coll dispatch choke point "
     "(labels: slot, provider, szb = log2 payload-size bucket)"),
    ("coll_host_algo_ns", "nanoseconds",
     "coll/host algorithm-body latency, labeled by collective and the "
     "algorithm the decision layer picked (the per-rung distribution "
     "the coll_xla_algorithm ladder wants)"),
    ("coll_nbc_ns", "nanoseconds",
     "nonblocking-collective schedule latency: NbcRequest post to "
     "completion (labels: kind)"),
    ("coll_pstart_ns", "nanoseconds",
     "persistent-collective Start-to-completion latency over a bound "
     "plan (labels: kind, provider)"),
    ("coll_ppublish_ns", "nanoseconds",
     "persistent arena publish time: bound-buffer copy into the pinned "
     "slot plus the arrive flag store (the straggler panel's 'work' "
     "half)"),
    ("coll_arena_wait_ns", "nanoseconds",
     "coll/shm arena flag-wait time (arrive/depart spins, one-shot and "
     "persistent) — the cross-rank straggler signal: a rank whose wait "
     "share is LOW is the one everyone else waits for"),
    ("pml_eager_send_ns", "nanoseconds",
     "eager-protocol isend latency: entry to local completion/handoff"),
    ("pml_rndv_send_ns", "nanoseconds",
     "rendezvous data push latency on the send worker: CTS-released "
     "fragment stream start to last fragment delivered"),
    ("btl_shm_drain_ns", "nanoseconds",
     "btl/shm poller drain-batch latency: one sweep over a peer ring "
     "that yielded frames"),
    ("btl_tcp_write_ns", "nanoseconds",
     "btl/tcp submission-ring drain-batch latency: one writer sweep "
     "over a peer backlog, enqueue-visible to kernel-accepted (the "
     "straggler panel's inter-node stall signal, the tcp twin of "
     "btl_shm_drain_ns)"),
    ("coll_rejoin_ns", "nanoseconds",
     "epoch-fenced coll-hierarchy rebuild latency after a selfheal "
     "revive: stale-state teardown through the re-agreed epoch, "
     "node/leader re-split and arena re-bootstrap (the rejoin half of "
     "kill -> first-successful-full-world-collective)"),
)

_HIST_NAMES = frozenset(n for n, _u, _d in _HIST_SPECS)

#: series key → [count_0 … count_25, sum_ns]; keys are either a bare
#: declared name or ``name{label="v",…}`` for labeled sub-series
hists: dict[str, list[int]] = {}

register_var("trace", "hist_enable", VarType.BOOL, True,
             "arm the always-on latency histogram plane (coll dispatch, "
             "persistent Start, arena waits, pml eager/rndv, btl drain "
             "batches).  Independent of the span timeline; the record "
             "path costs ~one dict hit + two int increments (measured "
             "in PERF.md).  Re-read by trace.refresh_hist_enable()")

#: THE flag every record site checks first (mirrors ``active`` for the
#: timeline) — refreshed from the ``trace_hist_enable`` var, not read
#: through the registry per event
hist_active = True


def refresh_hist_enable() -> bool:
    """Re-read ``trace_hist_enable`` into the module flag (called at
    init(); tests and tools call it after flipping the var)."""
    global hist_active
    try:
        hist_active = bool(var_registry.get("trace_hist_enable"))
    except Exception:  # noqa: BLE001 — a broken knob must not disarm init
        hist_active = True
    return hist_active


def _new_hist_series(name: str, key: str) -> list[int]:
    """Open a series vector; an undeclared base name is a KeyError, the
    same hot-path discipline as an undeclared counter bump."""
    if name not in _HIST_NAMES:
        raise KeyError(name)
    return hists.setdefault(key, [0] * HIST_VLEN)


def record_hist(name: str, dur_ns: int, labels: str = "") -> None:
    """Record one duration into a declared histogram (``labels`` is a
    preformatted Prometheus label-pair fragment opening a sub-series)."""
    key = f"{name}{{{labels}}}" if labels else name
    vec = hists.get(key)
    if vec is None:
        vec = _new_hist_series(name, key)
    i = dur_ns.bit_length() - HIST_MIN_EXP
    if i < 0:
        i = 0
    elif i >= HIST_NBUCKETS:
        i = HIST_NBUCKETS - 1
    vec[i] += 1
    vec[HIST_NBUCKETS] += dur_ns


def hist_bucket_index(dur_ns: int) -> int:
    """The bucket a duration lands in (exposed for tests/tools)."""
    i = int(dur_ns).bit_length() - HIST_MIN_EXP
    return 0 if i < 0 else min(i, HIST_NBUCKETS - 1)


def hist_quantile_ns(counts: list[int], q: float) -> float:
    """Estimate the q-quantile (0..1) from a bucket-count vector (the
    counts only — pass ``vec[:HIST_NBUCKETS]``).  Uses the geometric
    midpoint of the landing bucket's range; log2 buckets bound the
    estimate within ~sqrt(2) of the true value."""
    total = sum(counts[:HIST_NBUCKETS])
    if total <= 0:
        return 0.0
    target = q * total
    seen = 0
    for i, c in enumerate(counts[:HIST_NBUCKETS]):
        seen += c
        if seen >= target and c:
            hi = 1 << (HIST_MIN_EXP + i)
            return float(hi) / 1.4142135623730951   # hi / sqrt(2)
    return float(1 << (HIST_MIN_EXP + HIST_NBUCKETS - 1))


def hist_values() -> dict[str, list[int]]:
    """Every series vector by key, copied — the vector payload of the
    metrics uplink (scalar pvars ride :func:`metrics_values`)."""
    return {k: list(v) for k, v in hists.items()}


def hists_snapshot() -> dict[str, list[int]]:
    """Alias of :func:`hist_values` for symmetry with
    :func:`counters_snapshot` (benchmarks diff two snapshots)."""
    return hist_values()


for _name, _unit, _desc in _HIST_SPECS:
    pvar_registry.register_or_get(Pvar(
        _name, PvarClass.AGGREGATE, unit=_unit, description=_desc,
        # the read is the series map for this base (bare + labeled) —
        # a dict, so the scalar metrics walk skips it by design
        read_fn=lambda _b, n=_name: {
            k: list(v) for k, v in hists.items()
            if k == n or k.startswith(n + "{")}))


# ---------------------------------------------------------------------------
# collective flight recorder (always-on, beside the span ring)
# ---------------------------------------------------------------------------
#
# The "which collective is this rank in, and since when" record the hang
# doctor reads: a bounded ring of fixed-shape tuples fed by the coll
# dispatch choke point, nbc round advances, persistent Start/completion
# and the shm arena's slow-path waits.  Unlike the span ring it is NOT
# gated on ``active`` — it must already hold the evidence when a job
# wedges (target <1µs/record; measured in PERF.md).  Cross-rank matching
# key: (cid, op_seq) where op_seq is a per-(rank, cid) dispatch ordinal —
# ranks of one communicator issue matching collectives in the same order,
# so divergent kind/signature at one (cid, op_seq) IS the MPI-illegal
# collective mismatch the doctor's verdict names.

#: external knob: collective-recorder ring capacity in records
ENV_COLLREC_EVENTS = "OMPI_TPU_COLLREC_EVENTS"

#: how many trailing records ride a doctor capture / crash dump
COLLREC_TAIL = 256

_COLLREC_BASE = (
    "barrier", "bcast", "reduce", "allreduce", "gather", "allgather",
    "scatter", "alltoall", "reduce_scatter", "reduce_scatter_block",
    "scan", "exscan", "gatherv", "scatterv", "allgatherv", "alltoallv",
    "alltoallw")

#: the kind vocabulary: blocking dispatch slots, nbc schedules ("i"),
#: persistent Starts ("p") — indexed so the pushed recorder head can
#: ride the scalar metrics uplink as ``coll_cur_kind_id``
COLLREC_KINDS = (_COLLREC_BASE
                 + tuple("i" + k for k in _COLLREC_BASE)
                 + tuple("p" + k for k in _COLLREC_BASE))

_KIND_IDS = {k: i for i, k in enumerate(COLLREC_KINDS)}


def collrec_kind_id(kind: str) -> int:
    """The wire id of a collective kind (-1 for an unknown name)."""
    return _KIND_IDS.get(kind, -1)


def collrec_kind_name(kind_id: int) -> str:
    """Inverse of :func:`collrec_kind_id` ("?" for out-of-range)."""
    if 0 <= kind_id < len(COLLREC_KINDS):
        return COLLREC_KINDS[kind_id]
    return "?"


#: per-kind crc32 cache for the signature mix (one encode per kind ever)
_SIG_KIND: dict[str, int] = {}


def collrec_sig(kind: str, dtype: Any, nbytes: int, root: int = -1) -> int:
    """Deterministic cross-process signature of a collective's shape —
    crc32-seeded integer mix, NOT hash(): PYTHONHASHSEED randomization
    would make equal signatures diverge across ranks and every op read
    as a mismatch.  Pure int math on the dispatch hot path (~0.3 µs);
    the dtype contributes its stable numpy type code + itemsize."""
    import zlib

    kc = _SIG_KIND.get(kind)
    if kc is None:
        kc = _SIG_KIND[kind] = zlib.crc32(kind.encode())
    dn = 0
    if dtype is not None:
        num = getattr(dtype, "num", None)
        if num is not None:
            dn = (int(num) << 8) | int(getattr(dtype, "itemsize", 0))
        else:
            dn = zlib.crc32(str(dtype).encode())
    return (kc ^ (nbytes * 2654435761) ^ ((root + 3) * 2246822519)
            ^ (dn * 3266489917)) & 0xFFFFFFFF


#: one record: (ts_ns, rank, cid, op_seq, kind, phase, sig, info|None);
#: phases: post / done / err (dispatch), wait / stuck (arena slow path),
#: pub (persistent slot publish), round (nbc round advance), start
#: (persistent Start), fold (arena fold), fault (injected chaos)
_CollRecord = tuple[int, int, int, int, str, str, int,
                    Optional[dict[str, Any]]]


class CollRecorder:
    """The per-rank collective flight recorder ring (always-on).

    Keyed by (rank, cid) so the in-process multi-rank test harness —
    several PMLs in one interpreter — keeps each rank's op_seq stream
    intact; a launched rank process has exactly one rank key."""

    def __init__(self, capacity: int = 1024) -> None:
        self.capacity = max(64, int(capacity))
        self._buf: list[Optional[_CollRecord]] = [None] * self.capacity
        self._n = itertools.count()
        self._hwm = 0
        self._seq: dict[tuple[int, int], int] = {}
        #: (rank, cid) → STACK of (op_seq, kind, sig, t_post_ns,
        #: wall_post_s) between post and done — a stack because composed
        #: collectives nest (the shm barrier dispatches host allgathers
        #: through the same choke point); events attribute to the
        #: innermost in-flight op and a nested done re-exposes its parent
        self.current: dict[tuple[int, int],
                           list[tuple[int, str, int, int, float]]] = {}
        #: dispatch ordinal across all comms of this process (what
        #: faultinject's @coll=N triggers count)
        self.ops_total = 0
        #: the pushed head: [rank, cid, op_seq, kind_id, t_post_ns,
        #: done, wall_post_s] — wall_post_s (NOT an age) rides the
        #: uplink: a stable per-op value keeps the delta compression
        #: intact, and the DVM computes the age itself
        self.head: Optional[list[float]] = None

    def _add(self, rec: _CollRecord) -> None:
        i = next(self._n)
        self._buf[i % self.capacity] = rec
        self._hwm = i + 1

    def post(self, rank: int, cid: int, kind: str, sig: int,
             provider: Optional[str], nbytes: int) -> int:
        key = (rank, cid)
        seq = self._seq.get(key, -1) + 1
        self._seq[key] = seq
        now = time.monotonic_ns()
        wall = time.time()
        self.ops_total += 1
        self.current.setdefault(key, []).append(
            (seq, kind, sig, now, wall))
        self.head = [rank, cid, seq, _KIND_IDS.get(kind, -1), now, 0,
                     wall]
        self._add((now, rank, cid, seq, kind, "post", sig,
                   {"prov": provider, "nb": nbytes}))
        return seq

    def _pop_current(self, rank: int, cid: int, seq: int) -> None:
        key = (rank, cid)
        stack = self.current.get(key)
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == seq:
                    del stack[i]
                    break
        if stack:
            # a nested op closed: the head goes back to its still-open
            # parent (a wedged outer collective must not read as done)
            top = stack[-1]
            self.head = [rank, cid, top[0],
                         _KIND_IDS.get(top[1], -1), top[3], 0, top[4]]
        else:
            self.current.pop(key, None)
            h = self.head
            if h is not None and h[0] == rank and h[1] == cid \
                    and h[2] == seq:
                h[5] = 1

    def done(self, rank: int, cid: int, seq: int, kind: str) -> None:
        self._pop_current(rank, cid, seq)
        self._add((time.monotonic_ns(), rank, cid, seq, kind, "done",
                   0, None))

    def err(self, rank: int, cid: int, seq: int, kind: str,
            exc: str) -> None:
        self._pop_current(rank, cid, seq)
        self._add((time.monotonic_ns(), rank, cid, seq, kind, "err",
                   0, {"exc": exc}))

    def event(self, rank: int, cid: int, phase: str,
              info: Optional[dict[str, Any]] = None,
              seq: Optional[int] = None,
              kind: Optional[str] = None) -> tuple[int, str]:
        """A phase record attributed to the in-flight op on (rank, cid)
        (or to an explicit seq/kind for nbc/persistent callers)."""
        if seq is None or kind is None:
            stack = self.current.get((rank, cid))
            if stack:
                top = stack[-1]
                seq = top[0] if seq is None else seq
                kind = top[1] if kind is None else kind
            else:
                seq = -1 if seq is None else seq
                kind = "?" if kind is None else kind
        self._add((time.monotonic_ns(), rank, cid, seq, kind, phase,
                   0, info))
        return seq, kind

    @property
    def records_total(self) -> int:
        return self._hwm

    def snapshot(self) -> list[_CollRecord]:
        n = self._hwm
        if n <= self.capacity:
            out = self._buf[:n]
        else:
            cut = n % self.capacity
            out = self._buf[cut:] + self._buf[:cut]
        return [r for r in out if r is not None]

    def tail(self, limit: int = COLLREC_TAIL) -> list[list[Any]]:
        """The newest ``limit`` records as JSON/DSS-safe lists — the
        payload of doctor captures and crash dumps."""
        snap = self.snapshot()[-max(0, int(limit)):]
        return [list(r) for r in snap]

    def reset(self) -> None:
        """Tests only: forget every record, seq counter and head."""
        self._buf = [None] * self.capacity
        self._n = itertools.count()
        self._hwm = 0
        self._seq.clear()
        self.current.clear()
        self.ops_total = 0
        self.head = None


def _collrec_capacity() -> int:
    try:
        return int(os.environ.get(ENV_COLLREC_EVENTS, "") or 1024)
    except ValueError:
        return 1024      # a bad sizing knob must not kill import


#: THE process-global recorder (always armed; ~100 KiB at the default
#: 1024-record capacity)
collrec = CollRecorder(_collrec_capacity())


def coll_post(rank: int, cid: int, kind: str, sig: int,
              provider: Optional[str], nbytes: int) -> int:
    """Record a collective dispatch; returns its per-(rank, cid) op_seq."""
    return collrec.post(rank, cid, kind, sig, provider, nbytes)


def coll_done(rank: int, cid: int, seq: int, kind: str) -> None:
    collrec.done(rank, cid, seq, kind)


def coll_err(rank: int, cid: int, seq: int, kind: str, exc: str) -> None:
    collrec.err(rank, cid, seq, kind, exc)


def coll_event(rank: int, cid: int, phase: str,
               info: Optional[dict[str, Any]] = None,
               seq: Optional[int] = None,
               kind: Optional[str] = None) -> tuple[int, str]:
    return collrec.event(rank, cid, phase, info, seq=seq, kind=kind)


def coll_stuck(rank: int, cid: int, waited_s: float,
               on: Optional[int]) -> None:
    """An arena wait crossed ``coll_stuck_timeout``: record it, bump the
    watchdog counter and force an immediate metrics push so the HNP's
    doctor learns within one uplink hop instead of a push period."""
    count("coll_stuck_events_total")
    info: dict[str, Any] = {"s": round(waited_s, 2)}
    if on is not None:
        info["on"] = on
    collrec.event(rank, cid, "stuck", info)
    push_now()


def push_now() -> None:
    """One out-of-cadence metrics push (no-op when the uplink is off) —
    how a stuck event beats the push period to the HNP."""
    pusher = _pusher
    if pusher is not None:
        pusher.push()


def collrec_tail(limit: int = COLLREC_TAIL) -> list[list[Any]]:
    return collrec.tail(limit)


def _collrec_head(i: int, default: float = -1) -> float:
    h = collrec.head
    return float(h[i]) if h is not None else default


for _name, _klass, _unit, _desc, _read in (
    ("coll_recorder_ops", PvarClass.COUNTER, "operations",
     "collectives recorded by this process's flight recorder (posts "
     "across blocking dispatch, nbc launches and persistent Starts)",
     lambda _b: collrec.ops_total),
    ("coll_cur_seq", PvarClass.LEVEL, "operations",
     "op_seq of the recorder head (the last collective posted; -1 "
     "before the first) — with coll_cur_kind_id/cid/done/age_s this is "
     "the pushed head the --dvm-ps last_coll column and the doctor's "
     "no-response fallback read",
     lambda _b: _collrec_head(2)),
    ("coll_cur_kind_id", PvarClass.LEVEL, "kind",
     "COLLREC_KINDS index of the recorder head's kind (-1 = none)",
     lambda _b: _collrec_head(3)),
    ("coll_cur_cid", PvarClass.LEVEL, "communicator",
     "cid of the recorder head (-1 = none)",
     lambda _b: _collrec_head(1)),
    ("coll_cur_done", PvarClass.LEVEL, "flag",
     "1 when the recorder head completed, 0 while it is in flight "
     "(a rank whose head stays 0 with a growing age is wedged)",
     lambda _b: _collrec_head(5, default=1)),
    ("coll_cur_posted_ts", PvarClass.LEVEL, "seconds",
     "wall-clock time the recorder head was posted (0 before the "
     "first).  A stable per-op value — NOT an age, which would change "
     "every read and defeat the uplink's delta compression; the DVM "
     "computes ages against its own clock",
     lambda _b: _collrec_head(6, default=0.0)),
):
    pvar_registry.register_or_get(Pvar(
        _name, _klass, unit=_unit, description=_desc, read_fn=_read))


def _recorder_stat(attr: str) -> float:
    # late-bound: `recorder` is defined below this registration block
    rec = globals().get("recorder")
    return float(getattr(rec, attr)) if rec is not None else 0.0


# flight-recorder loss accounting as pushed pvars: silent trace loss
# (a wrapped ring overwriting evidence) becomes visible on /status and
# --dvm-ps instead of only inside a postmortem dump's otherData
for _name, _klass, _unit, _desc, _read in (
    ("trace_events_total", PvarClass.COUNTER, "events",
     "events ever emitted into this rank's flight-recorder ring "
     "(0 while the timeline is disarmed)",
     lambda _b: _recorder_stat("events_total")),
    ("trace_dropped_total", PvarClass.COUNTER, "events",
     "flight-recorder events lost to ring wrap (events_total beyond "
     "capacity) — a nonzero value means the merged timeline has holes "
     "and OMPI_TPU_TRACE_EVENTS should grow",
     lambda _b: _recorder_stat("dropped")),
    ("trace_ring_occupancy", PvarClass.LEVEL, "events",
     "events currently held in the flight-recorder ring "
     "(min(events_total, capacity))",
     lambda _b: min(_recorder_stat("events_total"),
                    _recorder_stat("capacity"))),
    ("trace_ring_capacity", PvarClass.LEVEL, "events",
     "flight-recorder ring capacity (OMPI_TPU_TRACE_EVENTS; 0 while "
     "disarmed)",
     lambda _b: _recorder_stat("capacity")),
):
    pvar_registry.register_or_get(Pvar(
        _name, _klass, unit=_unit, description=_desc, read_fn=_read))


# ---------------------------------------------------------------------------
# the ring buffer
# ---------------------------------------------------------------------------

#: one ring slot: (ts_ns, dur_ns|None, category, name, rank, args|None)
_Event = tuple[int, Optional[int], str, str, int,
               Optional[dict[str, Any]]]


class FlightRecorder:
    """Fixed-size ring of trace events.

    An event is the tuple ``(ts_ns, dur_ns|None, category, name, rank,
    args|None)``; ``dur_ns is None`` ⇒ instant, else a complete span that
    STARTED at ``ts_ns``.  ``itertools.count`` hands out slot indices
    atomically (CPython GIL), so concurrent emitters never fight over a
    lock on the hot path; a wrapped ring simply forgets the oldest
    events.
    """

    def __init__(self, capacity: int = 65536, rank: int = -1,
                 jobid: int = 0) -> None:
        self.capacity = max(16, int(capacity))
        self.rank = rank
        self.jobid = jobid
        self._buf: list[Optional[_Event]] = [None] * self.capacity
        self._n = itertools.count()
        self._hwm = 0           # highest index handed out + 1 (approx.)

    def add(self, ts_ns: int, dur_ns: Optional[int], cat: str, name: str,
            rank: int, args: Optional[dict[str, Any]]) -> None:
        i = next(self._n)
        self._buf[i % self.capacity] = (ts_ns, dur_ns, cat, name, rank,
                                        args)
        self._hwm = i + 1

    @property
    def events_total(self) -> int:
        return self._hwm

    @property
    def dropped(self) -> int:
        return max(0, self._hwm - self.capacity)

    def snapshot(self) -> list[_Event]:
        """Events in (approximate) emission order, oldest first."""
        n = self._hwm
        if n <= self.capacity:
            out = self._buf[:n]
        else:
            cut = n % self.capacity
            out = self._buf[cut:] + self._buf[:cut]
        return [e for e in out if e is not None]


# module state: `active` is THE flag every emit site checks
active = False
recorder: Optional[FlightRecorder] = None
_lock = threading.Lock()
_old_sigterm: Any = None
_sigterm_installed = False
#: (pml, cb) pairs attach_pml registered
_pml_listeners: list[tuple[Any, Callable[[str, Any], None]]] = []

# ---------------------------------------------------------------------------
# trace context (trace_id, span_id): the causal-flow pair carried in PML
# match headers and control-plane envelopes so the exporter can stitch
# send→recv, collective rounds and capture fan-outs across ranks
# ---------------------------------------------------------------------------

#: span-id namespace stride (mirrors pml._FLOW_STRIDE): ids are
#: ``rank * stride + local counter`` — globally unique without any
#: cross-rank coordination
SPAN_ID_STRIDE = 1 << 40

_trace_id = 0
_span_ids = itertools.count(1)


def trace_id() -> int:
    """The job-wide trace id (crc32 of the jobid — DETERMINISTIC across
    ranks and processes, never hash(): PYTHONHASHSEED randomization
    would split one job's flow edges into disjoint traces).  0 until
    :func:`enable` learns a jobid."""
    return _trace_id


def _compute_trace_id(jobid: int) -> int:
    import zlib

    return zlib.crc32(b"ompi_tpu_trace_%d" % int(jobid)) or 1


def next_span_id(rank: int = -1) -> int:
    """A fresh globally-unique span id for flow correlation (the
    span_id half of the (trace_id, span_id) context pair)."""
    r = rank if rank >= 0 else (recorder.rank if recorder is not None
                                else 0)
    return max(0, r) * SPAN_ID_STRIDE + next(_span_ids)


def env_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0")


def enabled() -> bool:
    return active


def enable(capacity: Optional[int] = None, rank: int = -1,
           jobid: int = 0, install_signal: bool = False) -> FlightRecorder:
    """Arm the flight recorder (idempotent).  ``install_signal`` chains a
    SIGTERM handler that flushes the buffer before dying — the errmgr
    abort path kills ranks with SIGTERM (then a grace, then SIGKILL), so
    every rank's trace survives a job teardown."""
    global active, recorder, _trace_id
    with _lock:
        if recorder is None:
            if capacity is None:
                try:
                    capacity = int(os.environ.get(ENV_EVENTS, "")
                                   or 65536)
                except ValueError:
                    # a bad sizing knob must not kill the job at init
                    capacity = 65536
            recorder = FlightRecorder(capacity, rank=rank, jobid=jobid)
        else:
            # idempotent re-enable must still adopt a LATER-learned
            # identity (an app that armed tracing before init() would
            # otherwise flush every rank to the shared rank--1 path,
            # ranks clobbering each other's dumps)
            if rank != -1:
                recorder.rank = rank
            if jobid:
                recorder.jobid = jobid
        active = True
        _trace_id = _compute_trace_id(recorder.jobid)
    _native_spans_arm(True)
    if install_signal:
        _install_sigterm_flush()
    return recorder


def disable() -> Optional[FlightRecorder]:
    """Disarm; returns the recorder (snapshot/flush still work on it).
    Also detaches every PML listener :func:`attach_pml` registered —
    leaving one behind would keep the PML's eager fast lane bypassed
    (it gates on having no listeners) long after tracing stopped."""
    global active, recorder
    with _lock:
        active = False
        rec, recorder = recorder, None
        listeners, _pml_listeners[:] = list(_pml_listeners), []
    _native_spans_arm(False)
    for pml, cb in listeners:
        try:
            pml.remove_listener(cb)
        except ValueError:
            pass
    return rec


def _install_sigterm_flush() -> None:
    """Best-effort: only the main thread may install handlers, and a
    launcher (tpurun --timeout) may own SIGTERM already — chain it.
    Idempotent: a second enable() must NOT chain the handler onto
    itself (the self-referential _old_sigterm would recurse forever
    inside the signal handler)."""
    global _old_sigterm, _sigterm_installed
    if _sigterm_installed:
        return
    import signal

    def _flush_and_die(signum: int, frame: Optional[FrameType]) -> None:
        try:
            crash_dump(reason="sigterm")
        except Exception:  # noqa: BLE001 — dying anyway
            pass
        if callable(_old_sigterm):
            _old_sigterm(signum, frame)
        elif _old_sigterm is signal.SIG_IGN:
            return   # the process was ignoring SIGTERM; keep ignoring
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    try:
        _old_sigterm = signal.signal(signal.SIGTERM, _flush_and_die)
        _sigterm_installed = True
    except (ValueError, OSError):   # not the main thread
        pass


# ---------------------------------------------------------------------------
# emit API (call sites gate on `trace.active` FIRST — see module doc)
# ---------------------------------------------------------------------------

def instant(cat: str, name: str, rank: int = -1, **args: Any) -> None:
    r = recorder
    if r is not None:
        r.add(time.monotonic_ns(), None, cat, name, rank,
              args or None)


def begin() -> int:
    """Span start timestamp (pair with :func:`complete`)."""
    return time.monotonic_ns()


def complete(cat: str, name: str, t0_ns: int, rank: int = -1,
             **args: Any) -> None:
    r = recorder
    if r is not None:
        now = time.monotonic_ns()
        r.add(t0_ns, now - t0_ns, cat, name, rank, args or None)


@contextmanager
def span(cat: str, name: str, rank: int = -1,
         **args: Any) -> Iterator[None]:
    t0 = time.monotonic_ns()
    try:
        yield
    finally:
        complete(cat, name, t0, rank=rank, **args)


def attach_pml(pml: Any) -> Any:
    """Bridge the PML's PERUSE-style EVT_* hooks into the timeline: every
    request-lifecycle event becomes a ``pml`` instant.  Returns the
    listener so a caller can ``pml.remove_listener`` it.

    Observer effect (same as attaching a monitoring.Monitor): a PML with
    listeners bypasses its compiled eager fast lane (_isend_fast gates on
    ``not self._listeners`` — the lane emits no events), so a TIMELINE
    run routes eligible eager sends down the header path.  The always-on
    counters (``pml_zero_copy_sends_total`` etc.) need no listener and
    observe the fast lane undisturbed — use them, not an enabled
    timeline, when measuring the fast path itself."""
    prank = pml.rank

    def _on_event(event: str, info: dict[str, Any]) -> None:
        if active:
            instant("pml", event, rank=prank, **info)

    pml.add_listener(_on_event)
    _pml_listeners.append((pml, _on_event))   # detached by disable()
    return _on_event


def detach_pml(pml: Any) -> None:
    """Remove the listener(s) attach_pml registered on ``pml`` — called
    from finalize() so a later init() epoch re-arms a FRESH bridge
    instead of keeping a closed PML in the listener table."""
    for pair in [p for p in _pml_listeners if p[0] is pml]:
        _pml_listeners.remove(pair)
        try:
            pml.remove_listener(pair[1])
        except ValueError:
            pass


# ---------------------------------------------------------------------------
# native-plane spans: arena.c / net.c park+batch begin–end pairs drained
# from the C-side span rings into the flight recorder, so GIL-released
# sections stop being invisible gaps in the timeline
# ---------------------------------------------------------------------------

#: below this duration the C side skips the ring store entirely (the
#: drain must not become its own hot-path tax); overridable via
#: OMPI_TPU_TRACE_NATIVE_MIN_NS
_NATIVE_SPAN_MIN_DEFAULT = 10_000


def _native_span_min_ns() -> int:
    try:
        return int(os.environ.get(ENV_NATIVE_SPAN_MIN, "")
                   or _NATIVE_SPAN_MIN_DEFAULT)
    except ValueError:
        return _NATIVE_SPAN_MIN_DEFAULT


def _native_spans_arm(on: bool) -> None:
    """Best-effort arm/disarm of the C span rings (no-op when the
    native plane never built — the timeline works without it)."""
    try:
        from ompi_tpu import _native

        _native.spans_enable(_native_span_min_ns() if on else -1)
    except Exception:  # noqa: BLE001 — observability must not break init
        pass


def drain_native_spans(limit: int = 4096) -> int:
    """Pull completed park/batch spans out of the native rings into the
    flight recorder (called on the uplink cadence, at flush, and by the
    live timeline capture).  Returns the number of spans drained."""
    rec = recorder
    if rec is None:
        return 0
    try:
        from ompi_tpu import _native

        spans = _native.spans_drain(limit)
    except Exception:  # noqa: BLE001 — native plane absent: nothing to do
        return 0
    for name, t0_ns, t1_ns in spans:
        rec.add(t0_ns, t1_ns - t0_ns, "runtime", f"native_{name}",
                rec.rank, None)
    if spans:
        count("trace_native_spans_total", len(spans))
    return len(spans)


def timeline_capture(tail: int = 2048) -> dict[str, Any]:
    """The bounded live-capture payload a TAG_TIMELINE doctor query
    pulls from a RUNNING rank: the newest ``tail`` chrome events plus
    the clock anchor and loss accounting the HNP merge needs.  Safe
    with tracing off (events empty, anchors still valid)."""
    drain_native_spans()
    rec = recorder
    events = chrome_events(rec)[-max(0, int(tail)):] if rec else []
    return {
        "rank": rec.rank if rec else -1,
        "jobid": rec.jobid if rec else 0,
        "trace_id": _trace_id,
        "events": events,
        "events_total": rec.events_total if rec else 0,
        "dropped": rec.dropped if rec else 0,
        "capacity": rec.capacity if rec else 0,
        "clock_offset_ns": time.time_ns() - time.monotonic_ns(),
        "counters": counters_snapshot(),
        "collrec": collrec_tail(64),
    }


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def chrome_events(rec: Optional[FlightRecorder] = None,
                  pid: Optional[int] = None) -> list[dict[str, Any]]:
    """The recorder's events as Chrome trace-event dicts (ts/dur in µs,
    one pid per rank, one tid per category)."""
    rec = rec if rec is not None else recorder
    if rec is None:
        return []
    tids = {c: i for i, c in enumerate(CATEGORIES)}
    out: list[dict[str, Any]] = []
    for ts_ns, dur_ns, cat, name, rank, args in rec.snapshot():
        ev_pid = pid if pid is not None else (
            rank if rank >= 0 else rec.rank)
        ev: dict[str, Any] = {
            "name": name, "cat": cat,
            "ph": "X" if dur_ns is not None else "i",
            "ts": ts_ns / 1000.0,
            "pid": ev_pid,
            "tid": tids.get(cat, len(CATEGORIES)),
        }
        if dur_ns is not None:
            ev["dur"] = dur_ns / 1000.0
        else:
            ev["s"] = "t"          # instant scope: thread
        if args:
            ev["args"] = args
        out.append(ev)
    out.sort(key=lambda e: e["ts"])
    return out


def default_path(jobid: Optional[int] = None,
                 rank: Optional[int] = None) -> str:
    rec = recorder
    if jobid is None:
        jobid = rec.jobid if rec is not None else 0
    if rank is None:
        rank = rec.rank if rec is not None else -1
    tmp = os.environ.get("TMPDIR") or tempfile.gettempdir()
    return os.path.join(tmp, f"ompi_tpu_trace_{jobid}_rank{rank}.json")


def flush(path: Optional[str] = None,
          rec: Optional[FlightRecorder] = None) -> Optional[str]:
    """Write this rank's buffer as a standalone Chrome trace JSON file;
    returns the path (None when there is nothing to flush)."""
    rec = rec if rec is not None else recorder
    if rec is None:
        return None
    if rec is recorder:
        drain_native_spans()     # GIL-released sections land in the dump
    if path is None:
        path = default_path(rec.jobid, rec.rank)
    doc = {
        "displayTimeUnit": "ns",
        "otherData": {
            "rank": rec.rank, "jobid": rec.jobid,
            "trace_id": _trace_id,
            "events_total": rec.events_total, "dropped": rec.dropped,
            # wall-vs-monotonic anchor: event ts are CLOCK_MONOTONIC
            # (boot-relative, per machine); the exporter uses this
            # offset to detect dumps whose clocks share no base
            # (ranks on different hosts)
            "clock_offset_ns": time.time_ns() - time.monotonic_ns(),
            "counters": counters_snapshot(),
            # latency-histogram vectors ([counts…, sum_ns] per series):
            # tools/straggler_report.py's offline mode reads these from
            # merged per-rank dumps when no live aggregate is reachable
            "hists": hist_values(),
            # collective-recorder tail: the postmortem hang doctor
            # (tools/hang_doctor.py --dir) reads these from crash dumps
            # when no live control plane is left to capture
            "collrec": collrec_tail(),
            "collrec_total": collrec.records_total,
        },
        "traceEvents": chrome_events(rec),
    }
    tmp_path = f"{path}.tmp.{os.getpid()}"
    with open(tmp_path, "w", encoding="utf-8") as f:
        # span args are recorded verbatim — apps pass numpy scalars and
        # other non-JSON types; a dump that raised here would break
        # finalize/abort under tracing, so coerce instead
        json.dump(doc, f, default=_json_coerce)
    os.replace(tmp_path, path)     # readers never see a partial dump
    return path


def _json_coerce(obj: Any) -> Any:
    """Last-resort encoder for event args (numpy scalars → numbers,
    everything else → its repr)."""
    for cast in (int, float):
        try:
            return cast(obj)
        except (TypeError, ValueError):
            continue
    return repr(obj)


def crash_dump(reason: str = "abort") -> Optional[str]:
    """The teardown flush: called from ``runtime.abort()`` and the
    SIGTERM handler the errmgr abort path triggers.  Stamps the reason as
    a final runtime instant so the timeline shows WHY it ends."""
    rec = recorder
    if rec is None:
        return None
    rec.add(time.monotonic_ns(), None, "runtime", f"crash_dump:{reason}",
            rec.rank, None)
    try:
        return flush(rec=rec)
    except Exception:  # noqa: BLE001 — teardown path must not raise
        return None


_METRIC_RE = re.compile(r"[^a-zA-Z0-9_]")


def metrics_values() -> dict[str, float]:
    """Every scalar pvar's current value by name — the numeric walk
    behind :func:`metrics_snapshot` and the payload of the metrics
    uplink (non-numeric and binding-required pvars are skipped — a
    scraper wants scalars)."""
    out: dict[str, float] = {}
    for name in pvar_registry.names():
        pv = pvar_registry.lookup(name)
        if pv.requires_binding:
            continue
        try:
            v = pv.read()
        except Exception:  # noqa: BLE001 — unreadable pvar: skip
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[name] = v
    return out


def metrics_snapshot() -> str:
    """Walk ``pvar_registry`` into a Prometheus-style text block
    (COUNTER → counter, everything else → gauge)."""
    lines: list[str] = []
    for name, v in metrics_values().items():
        pv = pvar_registry.lookup(name)
        metric = "ompi_tpu_" + _METRIC_RE.sub("_", name)
        kind = "counter" if pv.klass is PvarClass.COUNTER else "gauge"
        if pv.description:
            lines.append(f"# HELP {metric} {pv.description}")
        lines.append(f"# TYPE {metric} {kind}")
        lines.append(f"{metric} {v}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# metrics uplink (rank side): periodic pvar-snapshot pushes to the
# owning orted's UDP collector — delta-compressed (only changed values
# ride; every FULL_EVERY-th push resends the whole snapshot so a lost
# datagram heals), merged at each tree hop, aggregated at the HNP/DVM
#
# Histogram vectors ride the same datagrams with two wire forms, tagged
# by a leading marker element (runtime/metrics.py's merge_hop speaks
# both): ``["d", …ints]`` is the element-wise INCREMENT since the last
# push (merged by vector add at every hop — including the collector's
# failed-send re-merge, where add is the only correct fold), and
# ``["a", …ints]`` is the absolute cumulative vector (every FULL_EVERY-th
# push and the final flush), which subsumes any pending deltas so UDP
# loss heals for vectors exactly as it does for scalars.
# ---------------------------------------------------------------------------

#: every Nth push is a full snapshot (UDP loss self-heals within N pushes)
FULL_EVERY = 8

#: vector wire markers (see merge_hop): delta-increment / absolute
VEC_DELTA = "d"
VEC_ABS = "a"


class _MetricsPusher:
    """Background uplink thread: one small UDP datagram per period."""

    def __init__(self, jobid: int, rank: int, uri: str,
                 period: float) -> None:
        import socket

        host, port = uri.rsplit(":", 1)
        self._addr = (host, int(port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.jobid = jobid
        self.rank = rank
        self.period = period
        self._last: dict[str, float] = {}
        self._last_h: dict[str, list[int]] = {}
        self._n = 0
        # push() is entered by the periodic thread AND by push_now()
        # (a stuck wait's out-of-cadence push): without the lock, two
        # concurrent delta computations against one _last_h baseline
        # would double-count histogram increments at the aggregate
        self._push_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"trace-metrics-{rank}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            self.push()

    def push(self) -> None:
        """One uplink datagram now (delta vs the last push, or a full
        snapshot on the FULL_EVERY cadence).  Best-effort: metrics must
        never take a rank down."""
        from ompi_tpu.core import dss

        try:
            with self._push_lock:
                self._push_locked(dss)
        except Exception:  # noqa: BLE001 — uplink is best-effort
            pass

    def _push_locked(self, dss: Any) -> None:
        if active:
            # the uplink cadence doubles as the native span-ring drain
            # beat: parks complete between pushes, so the rings stay
            # small and a live timeline capture sees fresh spans
            drain_native_spans()
        cur = metrics_values()
        cur_h = hist_values()
        full = self._n % FULL_EVERY == 0
        vals: dict[str, Any] = (
            dict(cur) if full else
            {k: v for k, v in cur.items()
             if self._last.get(k) != v})
        for key, vec in cur_h.items():
            if full:
                vals[key] = [VEC_ABS, *vec]
                continue
            last = self._last_h.get(key)
            if last is None:
                # a series born between full pushes: its whole
                # vector IS the increment since the last push
                vals[key] = [VEC_DELTA, *vec]
            elif last != vec:
                vals[key] = [VEC_DELTA,
                             *(a - b for a, b in zip(vec, last))]
        self._n += 1
        if not vals and not full:
            return
        pkt = dss.pack(("m1", self.jobid, self.rank, self._n, vals))
        self._sock.sendto(pkt, self._addr)
        # self-metering AFTER the send: the datagram that carried these
        # counters doesn't count itself (the next push reports it)
        count("metrics_push_datagrams_total")
        count("metrics_push_bytes_total", len(pkt))
        self._last = cur
        self._last_h = cur_h

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if flush:
            self._n = 0          # final push is always a full snapshot
            self.push()
        try:
            self._sock.close()
        except OSError:
            pass


_pusher: Optional[_MetricsPusher] = None


def start_metrics_push(jobid: int, rank: int,
                       uri: Optional[str] = None) -> Optional[_MetricsPusher]:
    """Arm the metrics uplink (idempotent): no-op unless a collector URI
    is known (``OMPI_TPU_METRICS_URI``, exported by the owning orted)
    and ``trace_metrics_push_period`` > 0.  Independent of the timeline
    (:data:`active`): the always-on counters are worth scraping even
    when span recording is off."""
    global _pusher
    uri = uri if uri is not None else os.environ.get(ENV_METRICS_URI)
    period = push_period()
    if not uri or ":" not in uri or period <= 0:
        return None
    with _lock:
        if _pusher is None:
            _pusher = _MetricsPusher(jobid, rank, uri, period)
        return _pusher


def stop_metrics_push(flush: bool = True) -> None:
    """Disarm the uplink; ``flush`` sends one last full snapshot so a
    short job's final counter state still reaches the aggregate."""
    global _pusher
    with _lock:
        pusher, _pusher = _pusher, None
    if pusher is not None:
        pusher.stop(flush=flush)
