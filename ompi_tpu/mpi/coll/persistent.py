"""coll/persistent — bind-once persistent collectives (MPI-4 ``*_init``).

≈ MPI_Barrier_init / MPI_Allreduce_init & friends (MPI-4.0 §6.12) and
the MPI Advance persistent-collective work (PAPERS.md): a serving or
training step issues the identical collective sequence millions of
times, yet the one-shot path re-pays the whole dispatch stack on every
call — buffer classification, provider routing, the rules-file /
config-var decision walk, arena descriptor rounds, hierarchy lookups,
nbc schedule construction.  ``*_init`` compiles all of that ONCE into
a frozen plan; ``Start`` is a near-pure publish against pre-pinned
state.

What a bind freezes, by provider:

- ``shm``   — flat one-host communicators: a dedicated
  :class:`~ompi_tpu.mpi.coll.shm.PersistentSlots` segment is mapped
  collectively and pinned for the plan's lifetime — parity-indexed
  (op-sequence mod 2) double-buffered slot sets, so op k+1's publish
  overlaps op k's drain (a rank that finished waiting may immediately
  Start the next op while slower ranks still read the other parity;
  slot reuse is guarded by the depart counters two ops back, never a
  per-op barrier).  All slot numpy views are prebuilt at bind; Start
  is guard-check + ``np.copyto`` + one aligned counter store.
- ``hier``  — mixed-host communicators: the node/leader splits, block
  tables, and the inter-node host algorithm (+ its segment sizes) are
  resolved at bind; the drain runs the frozen composition.
- ``host``  — an explicit ``coll_host_*_algorithm`` /rules-file
  directive outranks the shortcut exactly like the one-shot ladder:
  the named algorithm is frozen (``HostColl.freeze_decision``) and
  runs blocking in the drain.
- ``nbc``   — the p2p ground case: the libnbc-style round schedule is
  pre-materialised at bind (``nbc.*_schedule``); Start launches it
  with a fresh state dict, posting round 0 immediately.
- ``self``  — size-1: Start completes instantly.

Progress model: Start publishes; the remaining work runs on the first
wait()er's thread (the framework's weak-progress model, same as the
nbc schedules).  The flat-arena provider is wait-order-safe across
plans (all cross-rank prerequisites are published at Start); the
hier/host providers run blocking phases in the drain, so outstanding
multi-phase plans must be waited in the same order on every rank.

FT contract: Start on a revoked communicator raises ``ERR_REVOKED``;
a detector-declared-dead member fails the Start fast
(``ERR_PROC_FAILED``); ``Comm.free()`` releases the pinned slots and
poisons every bound plan; a selfheal-revived member invalidates plans
that pinned its slot (the dead life's mapping is gone) — the next
Start detects the stale (bind-agreed) incarnation snapshot and
**auto-rebinds**: the plan recompiles collectively (the revived life's
fresh ``*_init`` pairs with the survivors' rebinds) with no
user-visible error, counted by ``coll_persistent_rebinds_total``.
Explicit :meth:`PersistentCollRequest.rebind` remains available.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Optional

import numpy as np

from ompi_tpu.core import output
from ompi_tpu.core.config import var_registry
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.constants import (
    ERR_PROC_FAILED, ERR_REVOKED, MPIException,
)
from ompi_tpu.mpi.request import (
    CompletedRequest, PersistentRequest, Request,
)

_log = output.get_stream("coll")

__all__ = ["PersistentCollRequest", "barrier_init", "bcast_init",
           "reduce_init", "allreduce_init", "allgather_init",
           "alltoall_init", "alltoallv_init", "reduce_scatter_init",
           "neighbor_alltoall_init", "neighbor_alltoallv_init"]

# persistent plans draw tags from their own reserved window starting at
# 10000 — far above the blocking-collective tags (1-16), the nbc
# sequence window [64, 500), the OSC 500s, and the neighbor-collective
# 700-891 block.  A plan HOLDS its tag for its whole lifetime, so the
# allocator NEVER wraps (a reused tag would cross-match a still-live
# plan's rounds); the window ends where the partitioned wire-tag space
# begins, and exhausting it raises instead of wrapping.
_PCOLL_TAG_BASE = 10_000
_PCOLL_TAG_MAX = 900_000


def _next_ptag(comm) -> int:
    with comm._lock:
        seq = comm._pcoll_seq = getattr(comm, "_pcoll_seq", 0) + 1
    if seq > _PCOLL_TAG_MAX - _PCOLL_TAG_BASE:
        raise MPIException(
            f"persistent-collective tag window exhausted on {comm.name} "
            f"({_PCOLL_TAG_MAX - _PCOLL_TAG_BASE} binds per "
            f"communicator)")
    return _PCOLL_TAG_BASE + seq


# ---------------------------------------------------------------------------
# start-time gates
# ---------------------------------------------------------------------------

def _check_start(comm) -> None:
    """The FT fail-fast gate every Start runs: revoked communicator or
    detector-declared-dead member raises NOW, mirroring the PML's
    check_send discipline (a publish toward a corpse can never
    complete)."""
    if comm.is_revoked():
        raise MPIException(
            f"Start on revoked communicator {comm.name} "
            f"(cid {comm.cid})", error_class=ERR_REVOKED)
    ft = getattr(comm.pml, "ft", None)
    if ft is not None:
        for w in comm.group.ranks:
            if ft.detector.is_dead(w, poll=False):
                raise MPIException(
                    f"Start on {comm.name}: member rank {w} has failed "
                    f"({ft.detector.reason(w) or 'detector-declared'})",
                    error_class=ERR_PROC_FAILED)


def _member_incs(comm) -> tuple:
    """Per-member incarnation snapshot (``ft.member_incs`` — THE shared
    adoption-merge): a bound plan pins peers' slots, and a selfheal-
    revived peer's NEW life never mapped them (the segment name was
    unlinked at bind) — any advance past the bind's agreed snapshot
    means the plan is stale.  Shared with ``ft.comm_coll_epoch`` (its
    sum) so the slots' epoch fence and this staleness gate can never
    drift."""
    from ompi_tpu.mpi import ft as ft_mod

    return ft_mod.member_incs(comm)


def _agree_incs(comm, incs: tuple) -> tuple:
    """Element-wise MAX of the per-member incarnation snapshot over the
    communicator — run once per (re)bind, which is collective anyway.
    The AGREED snapshot is what Start's staleness gate compares
    against: without it, a member that had not yet adopted a revived
    life at bind time would hold a lower snapshot than its peers and
    later auto-rebind ALONE (a collective call nobody pairs).  Rides
    the base p2p plane for the same reason the coll/shm epoch prologue
    does — base tags pair across lives, agree seq numbers do not."""
    if comm.size <= 1:
        return incs
    from ompi_tpu.mpi import op as op_mod
    from ompi_tpu.mpi.coll import base

    local = np.array(incs if incs else [0] * comm.size, np.int64)
    agreed = np.asarray(base.allreduce_recursive_doubling(
        comm, local, op_mod.MAX), np.int64)
    if not incs and not agreed.any():
        return ()        # keep the cheap empty form at job start
    return tuple(int(x) for x in agreed)


def _incs_stale(cur: tuple, bound: tuple, size: int) -> bool:
    """True when a member's CURRENT adopted incarnation exceeds the
    bind's agreed snapshot — a revive since bind.  ``cur`` below the
    snapshot is NOT stale: the bind already included a life this
    process simply has not adopted yet."""
    if cur == bound:
        return False
    c = cur or (0,) * size
    b = bound or (0,) * size
    return any(x > y for x, y in zip(c, b))


def _land(recvbuf: Optional[np.ndarray], out: Any) -> Any:
    """Copy a drain result into the bound receive buffer (when one was
    bound) — the mpi4py-style buffer contract for non-root bcast."""
    if recvbuf is None:
        return out
    arr = np.asarray(out)
    flat = recvbuf.reshape(-1)
    if arr.size != flat.size:
        raise MPIException(
            f"persistent bcast: bound recvbuf has {flat.size} elements, "
            f"payload has {arr.size}")
    flat[...] = arr.reshape(-1).astype(flat.dtype, copy=False)
    return recvbuf


# ---------------------------------------------------------------------------
# the split-phase inner request
# ---------------------------------------------------------------------------

class _LazyRequest(Request):
    """The drain half of a split-phase persistent op: ``run()`` executes
    exactly once, on the first wait()er's thread (the framework's weak
    -progress model, like NbcRequest).  ``poll()`` is an optional
    non-blocking readiness check so test() can complete the op without
    blocking once the publishes it depends on have landed."""

    def __init__(self, run: Callable[[], Any],
                 poll: Optional[Callable[[], bool]] = None,
                 kind: str = "pcoll") -> None:
        super().__init__(kind=kind)
        self._run = run
        self._poll = poll
        self._run_lock = threading.Lock()

    def _execute(self) -> None:
        with self._run_lock:
            if self._flag:
                return
            try:
                out = self._run()
            except BaseException as e:  # noqa: BLE001 — fail the request
                self.fail(e)
                return
            self.complete(out)

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self._flag:
            self._execute()
        return super().wait(timeout=timeout)

    def test(self) -> bool:
        if self._flag:
            return True
        if self._poll is not None and self._poll():
            self._execute()
        return self._flag


# ---------------------------------------------------------------------------
# providers (one frozen plan each)
# ---------------------------------------------------------------------------

class _SelfPlan:
    provider = "self"

    def __init__(self, result_fn: Callable[[], Any]) -> None:
        self._result = result_fn

    def start_op(self) -> Request:
        return CompletedRequest(self._result(), kind="pcoll-self")

    def close(self) -> None:
        pass


class _NbcPlan:
    """Pre-materialised round schedule: the rounds (and every closure
    in them) were built once at bind; Start instantiates an NbcRequest
    with a fresh state dict — round 0 posts immediately (the publish),
    later rounds advance in test()/wait()."""

    provider = "nbc"

    def __init__(self, comm, kind: str, schedule, tag: int,
                 recvbuf: Optional[np.ndarray] = None) -> None:
        from ompi_tpu.mpi.coll import nbc as nbc_mod

        self._nbc = nbc_mod
        self._comm = comm
        self._kind = kind
        self._rounds, self._make_state, result = schedule
        if recvbuf is not None:
            self._result = (lambda s, _r=result: _land(recvbuf, _r(s)))
        else:
            self._result = result
        self._tag = tag

    def start_op(self) -> Request:
        return self._nbc.NbcRequest(
            self._comm, self._rounds, self._result, self._tag,
            kind=f"p{self._kind}", state=self._make_state())

    def close(self) -> None:
        pass


class _DrainPlan:
    """host/hier providers: Start is the FT gate + sequencing only; the
    frozen composition runs blocking in the drain (weak progress)."""

    def __init__(self, provider: str, run: Callable[[], Any],
                 kind: str) -> None:
        self.provider = provider
        self._run = run
        self._kind = kind

    def start_op(self) -> Request:
        return _LazyRequest(self._run, kind=f"p{self._kind}")

    def close(self) -> None:
        pass


class _ArenaPlan:
    """Flat one-host plan over a pinned PersistentSlots segment.

    Counter protocol (all inherited Arena waits — monotonic u64,
    FT-checked, dead-writer-probed): ``arrive[r]`` counts ops rank r
    has published, ``depart[r]`` counts ops consumed (for the fold
    rank: folded).  Op k uses parity q = k mod 2; reuse of a parity-q
    slot by op k is guarded by the departs of op k-2 — the
    double-buffer overlap window.

    Allreduce binds one of two fold strategies (the
    ``decide_allreduce_algo`` ladder):

    - ``root_fold`` — rank 0 folds every slot (arrive +1/op);
    - ``segment_parallel`` — every rank reduce-scatters its 1/p element
      segment across ALL slots into the result slot, then allgathers by
      reading the whole result (the PiP/multi-process-per-GPU
      cooperative shape: O(n) fold work per rank, no single-rank
      bottleneck).  The arrive counter advances by TWO per op — 2k+1 =
      "op k published", 2k+2 = "op k's segment folded" — and the
      publish guard waits ALL departs of op k-2 (every rank reads every
      input slot and the whole result slot).  NOTE: a rank's completion
      needs every OTHER rank's fold (which runs on their wait), so
      outstanding segment-parallel plans must be waited in the same
      order on every rank — the hier/host providers' existing rule, not
      the root-fold arena's anything-order.
    """

    provider = "shm"

    def __init__(self, comm, kind: str, slots, buf, op, root: int,
                 shape, dtype, recvbuf: Optional[np.ndarray] = None,
                 algorithm: Optional[str] = None) -> None:
        from ompi_tpu.mpi.coll import shm as shm_mod

        self._shm = shm_mod
        self._comm = comm
        self._kind = kind
        self._slots = slots
        self._buf = buf
        self._op = op
        self._root = root
        self._shape = tuple(shape)
        self._dtype = np.dtype(dtype)
        self._n = int(np.prod(self._shape)) if self._shape else 1
        self._recvbuf = recvbuf
        self._k = 0
        self.algorithm = algorithm
        self._segpar = algorithm == "segment_parallel"
        p = comm.size
        # prebuilt slot views AND native offsets — the per-op
        # np.frombuffer / address arithmetic of the one-shot arena,
        # paid once here
        if kind in ("reduce", "allreduce", "allgather"):
            self._in = [[np.frombuffer(slots.pslot(q, r), self._dtype,
                                       self._n) for r in range(p)]
                        for q in (0, 1)]
            self._in_off = [[slots.pslot_off(q, r) for r in range(p)]
                            for q in (0, 1)]
        if kind in ("allreduce", "bcast"):
            ridx = p if kind == "allreduce" else 0
            self._res = [np.frombuffer(slots.pslot(q, ridx), self._dtype,
                                       self._n) for q in (0, 1)]
            self._res_off = [slots.pslot_off(q, ridx) for q in (0, 1)]
        # my reduce-scatter segment (element bounds, segment_parallel)
        self._seg_lo = comm.rank * self._n // p
        self._seg_hi = (comm.rank + 1) * self._n // p
        # native fold eligibility, frozen at bind (the executor handle
        # itself is re-resolved per call: benches flip coll_shm_native
        # mid-world for shared-fate comparisons)
        dc = shm_mod._fold_code(self._dtype)
        oc = shm_mod._NATIVE_OP_CODES.get(op) if op is not None else None
        self._fold_codes = ((dc, oc) if dc is not None and oc is not None
                            else None)

    def _fold_exec(self):
        """The native executor when this plan's fold can ride it."""
        s = self._slots
        if (self._fold_codes is None or s is None
                or s._base_addr is None
                or self._n * self._dtype.itemsize
                < self._shm._NATIVE_PUBLISH_MIN):
            return None
        return self._shm._exec()

    # -- plumbing ----------------------------------------------------------

    def _as_bound(self) -> np.ndarray:
        """Re-read the bound buffer (the persistent contract) and hold
        it to the frozen signature — the slot views were compiled for
        exactly this shape/dtype."""
        arr = np.asarray(self._buf)
        if arr.shape != self._shape or arr.dtype != self._dtype:
            raise MPIException(
                f"persistent {self._kind}: bound buffer changed to "
                f"{arr.dtype}{list(arr.shape)} since bind "
                f"({self._dtype}{list(self._shape)}); free() and "
                f"re-init")
        return arr

    def close(self) -> None:
        slots, self._slots = self._slots, None
        # drop the numpy views before detaching the mapping they pin
        self._in = self._res = None
        if slots is not None:
            slots.close()

    def _all_arrived(self, k: int) -> bool:
        s = self._slots
        return all(s.arrive_at(r) >= k + 1 for r in range(s.size))

    # -- Start: the publish half -------------------------------------------

    def start_op(self) -> Request:
        if self._slots is None:
            raise MPIException(
                f"Start on a closed persistent {self._kind} plan")
        k = self._k
        self._k += 1
        q = k & 1
        comm, s, kind = self._comm, self._slots, self._kind
        if kind == "barrier":
            s._set_arrive(k + 1)
            return _LazyRequest(lambda: self._drain_barrier(k),
                                poll=lambda: self._all_arrived(k),
                                kind="pbarrier")
        if kind == "bcast":
            if comm.rank == self._root:
                arr = self._as_bound()
                if k >= 2:         # readers done with this parity's
                    s._wait_all_depart(k - 1, comm)   # k-2 occupant
                _h_t0 = (time.monotonic_ns()
                         if trace_mod.hist_active else 0)
                if not s._publish_arrive(self._res_off[q], arr, k + 1):
                    np.copyto(self._res[q].reshape(self._shape), arr,
                              casting="no")
                    s._set_arrive(k + 1)
                s._set_depart(k + 1)
                if _h_t0:
                    # publish half of the straggler split: slot copy +
                    # flag store, no waits (those land in
                    # coll_arena_wait_ns)
                    trace_mod.record_hist(
                        "coll_ppublish_ns",
                        time.monotonic_ns() - _h_t0)
                trace_mod.coll_event(comm.pml.rank, comm.cid, "pub",
                                     {"k": k})
                return CompletedRequest(arr, kind="pbcast")
            return _LazyRequest(
                lambda: self._drain_bcast(k),
                poll=lambda: s.arrive_at(self._root) >= k + 1,
                kind="pbcast")
        # data publishers: reduce / allreduce / allgather
        arr = self._as_bound()
        segpar = kind == "allreduce" and self._segpar
        if kind == "allgather" or segpar:
            if k >= 2:   # every rank reads every slot (segment_parallel
                # additionally reads the whole result): all departs
                s._wait_all_depart(k - 1, comm)
        else:
            fold = 0 if kind == "allreduce" else self._root
            if k >= 2:
                s._wait_depart(fold, k - 1, comm)
        _h_t0 = time.monotonic_ns() if trace_mod.hist_active else 0
        arrive = 2 * k + 1 if segpar else k + 1
        if not s._publish_arrive(self._in_off[q][comm.rank], arr,
                                 arrive):
            np.copyto(self._in[q][comm.rank].reshape(self._shape), arr,
                      casting="no")
            s._set_arrive(arrive)
        if _h_t0:
            trace_mod.record_hist("coll_ppublish_ns",
                                  time.monotonic_ns() - _h_t0)
        trace_mod.coll_event(comm.pml.rank, comm.cid, "pub", {"k": k})
        if kind == "reduce":
            if comm.rank != self._root:
                # contribution is in the slot: locally complete (the
                # publish guard two ops out is the only backpressure)
                return CompletedRequest(None, kind="preduce")
            return _LazyRequest(lambda: self._drain_reduce(k),
                                poll=lambda: self._all_arrived(k),
                                kind="preduce")
        if kind == "allgather":
            return _LazyRequest(lambda: self._drain_allgather(k),
                                poll=lambda: self._all_arrived(k),
                                kind="pallgather")
        if segpar:
            return _LazyRequest(lambda: self._drain_allreduce_segpar(k),
                                poll=lambda: self._segpar_ready(k),
                                kind="pallreduce")
        if comm.rank == 0:
            return _LazyRequest(lambda: self._drain_allreduce(k),
                                poll=lambda: self._all_arrived(k),
                                kind="pallreduce")
        return _LazyRequest(lambda: self._drain_allreduce(k),
                            poll=lambda: s.depart_at(0) >= k + 1,
                            kind="pallreduce")

    # -- drains ------------------------------------------------------------

    def _drain_barrier(self, k: int) -> None:
        self._slots._wait_all_arrive(k + 1, self._comm)
        return None

    def _drain_bcast(self, k: int):
        q = k & 1
        s, comm = self._slots, self._comm
        s._wait_arrive(self._root, k + 1, comm)
        rb = self._recvbuf
        if rb is not None:
            flat = rb.reshape(-1)
            if not (rb.dtype == self._dtype
                    and s._copy_out_native(self._res_off[q], flat)):
                np.copyto(flat, self._res[q].astype(rb.dtype, copy=False))
            out = rb
        else:
            out = np.empty(self._n, self._dtype)
            if not s._copy_out_native(self._res_off[q], out):
                np.copyto(out, self._res[q])
            out = out.reshape(self._shape)
        s._set_depart(k + 1)
        return out

    def _fold(self, k: int) -> np.ndarray:
        """Rank-ordered fold straight over the parity-q slots — one
        GIL-released native call when the (op, dtype) pair compiled,
        the numpy view chain otherwise (bit-identical either way)."""
        trace_mod.coll_event(self._comm.pml.rank, self._comm.cid,
                             "fold", {"k": k})
        q = k & 1
        ex = self._fold_exec()
        if ex is not None:
            out = np.empty(self._n, self._dtype)
            s = self._slots
            self._shm._native_fold(
                ex, out.ctypes.data,
                [s._base_addr + off for off in self._in_off[q]],
                self._n, *self._fold_codes)
            return out
        views = self._in[q]
        acc = views[0]
        op = self._op
        for r in range(1, self._comm.size):
            acc = op.host(acc, views[r])
        # op.host returned a fresh array (size >= 2 members), so the
        # result does not alias the mapped slots
        return np.asarray(acc, self._dtype)

    def _drain_reduce(self, k: int):
        s, comm = self._slots, self._comm
        s._wait_all_arrive(k + 1, comm)
        out = self._fold(k)
        s._set_depart(k + 1)
        return out.reshape(self._shape)

    def _drain_allreduce(self, k: int):
        q = k & 1
        s, comm = self._slots, self._comm
        if comm.rank == 0:
            s._wait_all_arrive(k + 1, comm)
            if k >= 2:   # readers done with this parity's k-2 result
                s._wait_all_depart(k - 1, comm)
            ex = self._fold_exec()
            if ex is not None:
                # fold straight INTO the mapped result slot (the guard
                # above cleared it), then copy the root's own result out
                self._shm._native_fold(
                    ex, s._base_addr + self._res_off[q],
                    [s._base_addr + off for off in self._in_off[q]],
                    self._n, *self._fold_codes)
                out = np.empty(self._n, self._dtype)
                if not s._copy_out_native(self._res_off[q], out):
                    np.copyto(out, self._res[q])
            else:
                out = self._fold(k)
                np.copyto(self._res[q], out.reshape(-1), casting="no")
            s._set_depart(k + 1)
            return out.reshape(self._shape)
        s._wait_depart(0, k + 1, comm)
        out = np.empty(self._n, self._dtype)
        if not s._copy_out_native(self._res_off[q], out):
            np.copyto(out, self._res[q])
        s._set_depart(k + 1)
        return out.reshape(self._shape)

    # -- segment-parallel allreduce (the cooperative every-rank path) ------

    def _segpar_ready(self, k: int) -> bool:
        """Non-blocking completion poll: every OTHER rank folded its
        segment (arrive 2k+2 — their drains ran), mine is published
        (my own fold runs on this thread inside the drain)."""
        s, me = self._slots, self._comm.rank
        if s.arrive_at(me) < 2 * k + 1:
            return False
        return all(s.arrive_at(r) >= 2 * k + 2
                   for r in range(s.size) if r != me)

    def _drain_allreduce_segpar(self, k: int):
        """Reduce-scatter my 1/p segment across all slots into the
        result slot, then allgather by reading the whole result —
        O(n) fold work per rank instead of the root's O(p·n), viable
        because the concurrent folds and parks run GIL-released."""
        q = k & 1
        s, comm = self._slots, self._comm
        s._wait_all_arrive(2 * k + 1, comm)     # everyone published op k
        lo, hi = self._seg_lo, self._seg_hi
        if hi > lo:
            isz = self._dtype.itemsize
            ex = self._fold_exec()
            if ex is not None:
                self._shm._native_fold(
                    ex, s._base_addr + self._res_off[q] + lo * isz,
                    [s._base_addr + off + lo * isz
                     for off in self._in_off[q]], hi - lo,
                    *self._fold_codes)
            else:
                views = self._in[q]
                acc = views[0][lo:hi]
                op = self._op
                for r in range(1, comm.size):
                    acc = op.host(acc, views[r][lo:hi])
                np.copyto(self._res[q][lo:hi],
                          np.asarray(acc, self._dtype), casting="no")
        s._set_arrive(2 * k + 2)                # my segment is folded
        try:
            s._wait_all_arrive(2 * k + 2, comm)  # every segment is
        except MPIException as e:
            if "coll_shm_timeout" in str(e):
                # the fold we are missing runs inside a PEER's drain:
                # the usual cause is divergent wait order across
                # outstanding segment_parallel plans — name the
                # contract in the failure instead of reading as a hang
                raise MPIException(
                    f"{e} — outstanding segment_parallel allreduce "
                    f"plans must be waited in the same order on every "
                    f"rank (each rank's completion needs every other "
                    f"rank's fold); wait them in one order, or bind "
                    f"root_fold via coll_shm_allreduce_algorithm to "
                    f"restore anything-order waits",
                    error_class=getattr(e, "error_class", 13)
                ) from None
            raise
        out = np.empty(self._n, self._dtype)
        if not s._copy_out_native(self._res_off[q], out):
            np.copyto(out, self._res[q])
        s._set_depart(k + 1)
        return out.reshape(self._shape)

    def _drain_allgather(self, k: int):
        q = k & 1
        s, comm = self._slots, self._comm
        s._wait_all_arrive(k + 1, comm)
        out = np.empty((comm.size,) + self._shape, self._dtype)
        for r in range(comm.size):
            out[r] = self._in[q][r].reshape(self._shape)
        s._set_depart(k + 1)
        return out


# ---------------------------------------------------------------------------
# bind: provider resolution (collective)
# ---------------------------------------------------------------------------

def _arena_dtype_ok(dtype: np.dtype) -> bool:
    from ompi_tpu.mpi.coll import shm as shm_mod

    return shm_mod._arena_dtype_ok(dtype) and shm_mod._desc_dtype_ok(dtype)


def _bcast_meta(comm, buf, root: int):
    """Bind-time signature exchange for bcast: only the root knows the
    payload, so its (nbytes, shape, dtype, arena-eligibility) ride ONE
    base-algorithm bcast here — the per-op descriptor round of the
    one-shot arena path, paid once."""
    from ompi_tpu.mpi.coll import base

    if comm.rank == root:
        arr = np.asarray(buf)
        ok = 1 if _arena_dtype_ok(arr.dtype) else 0
        ints = np.array([arr.nbytes, arr.ndim, ok] + list(arr.shape),
                        np.int64)
        dts = arr.dtype.str.encode()[:32].ljust(32, b"\0")
        payload = np.concatenate([ints.view(np.uint8),
                                  np.frombuffer(dts, np.uint8)])
        base.bcast_binomial(comm, payload, root)
        return arr.shape, arr.dtype, int(arr.nbytes), bool(ok)
    got = np.ascontiguousarray(
        np.asarray(base.bcast_binomial(comm, None, root), np.uint8))
    ints = got[:-32].view(np.int64)
    nbytes, ndim, ok = int(ints[0]), int(ints[1]), int(ints[2])
    shape = tuple(int(x) for x in ints[3:3 + ndim])
    raw = bytes(got[-32:]).rstrip(b"\0").decode()
    try:
        dtype = np.dtype(raw) if raw else np.dtype(np.uint8)
    except TypeError:
        dtype, ok = np.dtype(np.uint8), 0
    return shape, dtype, nbytes, bool(ok)


def _freeze_directive(host, kind: str, comm, nbytes: int) -> Optional[str]:
    """A forced ``coll_host_*_algorithm`` var or rules-file hit — user
    tuning the persistent shortcut must honor, resolved once."""
    if kind not in ("bcast", "allreduce", "allgather",
                    "alltoall", "reduce_scatter"):
        return None
    return host._decide(kind, comm, 0 if kind == "bcast" else nbytes)


def _shm_state(comm):
    """The shm component's cached dispatch state, or None when the
    component is disabled/unusable or settled on host mode."""
    from ompi_tpu.mpi.coll import coll_framework
    from ompi_tpu.mpi.coll import shm as shm_mod  # noqa: F401 — register

    comp = coll_framework.lookup("shm")
    if comp.query(comm=comm) is None:
        return None, comp
    st = comp._state(comm)
    if st is None or getattr(st, "mode", "host") == "host":
        return None, comp
    return st, comp


def _bind(comm, kind: str, buf=None, op=None, root: int = 0,
          recvbuf: Optional[np.ndarray] = None):
    """Compile one frozen plan — collective over ``comm``."""
    from ompi_tpu.mpi.coll import coll_framework
    from ompi_tpu.mpi.coll import nbc as nbc_mod

    if comm.is_revoked():
        raise MPIException(
            f"{kind}_init on revoked communicator {comm.name}",
            error_class=ERR_REVOKED)
    if kind in ("bcast", "reduce") and not 0 <= root < comm.size:
        raise MPIException(
            f"{kind}_init: root {root} out of range for {comm.name} "
            f"(size {comm.size})", error_class=6)

    # size-1: everything degenerates locally (≈ coll/self)
    if comm.size == 1:
        results = {
            "barrier": lambda: None,
            "bcast": lambda: _land(recvbuf, np.asarray(buf)),
            "reduce": lambda: np.asarray(buf),
            "allreduce": lambda: np.asarray(buf),
            "allgather": lambda: np.asarray(buf)[None],
        }
        return _SelfPlan(results[kind])

    # frozen signature (bcast: root's, exchanged once)
    if kind == "bcast":
        shape, dtype, nbytes, dtype_ok = _bcast_meta(comm, buf, root)
        if recvbuf is not None and comm.rank != root:
            if recvbuf.size * recvbuf.dtype.itemsize != nbytes \
                    and dtype_ok:
                raise MPIException(
                    f"bcast_init: bound recvbuf is "
                    f"{recvbuf.size * recvbuf.dtype.itemsize}B, root's "
                    f"payload is {nbytes}B")
    elif kind == "barrier":
        shape, dtype, nbytes, dtype_ok = (), np.dtype(np.uint8), 0, True
    else:
        arr = np.asarray(buf)
        shape, dtype, nbytes = arr.shape, arr.dtype, int(arr.nbytes)
        dtype_ok = _arena_dtype_ok(dtype)

    host = coll_framework.lookup("host")
    directive = _freeze_directive(host, kind, comm, nbytes)
    st, comp = _shm_state(comm)
    cap = int(var_registry.get("coll_shm_arena_size") or 0)
    commutative = op is None or op.commutative

    arena_ok = (st is not None and st.mode == "arena"
                and directive is None and dtype_ok and nbytes <= cap)
    if kind in ("reduce", "allreduce"):
        arena_ok = arena_ok and commutative
    if kind == "allgather":
        arena_ok = arena_ok and nbytes * comm.size <= cap

    if arena_ok:
        plan = _bind_arena(comm, kind, buf, op, root, shape, dtype,
                           nbytes, recvbuf)
        if plan is not None:
            return plan
        # mapping failed (MIN-agreed): every rank falls through together

    if st is not None and st.mode == "hier" and directive is None:
        return _bind_hier(comp, st, host, comm, kind, buf, op, root,
                          nbytes, recvbuf)

    if directive is not None:
        fn, label = host.freeze_decision(kind, comm, nbytes, op)
        runs = {
            "bcast": lambda: _land(
                recvbuf if comm.rank != root else None,
                fn(comm, buf if comm.rank == root else None, root)),
            "allreduce": lambda: fn(comm, np.asarray(buf), op),
            "allgather": lambda: fn(comm, np.asarray(buf)),
        }
        return _DrainPlan("host", runs[kind], kind)

    # p2p ground case: pre-materialised nbc rounds
    schedules = {
        "barrier": lambda: nbc_mod.barrier_schedule(comm),
        "bcast": lambda: nbc_mod.bcast_schedule(
            comm, buf if comm.rank == root else None, root),
        "reduce": lambda: nbc_mod.reduce_schedule(comm, buf, op, root),
        "allreduce": lambda: nbc_mod.allreduce_schedule(comm, buf, op),
        "allgather": lambda: nbc_mod.allgather_schedule(comm, buf),
    }
    return _NbcPlan(comm, kind, schedules[kind](), _next_ptag(comm),
                    recvbuf=recvbuf if kind == "bcast"
                    and comm.rank != root else None)


def _bind_arena(comm, kind, buf, op, root, shape, dtype, nbytes,
                recvbuf) -> Optional[_ArenaPlan]:
    from ompi_tpu.mpi.coll import shm as shm_mod

    p = comm.size
    algorithm = None
    if kind == "allreduce":
        # fold strategy frozen at bind: root_fold vs segment_parallel,
        # resolved by the standard ladder (forced var > rules file >
        # payload crossover) — every rank computes the same verdict
        # from globally-agreed inputs
        algorithm, src = shm_mod.decide_allreduce_algo(comm, nbytes)
        if trace_mod.active:
            trace_mod.instant(
                "coll", "decision:shm_allreduce", rank=comm.pml.rank,
                algorithm=algorithm, source=src, nbytes=nbytes,
                size=comm.size)
    nslots = {"barrier": 0, "bcast": 1, "allgather": p,
              "reduce": p + 1, "allreduce": p + 1}[kind]
    slots = shm_mod.make_persistent_slots(comm, nbytes, nslots)
    if slots is None:
        return None
    return _ArenaPlan(comm, kind, slots, buf, op, root, shape, dtype,
                      recvbuf=recvbuf if kind == "bcast"
                      and comm.rank != root else None,
                      algorithm=algorithm)


def _bind_hier(comp, st, host, comm, kind, buf, op, root, nbytes,
               recvbuf) -> _DrainPlan:
    """Freeze the hierarchical composition: node/leader comms and block
    tables come from the cached shm state; the inter-node algorithm is
    resolved by ``HostColl.freeze_decision`` now, not per op."""
    from ompi_tpu.mpi.coll import base

    leader = st.leader
    if kind == "barrier":
        inter = (host.freeze_decision("barrier", leader, 0)[0]
                 if leader is not None else None)

        def run():
            comp._intra_gate_in(st)
            if inter is not None:
                inter(leader)
            comp._intra_gate_out(st)
            return None

        return _DrainPlan("hier", run, kind)

    my_idx = st.node_idx_of[comm.rank]
    if kind == "bcast":
        root_idx = st.node_idx_of[root]
        nroot = (st.node.group.rank_of(comm.world_rank(root))
                 if my_idx == root_idx and st.node.size > 1 else 0)
        inter = (host.freeze_decision("bcast", leader, 0)[0]
                 if leader is not None else None)

        def run():
            data = buf
            if my_idx == root_idx and st.node.size > 1:
                data = comp._intra_bcast(st, data, nroot)
            if inter is not None:
                data = inter(leader,
                             data if my_idx == root_idx else None,
                             root_idx)
            if my_idx != root_idx:
                data = comp._intra_bcast(st, data, 0)
            return _land(recvbuf if comm.rank != root else None,
                         np.asarray(data))

        return _DrainPlan("hier", run, kind)

    if kind == "allreduce":
        inter = (host.freeze_decision("allreduce", leader, nbytes, op)[0]
                 if leader is not None else None)

        def run():
            arr = np.asarray(buf)
            partial = comp._intra_reduce(st, arr, op)
            total = partial
            if inter is not None:
                total = inter(leader, partial, op)
            out = comp._intra_bcast(st, total, 0)
            return np.asarray(out).reshape(arr.shape).astype(
                arr.dtype, copy=False)

        return _DrainPlan("hier", run, kind)

    if kind == "reduce":
        root_idx = st.node_idx_of[root]
        root_leader = st.node_blocks[root_idx][0]
        inter = (host.freeze_decision("reduce", leader, nbytes)[0]
                 if leader is not None else None)

        def run():
            arr = np.asarray(buf)
            partial = comp._intra_reduce(st, arr, op)
            out = None
            if inter is not None:
                out = inter(leader, partial, op, root_idx)
            if root_leader != root:   # root is not its node's leader
                if comm.rank == root_leader:
                    comm._coll_isend(out, root, base.TAG_REDUCE).wait()
                    out = None
                elif comm.rank == root:
                    out = comm._coll_irecv(None, root_leader,
                                           base.TAG_REDUCE).wait()
                    out = out.reshape(arr.shape).astype(arr.dtype,
                                                        copy=False)
            return out if comm.rank == root else None

        return _DrainPlan("hier", run, kind)

    # allgather: node gather → leader allgatherv → reorder → node bcast
    from ompi_tpu.mpi.coll import shm as shm_mod

    node = st.node
    node_blocks = st.node_blocks
    raw_ok = shm_mod._arena_dtype_ok(np.asarray(buf).dtype)

    def run():
        arr = np.asarray(buf)
        if node.size > 1:
            if (st.arena is not None and raw_ok
                    and arr.nbytes <= st.arena.slot_bytes):
                trace_mod.count("coll_shm_fanin_total")
                block = st.arena.allgather(node, arr)
            else:
                block = base.allgather_ring(node, arr)
        else:
            block = arr[None]
        full = None
        if st.leader is not None:
            rows = base.allgatherv_ring(
                st.leader, np.ascontiguousarray(block).reshape(
                    block.shape[0], -1))
            full = np.empty((comm.size, max(arr.size, 0)), arr.dtype)
            for bi, blk in enumerate(rows):
                full[np.asarray(node_blocks[bi])] = np.asarray(
                    blk, arr.dtype).reshape(len(node_blocks[bi]), -1)
        full = comp._intra_bcast(st, full, 0)
        return np.asarray(full, arr.dtype).reshape(
            (comm.size,) + arr.shape)

    return _DrainPlan("hier", run, kind)


def _bind_dense(comm, kind: str, buf=None, op=None):
    """Compile a dense-exchange plan (alltoall / alltoallv /
    reduce_scatter) — collective over ``comm``.

    Dense kinds carry p× the payload of a fan-in collective, so they
    never pin private slots: the shm component's cached ``_state``
    (node/leader splits, arena mapping, reorder tables) IS the
    precompiled schedule, and it is already epoch-fenced.  The bind
    therefore freezes the ROUTE (arena vs hier vs host) plus the
    host-side algorithm pick, and Start is one dispatch against the
    frozen provider.  A revived member invalidates the agreed
    incarnation snapshot exactly like the slot-backed kinds
    (``_incs_stale`` in ``_launch`` → auto-rebind)."""
    from ompi_tpu.mpi.coll import coll_framework

    if comm.is_revoked():
        raise MPIException(
            f"{kind}_init on revoked communicator {comm.name}",
            error_class=ERR_REVOKED)

    # size-1: ≈ coll/self's dense contracts
    if comm.size == 1:
        results = {
            "alltoall": lambda: np.asarray(buf),
            "alltoallv": lambda: [np.empty(0, np.uint8)
                                  if buf[0] is None
                                  else np.asarray(buf[0])],
            "reduce_scatter": lambda: np.asarray(buf).reshape(-1),
        }
        return _SelfPlan(results[kind])

    if kind == "alltoallv":
        if len(buf) != comm.size:
            raise MPIException(
                f"alltoallv_init: need {comm.size} send parts, got "
                f"{len(buf)}", error_class=2)
        nbytes = sum(int(np.asarray(p).nbytes)
                     for p in buf if p is not None)
    else:
        nbytes = int(np.asarray(buf).nbytes)

    host = coll_framework.lookup("host")
    directive = _freeze_directive(host, kind, comm, nbytes)
    st, comp = _shm_state(comm)

    if st is not None and directive is None:
        runs = {
            "alltoall": lambda: comp.coll_alltoall(
                comm, np.asarray(buf)),
            "alltoallv": lambda: comp.coll_alltoallv(comm, list(buf)),
            "reduce_scatter": lambda: comp.coll_reduce_scatter(
                comm, np.asarray(buf), op),
        }
        return _DrainPlan("shm" if st.mode == "arena" else "hier",
                          runs[kind], kind)

    fn, _label = host.freeze_decision(kind, comm, nbytes, op)
    runs = {
        "alltoall": lambda: fn(comm, np.asarray(buf)),
        "alltoallv": lambda: fn(comm, list(buf)),
        "reduce_scatter": lambda: fn(comm, np.asarray(buf), op),
    }
    return _DrainPlan("host", runs[kind], kind)


def _bind_neighbor(comm, kind: str, parts):
    """Compile a persistent neighborhood exchange over the comm's
    attached topology (cart / graph / dist_graph).

    The wire plan — per-edge slot indices and tags, the subtle part of
    the neighbor discipline (parallel-edge pairing on 2-cycle tori) —
    is frozen once from ``topo._edge_meta``; only the bound send parts
    are re-read at each Start.  Topology is immutable state on the
    communicator, so a revive-triggered rebind reproduces the same
    plan under a fresh tag window."""
    from ompi_tpu.mpi import topo as topo_mod

    if comm.is_revoked():
        raise MPIException(
            f"{kind}_init on revoked communicator {comm.name}",
            error_class=ERR_REVOKED)
    tag = _next_ptag(comm)
    srcs, send_meta, recvs = topo_mod._edge_meta(comm, len(parts), tag)

    def run():
        rreq_by_i = {i: comm._coll_irecv(None, s, t)
                     for i, s, t in recvs}
        sreqs = [comm._coll_isend(np.asarray(parts[j]), d, t)
                 for j, d, t in send_meta]
        out = [rreq_by_i[i].wait() if i in rreq_by_i else None
               for i in range(len(srcs))]
        for s in sreqs:
            s.wait()
        return out

    return _DrainPlan("topo", run, kind)


# ---------------------------------------------------------------------------
# the public request
# ---------------------------------------------------------------------------

class PersistentCollRequest(PersistentRequest):
    """A bound persistent collective: created inactive by ``*_init``,
    armed by start()/Startall, waited like any persistent request.
    The plan (provider, slots, schedule, decisions) is compiled once
    in the constructor; each start() re-runs only the FT gate, the
    staleness check, and the provider's publish."""

    def __init__(self, comm, kind: str,
                 binder: Callable[[], Any]) -> None:
        self._comm = comm
        self._ckind = kind
        self._binder = binder
        self._plan = None
        self._incs: tuple = ()
        # recorder signature of this plan's Starts (kind + world size:
        # a persistent op's shape is frozen at bind, so the signature
        # cannot drift between Starts)
        self._rec_sig = trace_mod.collrec_sig(f"p{kind}", None, comm.size)
        super().__init__(self._launch, kind=f"persistent-{kind}")
        self._compile(first=True)
        comm._persistent_colls.append(weakref.ref(self))

    def _compile(self, first: bool) -> None:
        t0 = trace_mod.begin() if trace_mod.active else 0
        self._plan = self._binder()
        # the staleness snapshot is AGREED across the members (element-
        # wise MAX — one base allreduce on a path that is collective
        # anyway), so every rank's Start reaches the same stale/fresh
        # verdict and the auto-rebind stays collective
        self._incs = _agree_incs(self._comm, _member_incs(self._comm))
        slots = getattr(self._plan, "_slots", None)
        if slots is not None and getattr(slots, "_fence", None) is not None:
            # re-stamp the pinned slots' epoch fence with the agreed
            # snapshot's epoch (sum of agreed incarnations): a member
            # that bound pre-adoption must not spuriously fence a life
            # the bind already included
            slots._fence = (sum(self._incs), slots._fence[1])
        trace_mod.count("coll_persistent_binds_total")
        if not first:
            trace_mod.count("coll_persistent_rebinds_total")
        if t0:
            trace_mod.complete(
                "coll", f"persistent_bind:{self._ckind}", t0,
                rank=self._comm.pml.rank, cid=self._comm.cid,
                provider=self._plan.provider, rebind=not first)

    @property
    def provider(self) -> Optional[str]:
        """Which layer the plan bound to: shm | hier | host | nbc |
        topo | self (None once freed)."""
        return self._plan.provider if self._plan is not None else None

    @property
    def algorithm(self) -> Optional[str]:
        """The bound fold strategy, where the plan has one (shm
        allreduce: root_fold | segment_parallel)."""
        return getattr(self._plan, "algorithm", None)

    def _launch(self) -> Request:
        plan = self._plan
        if plan is None:
            raise MPIException(
                f"Start on a freed persistent {self._ckind} plan "
                f"(Comm.free() released its pinned slots)")
        comm = self._comm
        _check_start(comm)
        if _incs_stale(_member_incs(comm), self._incs, comm.size):
            # a member was revived since bind: its pinned slot mapping
            # is gone.  AUTO-rebind here instead of raising — Start is
            # issued on every rank (and the revived life re-inits its
            # plan, a fresh collective bind that pairs with these
            # rebinds), so the recompile is collective; the revive
            # stays invisible to the application
            _log.verbose(1, "persistent %s on %s: member revived since "
                         "bind — auto-rebind", self._ckind, comm.name)
            self.rebind()
            plan = self._plan
        trace_mod.count("coll_persistent_starts_total")
        # collective flight recorder: every Start posts under the
        # "p<kind>" name with its own (rank, cid) op_seq; completion of
        # the inner request records done — a wedged Start therefore
        # leaves a post-without-done head the hang doctor reads
        rank = comm.pml.rank
        seq = trace_mod.coll_post(
            rank, comm.cid, f"p{self._ckind}", self._rec_sig,
            plan.provider, 0)
        # Start→completion latency: stamped here, recorded when the
        # inner request completes (CompletedRequest fires the callback
        # inline, so a locally-complete publish still lands a sample)
        _h_t0 = trace_mod.begin() if trace_mod.hist_active else 0
        req = plan.start_op()

        def _rec_close(_r, r=rank, c=comm.cid, s=seq,
                       k=f"p{self._ckind}"):
            # completion callbacks also fire from Request.fail() — a
            # failed Start must record err, not done (the doctor's
            # "an err-closed wait keeps its wait-for edge" contract)
            exc = getattr(_r, "_exc", None)
            if exc is not None:
                trace_mod.coll_err(r, c, s, k, type(exc).__name__)
            else:
                trace_mod.coll_done(r, c, s, k)

        req.add_completion_callback(_rec_close)
        if _h_t0:
            labels = (f'kind="{self._ckind}",'
                      f'provider="{plan.provider}"')
            req.add_completion_callback(
                lambda _r, t0=_h_t0, lb=labels: trace_mod.record_hist(
                    "coll_pstart_ns", time.monotonic_ns() - t0,
                    labels=lb))
        return req

    def _rebind_if_stale(self) -> bool:
        """Recompile iff a member was revived since bind (the coll/shm
        rejoin calls this for every plan on the comm, in bind order, so
        the survivors' rebind collectives pair with the revived life's
        re-executed prologue ``*_init`` calls).  Active or freed plans
        are left alone — the Start-gate / wait failure paths own
        those."""
        if self._plan is None or self.active:
            return False
        comm = self._comm
        if not _incs_stale(_member_incs(comm), self._incs, comm.size):
            return False
        _log.verbose(1, "persistent %s on %s: member revived since bind "
                     "— rejoin rebind", self._ckind, comm.name)
        self.rebind()
        return True

    def rebind(self) -> "PersistentCollRequest":
        """Recompile the bound plan on the same communicator —
        collective over it, like ``*_init``.  Run automatically by the
        next Start after a revived member invalidated the pinned slots
        (the stale-snapshot gate in ``_launch``); callable explicitly
        for eager recompilation."""
        if self.active:
            raise MPIException(
                "rebind on an active persistent request (wait it first)")
        old, self._plan = self._plan, None
        self._inner = None
        if old is not None:
            old.close()
        self._compile(first=False)
        return self

    def free(self) -> None:
        """≈ MPI_Request_free: release the pinned slots; later starts
        raise."""
        plan, self._plan = self._plan, None
        if plan is not None:
            plan.close()
        super().free()


# ---------------------------------------------------------------------------
# public constructors (Communicator delegates here)
# ---------------------------------------------------------------------------

def barrier_init(comm) -> PersistentCollRequest:
    """≈ MPI_Barrier_init."""
    return PersistentCollRequest(comm, "barrier",
                                 lambda: _bind(comm, "barrier"))


def bcast_init(comm, buf=None, root: int = 0) -> PersistentCollRequest:
    """≈ MPI_Bcast_init: on the root ``buf`` is the (re-read) payload;
    on other ranks an optional landing buffer filled at each wait."""
    rb = None
    if comm.rank != root and isinstance(buf, np.ndarray):
        rb = buf
        if not rb.flags["C_CONTIGUOUS"] or not rb.flags.writeable:
            # a non-contiguous landing buffer would make reshape(-1) a
            # COPY and the drain would silently fill the temporary
            raise MPIException(
                "bcast_init: the landing buffer must be a writable "
                "C-contiguous ndarray (results land in place)")
    return PersistentCollRequest(
        comm, "bcast",
        lambda: _bind(comm, "bcast", buf=buf, root=root, recvbuf=rb))


def reduce_init(comm, sendbuf, op, root: int = 0) -> PersistentCollRequest:
    """≈ MPI_Reduce_init."""
    return PersistentCollRequest(
        comm, "reduce",
        lambda: _bind(comm, "reduce", buf=sendbuf, op=op, root=root))


def allreduce_init(comm, sendbuf, op) -> PersistentCollRequest:
    """≈ MPI_Allreduce_init."""
    return PersistentCollRequest(
        comm, "allreduce",
        lambda: _bind(comm, "allreduce", buf=sendbuf, op=op))


def allgather_init(comm, sendbuf) -> PersistentCollRequest:
    """≈ MPI_Allgather_init."""
    return PersistentCollRequest(
        comm, "allgather",
        lambda: _bind(comm, "allgather", buf=sendbuf))


def alltoall_init(comm, sendbuf) -> PersistentCollRequest:
    """≈ MPI_Alltoall_init: ``sendbuf`` (re-read at each Start) is the
    row-per-destination dense block, as in the blocking form."""
    return PersistentCollRequest(
        comm, "alltoall",
        lambda: _bind_dense(comm, "alltoall", buf=sendbuf))


def alltoallv_init(comm, sendparts) -> PersistentCollRequest:
    """≈ MPI_Alltoallv_init: one (possibly None) part per destination;
    the bound list is re-indexed at each Start."""
    parts = list(sendparts)
    return PersistentCollRequest(
        comm, "alltoallv",
        lambda: _bind_dense(comm, "alltoallv", buf=parts))


def reduce_scatter_init(comm, sendbuf, op) -> PersistentCollRequest:
    """≈ MPI_Reduce_scatter_init (block-free contiguous split, like the
    one-shot form: rank r lands ``np.array_split`` chunk r)."""
    return PersistentCollRequest(
        comm, "reduce_scatter",
        lambda: _bind_dense(comm, "reduce_scatter", buf=sendbuf, op=op))


def neighbor_alltoall_init(comm, sendparts) -> PersistentCollRequest:
    """≈ MPI_Neighbor_alltoall_init: one block per out-neighbor over
    the comm's cart/graph/dist-graph topology; each wait yields one
    entry per in-neighbor (None on PROC_NULL edges)."""
    parts = list(sendparts)
    return PersistentCollRequest(
        comm, "neighbor_alltoall",
        lambda: _bind_neighbor(comm, "neighbor_alltoall", parts))


def neighbor_alltoallv_init(comm, sendparts) -> PersistentCollRequest:
    """≈ MPI_Neighbor_alltoallv_init (the exchange is already
    shape-polymorphic per edge, as in the blocking v-form)."""
    parts = list(sendparts)
    return PersistentCollRequest(
        comm, "neighbor_alltoallv",
        lambda: _bind_neighbor(comm, "neighbor_alltoallv", parts))
