"""coll — the collectives framework.

≈ ompi/mca/coll: a per-communicator function table filled by priority-ordered
component query (coll.h:426-530, coll_base_comm_select.c:107,270).  Components
may implement any subset of the collective functions; for each function the
highest-priority component providing it wins, so e.g. a future accelerated
component can override just allreduce while ``host`` keeps the rest — the
exact stacking semantics of the reference.

Components here:
- ``self``  — size-1 communicators: every collective is a local no-op/copy
  (≈ coll/self).
- ``host``  — the full algorithm library over host p2p with a tuned-style
  decision layer (≈ coll/base + coll/tuned).

The device path (``coll/xla`` lowering to lax.psum/all_gather/ppermute/
all_to_all) lives on DeviceCommunicator (ompi_tpu.mpi.device_comm) because it
executes inside jit-traced SPMD programs, not against host buffers.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ompi_tpu.core.mca import Component, Framework

if TYPE_CHECKING:
    from ompi_tpu.mpi.comm import Communicator

__all__ = ["coll_framework", "install", "CollModule"]

coll_framework = Framework("coll", "collective operations")

# the function table slots (≈ mca_coll_base_comm_coll_t)
COLL_FUNCTIONS = (
    "barrier", "bcast", "reduce", "allreduce", "gather", "allgather",
    "scatter", "alltoall", "reduce_scatter", "reduce_scatter_block", "scan",
    "exscan", "gatherv", "scatterv", "allgatherv", "alltoallv",
)


class CollModule:
    """The per-communicator collective table. Attributes are bound functions
    chosen per-slot from the winning components."""

    def __init__(self) -> None:
        self.providers: dict[str, str] = {}  # slot → component name (introspection)


def install(comm: "Communicator") -> None:
    """Fill comm.coll by priority query (≈ coll_base_comm_select)."""
    # import registers the components
    from ompi_tpu.mpi.coll import host as _host  # noqa: F401
    from ompi_tpu.mpi.coll import selfcoll as _selfcoll  # noqa: F401

    module = CollModule()
    ranked = coll_framework.select_all(comm=comm)
    for slot in COLL_FUNCTIONS:
        for comp in ranked:
            fn = getattr(comp, f"coll_{slot}", None)
            if fn is not None:
                setattr(module, slot, fn)
                module.providers[slot] = comp.NAME
                break
        else:
            setattr(module, slot, _unimplemented(slot))
    comm.coll = module


def _unimplemented(slot: str):
    def stub(comm, *a, **kw):
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"no coll component provides {slot} for {comm.name}")

    return stub
