"""coll — the collectives framework.

≈ ompi/mca/coll: a per-communicator function table filled by priority-ordered
component query (coll.h:426-530, coll_base_comm_select.c:107,270).  Components
may implement any subset of the collective functions; for each function the
highest-priority component providing it wins, so e.g. an accelerated
component can override just allreduce while ``host`` keeps the rest — the
exact stacking semantics of the reference.

Components here:
- ``self``  — size-1 communicators: every collective is a local no-op/copy
  (≈ coll/self).
- ``host``  — the full algorithm library over host p2p with a tuned-style
  decision layer (≈ coll/base + coll/tuned).
- ``shm``   — single-copy on-node barrier/bcast/reduce/allreduce/allgather
  through a per-communicator shared-memory arena, hierarchical
  (intra-node arena + inter-node host) on mixed-host communicators
  (≈ coll/sm + the HiCCL decomposition).
- ``xla``   — the device path (≈ the coll/cuda slot, inverted): collectives
  on jax arrays lower to lax.psum/all_gather/all_to_all/ppermute over the
  communicator's bound DeviceCommunicator — zero host copies.

Buffer-location dispatch: each table slot is a dispatcher that routes by
``core.buffer.classify()`` — HOST buffers to the best host-capable
component, DEVICE/TRACED buffers to the best device-capable one.  This is
the single choke point the reference never had (its CUDA checks are
sprinkled through convertor/pml/btl/coll); a device buffer reaching a
host-only table raises ``BufferLocationError`` instead of silently staging.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Optional

from ompi_tpu.core.buffer import BufferKind, BufferLocationError, classify
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.mpi import trace as trace_mod

if TYPE_CHECKING:
    from ompi_tpu.mpi.comm import Communicator

__all__ = ["coll_framework", "install", "CollModule"]

coll_framework = Framework("coll", "collective operations")

# the function table slots (≈ mca_coll_base_comm_coll_t)
COLL_FUNCTIONS = (
    "barrier", "bcast", "reduce", "allreduce", "gather", "allgather",
    "scatter", "alltoall", "reduce_scatter", "reduce_scatter_block", "scan",
    "exscan", "gatherv", "scatterv", "allgatherv", "alltoallv",
    "alltoallw",
)

# slots whose first argument is a data buffer (everything but barrier)
_BUFFER_SLOTS = frozenset(COLL_FUNCTIONS) - {"barrier"}


class CollModule:
    """The per-communicator collective table. Attributes are bound
    dispatchers choosing host vs device providers per buffer location."""

    def __init__(self) -> None:
        # slot → component name serving host buffers (introspection)
        self.providers: dict[str, str] = {}
        # slot → component name serving device/traced buffers
        self.device_providers: dict[str, str] = {}


def _handles(comp: Component) -> frozenset:
    return getattr(comp, "HANDLES", frozenset({"host"}))


#: per-rank chaos injector for the @coll=N triggers, resolved once per
#: rank (None = no armed plan / no coll faults — ONE dict hit per
#: dispatch after the first)
_fi_cache: dict[int, object] = {}

#: per-rank BLOCKING dispatch depth (maintained only while an injector
#: with coll faults is armed): distinguishes a composed collective's
#: nested sub-dispatch from a genuine top-level one without being
#: confused by outstanding nonblocking schedules
_depth: dict[int, int] = {}

#: position of the root argument within a dispatcher's ``*args`` (after
#: the buffer) — mirrors Communicator's positional call shapes so the
#: recorder signature catches divergent-root mismatches
_ROOT_ARG = {"bcast": 0, "gather": 0, "scatter": 0, "gatherv": 0,
             "scatterv": 0, "reduce": 1}


def _coll_injector(rank: int):
    from ompi_tpu.testing import faultinject

    inj = faultinject.injector_for(rank) if faultinject.active() else None
    if inj is not None and not inj.coll_faults():
        inj = None
    _fi_cache[rank] = inj
    return inj


def _run_recorded(comm, slot: str, kind: str, sig: int,
                  provider: Optional[str], nbytes: int, fn, fargs, fkw):
    """The ONE choke point: flight-recorder post/done (always-on, the
    hang doctor's evidence), the injected @coll stall/mismatch triggers,
    the per-collective span (timeline) and the dispatch-latency
    histogram labeled provider + log2 size bucket (szb) — the
    distribution the algorithm ladder and the p50/p99 columns read."""
    rank = comm.pml.rank
    inj = (_fi_cache[rank] if rank in _fi_cache
           else _coll_injector(rank))
    act = None
    ordinal = -1
    depth = _depth.get(rank, 0)
    if inj is not None and depth == 0:
        # TOP-LEVEL dispatches only: a composed collective's nested
        # sub-dispatches must neither advance the @coll ordinal nor
        # fire inside infrastructure phases (arena build, hierarchy
        # gates) that no timeout bounds.  The BLOCKING dispatch depth
        # decides it — an outstanding nonblocking schedule on the side
        # must not freeze the ordinal
        act, ordinal = inj.coll_op()
        if act == "mismatch":
            # the seeded collective mismatch: this rank records (and
            # announces up the uplink) a DIVERGENT kind at the same
            # (cid, op_seq) its peers dispatch the real one — the
            # MUST-class application error, reproduced on demand
            kind = "bcast" if slot != "bcast" else "barrier"
            sig = trace_mod.collrec_sig(kind, None, 0)
    seq = trace_mod.coll_post(rank, comm.cid, kind, sig, provider,
                              nbytes)
    if act is not None:
        trace_mod.push_now()     # the divergent/stalled/dying head must
        # be visible to the HNP even though this rank never completes
        # (kill@coll exits inside fire_coll: the victim dies after the
        # recorder post, before the collective body publishes — the
        # deterministic mid-collective death the selfheal-coll rejoin
        # chaos class keys on)
        inj.fire_coll(act, ordinal, seq)
    t0 = (trace_mod.begin()
          if trace_mod.hist_active or trace_mod.active else 0)
    if inj is not None:
        _depth[rank] = depth + 1
    try:
        ret = fn(comm, *fargs, **fkw)
        trace_mod.coll_done(rank, comm.cid, seq, kind)
        return ret
    except BaseException as e:
        trace_mod.coll_err(rank, comm.cid, seq, kind, type(e).__name__)
        raise
    finally:
        if inj is not None:
            _depth[rank] = depth
        # span + histogram land on the raise path too: the one
        # collective that FAILED (arena wait hitting coll_shm_timeout
        # mid-hang) is exactly the sample the postmortem trace needs
        if t0:
            now = time.monotonic_ns()
            if trace_mod.hist_active:
                szb = nbytes.bit_length()
                trace_mod.record_hist(
                    "coll_dispatch_ns", now - t0,
                    labels=f'slot="{slot}",provider="{provider}",'
                           f'szb="{szb}"')
            if trace_mod.active:
                # cid+seq is the cross-rank round key: every rank's
                # span of one collective records the same pair, and
                # the timeline merge chains them into one flow arrow
                # path (the straggler is where the arrow waits)
                trace_mod.complete(
                    "coll", slot, t0, rank=rank, provider=provider,
                    comm=comm.name, cid=comm.cid, size=comm.size,
                    seq=seq)


def _make_dispatch(slot: str, host_fn, host_name: Optional[str],
                   dev_fn, dev_name: Optional[str]):
    def dispatch(comm, buf, *args, **kw):
        if classify(buf) is BufferKind.HOST:
            if host_fn is None:
                raise BufferLocationError(
                    f"{slot}: host buffer but no host-capable coll "
                    f"component selected (directive excludes "
                    f"host/self; device path [{dev_name}] needs jax "
                    f"arrays)")
            fn, provider = host_fn, host_name
        else:
            if dev_fn is None:
                raise BufferLocationError(
                    f"{slot}: device/traced buffer but no device-capable "
                    f"coll component selected (have [{host_name}]; enable "
                    f"coll/xla and comm.bind_device(...) for the device "
                    f"path, or np.asarray() the buffer if host staging is "
                    f"intended)")
            fn, provider = dev_fn, dev_name
        nbytes = int(getattr(buf, "nbytes", 0))
        if root_pos is not None:
            # Communicator passes root positionally (comm.py) — pull it
            # from its slot-specific position so a divergent-root
            # collective signs differently across ranks
            if len(args) > root_pos:
                root = args[root_pos]
            else:
                root = kw.get("root", -1)
            root = root if isinstance(root, int) else -1
        else:
            root = -1
        sig = trace_mod.collrec_sig(
            slot, getattr(buf, "dtype", None), nbytes, root)
        return _run_recorded(comm, slot, slot, sig, provider, nbytes,
                             fn, (buf, *args), kw)

    root_pos = _ROOT_ARG.get(slot)
    dispatch.__name__ = f"coll_{slot}_dispatch"
    return dispatch


def _make_traced_barrier(host_fn, provider):
    """Barrier has no buffer to classify; wrap the provider directly so
    the epoch still shows up on the recorder, the coll timeline and the
    dispatch histogram — a barrier's latency IS the wait for the last
    arriver."""
    sig = trace_mod.collrec_sig("barrier", None, 0)

    def barrier(comm, *args, **kw):
        return _run_recorded(comm, "barrier", "barrier", sig, provider,
                             0, host_fn, args, kw)

    return barrier


def install(comm: "Communicator") -> None:
    """Fill comm.coll by priority query (≈ coll_base_comm_select)."""
    # import registers the components
    from ompi_tpu.mpi.coll import host as _host  # noqa: F401
    from ompi_tpu.mpi.coll import selfcoll as _selfcoll  # noqa: F401
    from ompi_tpu.mpi.coll import shm as _shm  # noqa: F401
    from ompi_tpu.mpi.coll import xla as _xla  # noqa: F401

    module = CollModule()
    ranked = coll_framework.select_all(comm=comm)
    for slot in COLL_FUNCTIONS:
        host_fn = host_name = dev_fn = dev_name = None
        for comp in ranked:
            fn = getattr(comp, f"coll_{slot}", None)
            if fn is None:
                continue
            caps = _handles(comp)
            if host_fn is None and "host" in caps:
                host_fn, host_name = fn, comp.NAME
            if dev_fn is None and ("device" in caps or "traced" in caps):
                dev_fn, dev_name = fn, comp.NAME
        if host_fn is None and dev_fn is None:
            setattr(module, slot, _unimplemented(slot))
            continue
        if slot in _BUFFER_SLOTS:
            setattr(module, slot,
                    _make_dispatch(slot, host_fn, host_name, dev_fn,
                                   dev_name))
        else:  # barrier: no buffer to classify; host provider wins
            setattr(module, slot, _make_traced_barrier(
                host_fn or dev_fn, host_name or dev_name))
        if host_name:
            module.providers[slot] = host_name
        if dev_name:
            module.device_providers[slot] = dev_name
    comm.coll = module


def _unimplemented(slot: str):
    def stub(comm, *a, **kw):
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"no coll component provides {slot} for {comm.name}")

    return stub
