"""coll — the collectives framework.

≈ ompi/mca/coll: a per-communicator function table filled by priority-ordered
component query (coll.h:426-530, coll_base_comm_select.c:107,270).  Components
may implement any subset of the collective functions; for each function the
highest-priority component providing it wins, so e.g. an accelerated
component can override just allreduce while ``host`` keeps the rest — the
exact stacking semantics of the reference.

Components here:
- ``self``  — size-1 communicators: every collective is a local no-op/copy
  (≈ coll/self).
- ``host``  — the full algorithm library over host p2p with a tuned-style
  decision layer (≈ coll/base + coll/tuned).
- ``shm``   — single-copy on-node barrier/bcast/reduce/allreduce/allgather
  through a per-communicator shared-memory arena, hierarchical
  (intra-node arena + inter-node host) on mixed-host communicators
  (≈ coll/sm + the HiCCL decomposition).
- ``xla``   — the device path (≈ the coll/cuda slot, inverted): collectives
  on jax arrays lower to lax.psum/all_gather/all_to_all/ppermute over the
  communicator's bound DeviceCommunicator — zero host copies.

Buffer-location dispatch: each table slot is a dispatcher that routes by
``core.buffer.classify()`` — HOST buffers to the best host-capable
component, DEVICE/TRACED buffers to the best device-capable one.  This is
the single choke point the reference never had (its CUDA checks are
sprinkled through convertor/pml/btl/coll); a device buffer reaching a
host-only table raises ``BufferLocationError`` instead of silently staging.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Optional

from ompi_tpu.core.buffer import BufferKind, BufferLocationError, classify
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.mpi import trace as trace_mod

if TYPE_CHECKING:
    from ompi_tpu.mpi.comm import Communicator

__all__ = ["coll_framework", "install", "CollModule"]

coll_framework = Framework("coll", "collective operations")

# the function table slots (≈ mca_coll_base_comm_coll_t)
COLL_FUNCTIONS = (
    "barrier", "bcast", "reduce", "allreduce", "gather", "allgather",
    "scatter", "alltoall", "reduce_scatter", "reduce_scatter_block", "scan",
    "exscan", "gatherv", "scatterv", "allgatherv", "alltoallv",
    "alltoallw",
)

# slots whose first argument is a data buffer (everything but barrier)
_BUFFER_SLOTS = frozenset(COLL_FUNCTIONS) - {"barrier"}


class CollModule:
    """The per-communicator collective table. Attributes are bound
    dispatchers choosing host vs device providers per buffer location."""

    def __init__(self) -> None:
        # slot → component name serving host buffers (introspection)
        self.providers: dict[str, str] = {}
        # slot → component name serving device/traced buffers
        self.device_providers: dict[str, str] = {}


def _handles(comp: Component) -> frozenset:
    return getattr(comp, "HANDLES", frozenset({"host"}))


def _make_dispatch(slot: str, host_fn, host_name: Optional[str],
                   dev_fn, dev_name: Optional[str]):
    def dispatch(comm, buf, *args, **kw):
        if classify(buf) is BufferKind.HOST:
            if host_fn is None:
                raise BufferLocationError(
                    f"{slot}: host buffer but no host-capable coll "
                    f"component selected (directive excludes "
                    f"host/self; device path [{dev_name}] needs jax "
                    f"arrays)")
            fn, provider = host_fn, host_name
        else:
            if dev_fn is None:
                raise BufferLocationError(
                    f"{slot}: device/traced buffer but no device-capable "
                    f"coll component selected (have [{host_name}]; enable "
                    f"coll/xla and comm.bind_device(...) for the device "
                    f"path, or np.asarray() the buffer if host staging is "
                    f"intended)")
            fn, provider = dev_fn, dev_name
        # the ONE choke point: per-collective span (timeline) and the
        # dispatch-latency histogram labeled provider + log2 size
        # bucket (szb) — the distribution the algorithm ladder and the
        # p50/p99 columns read
        if trace_mod.hist_active or trace_mod.active:
            t0 = trace_mod.begin()
            try:
                return fn(comm, buf, *args, **kw)
            finally:
                now = time.monotonic_ns()
                if trace_mod.hist_active:
                    szb = int(getattr(buf, "nbytes", 0)).bit_length()
                    trace_mod.record_hist(
                        "coll_dispatch_ns", now - t0,
                        labels=f'slot="{slot}",provider="{provider}",'
                               f'szb="{szb}"')
                if trace_mod.active:
                    trace_mod.complete(
                        "coll", slot, t0, rank=comm.pml.rank,
                        provider=provider, comm=comm.name,
                        cid=comm.cid, size=comm.size)
        return fn(comm, buf, *args, **kw)

    dispatch.__name__ = f"coll_{slot}_dispatch"
    return dispatch


def _make_traced_barrier(host_fn, provider):
    """Barrier has no buffer to classify; wrap the provider directly so
    the epoch still shows up on the coll timeline (and in the dispatch
    histogram — a barrier's latency IS the wait for the last arriver)."""
    def barrier(comm, *args, **kw):
        if trace_mod.hist_active or trace_mod.active:
            t0 = trace_mod.begin()
            try:
                return host_fn(comm, *args, **kw)
            finally:
                now = time.monotonic_ns()
                if trace_mod.hist_active:
                    trace_mod.record_hist(
                        "coll_dispatch_ns", now - t0,
                        labels=f'slot="barrier",'
                               f'provider="{provider}",szb="0"')
                if trace_mod.active:
                    trace_mod.complete(
                        "coll", "barrier", t0, rank=comm.pml.rank,
                        comm=comm.name, cid=comm.cid, size=comm.size)
        return host_fn(comm, *args, **kw)

    return barrier


def install(comm: "Communicator") -> None:
    """Fill comm.coll by priority query (≈ coll_base_comm_select)."""
    # import registers the components
    from ompi_tpu.mpi.coll import host as _host  # noqa: F401
    from ompi_tpu.mpi.coll import selfcoll as _selfcoll  # noqa: F401
    from ompi_tpu.mpi.coll import shm as _shm  # noqa: F401
    from ompi_tpu.mpi.coll import xla as _xla  # noqa: F401

    module = CollModule()
    ranked = coll_framework.select_all(comm=comm)
    for slot in COLL_FUNCTIONS:
        host_fn = host_name = dev_fn = dev_name = None
        for comp in ranked:
            fn = getattr(comp, f"coll_{slot}", None)
            if fn is None:
                continue
            caps = _handles(comp)
            if host_fn is None and "host" in caps:
                host_fn, host_name = fn, comp.NAME
            if dev_fn is None and ("device" in caps or "traced" in caps):
                dev_fn, dev_name = fn, comp.NAME
        if host_fn is None and dev_fn is None:
            setattr(module, slot, _unimplemented(slot))
            continue
        if slot in _BUFFER_SLOTS:
            setattr(module, slot,
                    _make_dispatch(slot, host_fn, host_name, dev_fn,
                                   dev_name))
        else:  # barrier: no buffer to classify; host provider wins
            setattr(module, slot, _make_traced_barrier(
                host_fn or dev_fn, host_name or dev_name))
        if host_name:
            module.providers[slot] = host_name
        if dev_name:
            module.device_providers[slot] = dev_name
    comm.coll = module


def _unimplemented(slot: str):
    def stub(comm, *a, **kw):
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"no coll component provides {slot} for {comm.name}")

    return stub
