"""Dynamic collective-selection rules file.

≈ ompi/mca/coll/tuned/coll_tuned_dynamic_file.c — the reference lets admins
override the fixed decision tables with a file of measured crossover points,
keyed by communicator size and message size.  Same idea here with a
line-oriented format (the reference's positional integer format is tied to
its enum numbering; ours names algorithms):

    # collective  comm_size_min  msg_bytes_min  algorithm
    allreduce     0              0              recursive_doubling
    allreduce     0              10240          ring
    allreduce     16             1048576        segmented_ring

For a lookup (collective, comm_size, msg_bytes) the matching rule with the
largest (comm_size_min, msg_bytes_min) wins — i.e. rules refine from generic
to specific exactly like the reference's nested comm-size → msg-size tables.
Returns None when no rule matches (fall through to the fixed decision).
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["RuleSet", "load_rules", "decide",
           "SHM_ALLREDUCE", "SHM_ALLREDUCE_ALGORITHMS"]

#: rules-file collective key selecting the coll/shm arena allreduce
#: fold strategy (coll/shm.decide_allreduce_algo's ladder reads it) —
#: e.g. ``shm_allreduce 0 1048576 segment_parallel``
SHM_ALLREDUCE = "shm_allreduce"
SHM_ALLREDUCE_ALGORITHMS = ("root_fold", "segment_parallel")


class RuleSet:
    def __init__(self, rules: list[tuple[str, int, int, str]],
                 meta: Optional[dict] = None) -> None:
        # rules: (collective, comm_size_min, msg_bytes_min, algorithm)
        # meta: provenance from "#!" lines (platform=…, n_devices=…) —
        # lets a consumer refuse rules measured on a different backend
        self.meta: dict[str, str] = meta or {}
        self._by_coll: dict[str, list[tuple[int, int, str]]] = {}
        for coll, cmin, mmin, alg in rules:
            self._by_coll.setdefault(coll, []).append((cmin, mmin, alg))
        for lst in self._by_coll.values():
            lst.sort()

    def lookup(self, coll: str, comm_size: int,
               msg_bytes: int) -> Optional[str]:
        best: Optional[tuple[int, int, str]] = None
        for cmin, mmin, alg in self._by_coll.get(coll, ()):
            if cmin <= comm_size and mmin <= msg_bytes:
                if best is None or (cmin, mmin) >= best[:2]:
                    best = (cmin, mmin, alg)
        return best[2] if best else None

    def __len__(self) -> int:
        return sum(len(v) for v in self._by_coll.values())


def parse(text: str, source: str = "<string>") -> RuleSet:
    rules = []
    meta: dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if line.startswith("#!"):  # provenance: "#! key=value"
            body = line[2:].strip()
            if "=" in body:
                k, v = body.split("=", 1)
                meta[k.strip()] = v.strip()
            continue
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        fields = line.split()
        if len(fields) != 4:
            from ompi_tpu.mpi.constants import MPIException

            raise MPIException(
                f"{source}:{lineno}: expected "
                f"'collective comm_size_min msg_bytes_min algorithm', "
                f"got {line!r}")
        coll, cmin, mmin, alg = fields
        try:
            rules.append((coll, int(cmin), int(mmin), alg))
        except ValueError as e:
            from ompi_tpu.mpi.constants import MPIException

            raise MPIException(f"{source}:{lineno}: {e}") from e
    return RuleSet(rules, meta)


_cache: dict[str, tuple[float, RuleSet]] = {}


def load_rules(path: str) -> RuleSet:
    """Parse a rules file, cached by mtime."""
    mtime = os.stat(path).st_mtime
    hit = _cache.get(path)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    with open(path, encoding="utf-8") as f:
        rs = parse(f.read(), source=path)
    _cache[path] = (mtime, rs)
    return rs


def decide(coll: str, comm_size: int, msg_bytes: int, forced: str = "",
           path: str = "", valid: Optional[tuple] = None,
           forced_src: str = "forced var",
           load=None) -> tuple[Optional[str], str]:
    """The selection ladder every decision layer repeats, factored
    once: forced config var > rules-file hit > ``(None, "fixed")``
    (the caller applies its fixed default).  ``valid`` is the
    validation universe (None skips validation; an EMPTY tuple means
    nothing is valid, so any forced name raises — user tuning must
    fail loudly, not silently fall through).  ``forced_src`` labels
    the forced rung in traces/errors; ``load`` substitutes the
    caller's RuleSet cache for :func:`load_rules` (HostColl keeps its
    lock-guarded component cache).  Returns
    ``(algorithm | None, source)``."""
    if forced:
        alg: Optional[str] = forced
        src = forced_src
    elif path:
        alg = (load or load_rules)(path).lookup(coll, comm_size,
                                                msg_bytes)
        src = f"rules file {path}"
        if alg is None:
            return None, "fixed"
    else:
        return None, "fixed"
    if valid is not None and alg not in valid:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"unknown {coll} algorithm {alg!r} (from {src}); "
            f"valid: {', '.join(valid)}")
    return alg, src
