"""The host collective algorithm library.

≈ ompi/mca/coll/base/coll_base_*.c — the same algorithm inventory (SURVEY.md
§2.4 table), reimplemented over this framework's p2p with numpy buffers:

- allreduce: recursive doubling (coll_base_allreduce.c:128), ring (:339),
  linear fallback (:877)
- bcast: binomial tree (coll_base_bcast.c:313), linear (:608)
- reduce: binomial (rank-ordered fold, valid for non-commutative), linear
- allgather: recursive doubling (:256), bruck (:85), ring (:364), linear
- alltoall: pairwise (:132), linear
- reduce_scatter: ring (:455), reduce+scatter fallback (:46)
- gather/scatter: linear; barrier: dissemination (Bruck) exchange
- scan: linear chain

All functions are collective over `comm` and exchange equal-shaped arrays
(MPI's equal-count contract); variable-count (v-) versions take per-rank
counts along axis 0.

Array convention: pythonic — input array in, result array out (the reference
mutates out-buffers; on TPU-first design immutability matches jax).  Rank
ordering for non-commutative ops follows MPI: the fold is always equivalent
to op(x_0, op(x_1, ... op(x_{p-2}, x_{p-1}))).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.mpi.op import Op
from ompi_tpu.mpi.request import wait_all

# reserved collective tags (negative space via comm._coll_isend)
TAG_BARRIER = 1
TAG_BCAST = 2
TAG_REDUCE = 3
TAG_ALLREDUCE = 4
TAG_GATHER = 5
TAG_ALLGATHER = 6
TAG_SCATTER = 7
TAG_ALLTOALL = 8
TAG_REDUCE_SCATTER = 9
TAG_SCAN = 10
TAG_GATHERV = 11
TAG_SCATTERV = 12
TAG_ALLGATHERV = 13
TAG_ALLTOALLV = 14
TAG_EXSCAN = 15
TAG_ALLTOALLW = 16


def _fold(op: Op, lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Reduce two blocks where `lo` covers lower ranks than `hi`."""
    return np.asarray(op.host(lo, hi))


# ---------------------------------------------------------------------------
# barrier — dissemination exchange (≈ coll_base_barrier.c bruck)

def barrier_dissemination(comm) -> None:
    size, rank = comm.size, comm.rank
    if size == 1:
        return
    token = np.zeros(0, dtype=np.uint8)
    step = 1
    while step < size:
        to = (rank + step) % size
        frm = (rank - step) % size
        sreq = comm._coll_isend(token, to, TAG_BARRIER)
        rreq = comm._coll_irecv(None, frm, TAG_BARRIER,
                                datatype=None, count=None)
        wait_all([sreq, rreq])
        step <<= 1


# ---------------------------------------------------------------------------
# bcast

def bcast_binomial(comm, buf: Optional[np.ndarray], root: int) -> np.ndarray:
    """Binomial tree broadcast (coll_base_bcast.c:313)."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return np.asarray(buf)
    vrank = (rank - root) % size
    # my receive level = lowest set bit of vrank; parent is computable, so
    # receive from it specifically (ANY_SOURCE would race with the next
    # bcast's parent on the same tag)
    recv_mask = 1
    while recv_mask < size and not (vrank & recv_mask):
        recv_mask <<= 1
    if vrank != 0:
        parent = ((vrank & ~recv_mask) + root) % size
        buf = comm._coll_irecv(None, parent, TAG_BCAST).wait()
    arr = np.asarray(buf)
    mask = 1
    while mask < size:
        mask <<= 1
    mask >>= 1
    send_mask = recv_mask >> 1 if vrank != 0 else mask
    reqs = []
    while send_mask >= 1:
        vchild = vrank | send_mask
        if vchild < size and vchild != vrank:
            child = (vchild + root) % size
            reqs.append(comm._coll_isend(arr, child, TAG_BCAST))
        send_mask >>= 1
    wait_all(reqs)
    return arr


def bcast_linear(comm, buf: Optional[np.ndarray], root: int) -> np.ndarray:
    size, rank = comm.size, comm.rank
    if rank == root:
        arr = np.asarray(buf)
        wait_all([comm._coll_isend(arr, r, TAG_BCAST)
                  for r in range(size) if r != rank])
        return arr
    return comm._coll_irecv(None, root, TAG_BCAST).wait()


# ---------------------------------------------------------------------------
# reduce

def reduce_binomial(comm, sendbuf, op: Op, root: int) -> Optional[np.ndarray]:
    """Binomial tree reduce with rank-ordered folding: at every step the
    receiver holds ranks [vrank, vrank+mask) and receives [vrank+mask, ...),
    so op(acc, recv) is always in rank order — valid for non-commutative ops
    when root == 0; other roots rotate, so non-commutative ops reduce at
    vroot 0 and forward (the reference's approach in coll_base_reduce.c)."""
    size, rank = comm.size, comm.rank
    acc = np.asarray(sendbuf)
    if size == 1:
        return acc
    eff_root = root if op.commutative else 0
    vrank = (rank - eff_root) % size
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + eff_root) % size
            comm._coll_isend(acc, parent, TAG_REDUCE).wait()
            acc = None
            break
        else:
            vchild = vrank | mask
            if vchild < size:
                child = (vchild + eff_root) % size
                recv = comm._coll_irecv(None, child, TAG_REDUCE).wait()
                recv = recv.reshape(acc.shape).astype(acc.dtype, copy=False)
                acc = _fold(op, acc, recv)
        mask <<= 1
    if eff_root != root:  # forward the result for non-commutative odd roots
        if rank == eff_root:
            comm._coll_isend(acc, root, TAG_REDUCE).wait()
            acc = None
        elif rank == root:
            shape = np.asarray(sendbuf).shape
            acc = comm._coll_irecv(None, eff_root, TAG_REDUCE).wait()
            acc = acc.reshape(shape)
    return acc if rank == root else None


# ---------------------------------------------------------------------------
# allreduce

def allreduce_recursive_doubling(comm, sendbuf, op: Op) -> np.ndarray:
    """coll_base_allreduce.c:128 — lg(p) rounds; non-power-of-2 folds
    *adjacent pairs* (rank 2r into 2r+1) first so every surviving rank holds
    a rank-contiguous block and the doubling folds stay rank-ordered —
    valid for non-commutative ops."""
    size, rank = comm.size, comm.rank
    acc = np.asarray(sendbuf)
    if size == 1:
        return acc
    shape, dtype = acc.shape, acc.dtype

    pof2 = 1
    while pof2 * 2 <= size:
        pof2 *= 2
    rem = size - pof2
    # pre-fold: among the first 2*rem ranks, even ranks fold into their odd
    # neighbor (keeps combined data rank-contiguous: d_{2r} ∘ d_{2r+1})
    if rank < 2 * rem:
        if rank % 2 == 0:
            comm._coll_isend(acc, rank + 1, TAG_ALLREDUCE).wait()
            newrank = -1
        else:
            recv = comm._coll_irecv(None, rank - 1, TAG_ALLREDUCE).wait()
            acc = _fold(op, recv.reshape(shape).astype(dtype, copy=False),
                        acc)
            newrank = rank // 2
    else:
        newrank = rank - rem
    if newrank >= 0:
        # newrank order == rank order of the contiguous blocks, so
        # partner<newrank decides the fold direction correctly
        def real_rank(nr: int) -> int:
            return 2 * nr + 1 if nr < rem else nr + rem

        mask = 1
        while mask < pof2:
            partner = real_rank(newrank ^ mask)
            sreq = comm._coll_isend(acc, partner, TAG_ALLREDUCE)
            recv = comm._coll_irecv(None, partner, TAG_ALLREDUCE).wait()
            sreq.wait()
            recv = recv.reshape(shape).astype(dtype, copy=False)
            acc = (_fold(op, recv, acc) if (newrank ^ mask) < newrank
                   else _fold(op, acc, recv))
            mask <<= 1
    # return results to the folded-out even ranks
    if rank < 2 * rem:
        if rank % 2:
            comm._coll_isend(acc, rank - 1, TAG_ALLREDUCE).wait()
        else:
            acc = comm._coll_irecv(None, rank + 1, TAG_ALLREDUCE).wait()
            acc = acc.reshape(shape).astype(dtype, copy=False)
    return acc


def allreduce_ring(comm, sendbuf, op: Op) -> np.ndarray:
    """coll_base_allreduce.c:339 — reduce-scatter ring + allgather ring.
    2(p-1) steps, each moving size/p; bandwidth-optimal. Commutative only."""
    size, rank = comm.size, comm.rank
    arr = np.asarray(sendbuf)
    if size == 1:
        return arr
    flat = arr.reshape(-1)
    chunks = np.array_split(flat, size)
    chunks = [c.copy() for c in chunks]
    right = (rank + 1) % size
    left = (rank - 1) % size
    # reduce-scatter: after p-1 steps, chunk (rank+1)%size is fully reduced
    send_idx = rank
    for _ in range(size - 1):
        sreq = comm._coll_isend(chunks[send_idx], right, TAG_ALLREDUCE)
        recv_idx = (send_idx - 1) % size
        recv = comm._coll_irecv(None, left, TAG_ALLREDUCE).wait()
        sreq.wait()
        chunks[recv_idx] = np.asarray(
            op.host(chunks[recv_idx],
                    recv.astype(chunks[recv_idx].dtype, copy=False)))
        send_idx = recv_idx
    # allgather ring: circulate the reduced chunks
    send_idx = (rank + 1) % size
    for _ in range(size - 1):
        sreq = comm._coll_isend(chunks[send_idx], right, TAG_ALLGATHER)
        recv_idx = (send_idx - 1) % size
        recv = comm._coll_irecv(None, left, TAG_ALLGATHER).wait()
        sreq.wait()
        chunks[recv_idx] = recv.astype(chunks[recv_idx].dtype, copy=False)
        send_idx = recv_idx
    return np.concatenate(chunks).reshape(arr.shape)


def allreduce_linear(comm, sendbuf, op: Op) -> np.ndarray:
    """reduce to 0 + bcast (coll_base_allreduce.c:877 nonoverlapping)."""
    out = reduce_binomial(comm, sendbuf, op, 0)
    return bcast_binomial(comm, out, 0)


# ---------------------------------------------------------------------------
# allgather

def allgather_bruck(comm, sendbuf) -> np.ndarray:
    """coll_base_allgather.c:85 — lg(p) rounds, any p; blocks end rotated."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if size == 1:
        return mine[None]
    blocks: list[Optional[np.ndarray]] = [None] * size
    blocks[0] = mine
    step = 1
    filled = 1
    while step < size:
        cnt = min(step, size - filled)
        to = (rank - step) % size
        frm = (rank + step) % size
        payload = np.stack(blocks[0:cnt])
        sreq = comm._coll_isend(payload, to, TAG_ALLGATHER)
        recv = comm._coll_irecv(None, frm, TAG_ALLGATHER).wait()
        sreq.wait()
        recv = recv.reshape((cnt,) + mine.shape).astype(mine.dtype, copy=False)
        for i in range(cnt):
            blocks[filled + i] = recv[i]
        filled += cnt
        step <<= 1
    # local rotation: blocks[i] holds rank (rank+i)%size's data
    out = [None] * size
    for i in range(size):
        out[(rank + i) % size] = blocks[i]
    return np.stack(out)  # type: ignore[arg-type]


def allgather_ring(comm, sendbuf) -> np.ndarray:
    """coll_base_allgather.c:364 — p-1 neighbor exchanges."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if size == 1:
        return mine[None]
    out: list[Optional[np.ndarray]] = [None] * size
    out[rank] = mine
    right = (rank + 1) % size
    left = (rank - 1) % size
    send_idx = rank
    for _ in range(size - 1):
        sreq = comm._coll_isend(out[send_idx], right, TAG_ALLGATHER)
        recv_idx = (send_idx - 1) % size
        recv = comm._coll_irecv(None, left, TAG_ALLGATHER).wait()
        sreq.wait()
        out[recv_idx] = recv.reshape(mine.shape).astype(mine.dtype, copy=False)
        send_idx = recv_idx
    return np.stack(out)  # type: ignore[arg-type]


# ---------------------------------------------------------------------------
# gather / scatter (linear, ≈ coll_base_gather/scatter.c basic linear)

def gather_linear(comm, sendbuf, root: int) -> Optional[np.ndarray]:
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if rank == root:
        parts: list[Optional[np.ndarray]] = [None] * size
        parts[rank] = mine
        reqs = {r: comm._coll_irecv(None, r, TAG_GATHER)
                for r in range(size) if r != root}
        for r, req in reqs.items():
            parts[r] = req.wait().reshape(mine.shape).astype(
                mine.dtype, copy=False)
        return np.stack(parts)  # type: ignore[arg-type]
    comm._coll_isend(mine, root, TAG_GATHER).wait()
    return None


def scatter_linear(comm, sendbuf, root: int) -> np.ndarray:
    size, rank = comm.size, comm.rank
    if rank == root:
        arr = np.asarray(sendbuf)
        if arr.shape[0] % size:
            from ompi_tpu.mpi.constants import MPIException

            raise MPIException(
                f"scatter: axis 0 ({arr.shape[0]}) not divisible by {size}")
        parts = np.split(arr, size, axis=0)
        reqs = [comm._coll_isend(parts[r], r, TAG_SCATTER)
                for r in range(size) if r != root]
        wait_all(reqs)
        return parts[rank]
    return comm._coll_irecv(None, root, TAG_SCATTER).wait()


# ---------------------------------------------------------------------------
# alltoall — pairwise exchange (coll_base_alltoall.c:132)

def alltoall_pairwise(comm, sendbuf) -> np.ndarray:
    size, rank = comm.size, comm.rank
    arr = np.asarray(sendbuf)
    if arr.shape[0] % size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"alltoall: axis 0 ({arr.shape[0]}) not divisible by {size}")
    parts = np.split(arr, size, axis=0)
    out: list[Optional[np.ndarray]] = [None] * size
    out[rank] = parts[rank]
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step) % size
        sreq = comm._coll_isend(parts[to], to, TAG_ALLTOALL)
        recv = comm._coll_irecv(None, frm, TAG_ALLTOALL).wait()
        sreq.wait()
        out[frm] = recv.reshape(parts[rank].shape).astype(arr.dtype, copy=False)
    return np.concatenate(out)  # type: ignore[arg-type]


# ---------------------------------------------------------------------------
# reduce_scatter — ring (coll_base_reduce_scatter.c:455)

def reduce_scatter_ring(comm, sendbuf, op: Op) -> np.ndarray:
    """Each rank ends with its block of the fully-reduced array.
    Commutative only (ring accumulation order)."""
    size, rank = comm.size, comm.rank
    arr = np.asarray(sendbuf)
    if size == 1:
        return arr
    flat = arr.reshape(-1)
    chunks = [c.copy() for c in np.array_split(flat, size)]
    right = (rank + 1) % size
    left = (rank - 1) % size
    # after p-1 steps the fully-reduced chunk is (start_idx+1) mod p, so
    # starting at rank-1 leaves rank owning its own chunk
    send_idx = (rank - 1) % size
    for _ in range(size - 1):
        sreq = comm._coll_isend(chunks[send_idx], right, TAG_REDUCE_SCATTER)
        recv_idx = (send_idx - 1) % size
        recv = comm._coll_irecv(None, left, TAG_REDUCE_SCATTER).wait()
        sreq.wait()
        chunks[recv_idx] = np.asarray(
            op.host(chunks[recv_idx],
                    recv.astype(chunks[recv_idx].dtype, copy=False)))
        send_idx = recv_idx
    return chunks[rank]


def reduce_scatter_basic(comm, sendbuf, op: Op) -> np.ndarray:
    """reduce + scatter fallback (valid for non-commutative ops)."""
    size = comm.size
    reduced = reduce_binomial(comm, sendbuf, op, 0)
    if comm.rank == 0:
        flat = reduced.reshape(-1)
        # pad-free equal split contract: use array_split boundaries
        parts = np.array_split(flat, size)
        for r in range(1, size):
            comm._coll_isend(parts[r], r, TAG_REDUCE_SCATTER).wait()
        return parts[0]
    return comm._coll_irecv(None, 0, TAG_REDUCE_SCATTER).wait()


# ---------------------------------------------------------------------------
# scan / exscan — linear chain

def scan_linear(comm, sendbuf, op: Op) -> np.ndarray:
    """Inclusive prefix reduction: result_r = op(x_0, ..., x_r)."""
    rank, size = comm.rank, comm.size
    acc = np.asarray(sendbuf)
    if rank > 0:
        prev = comm._coll_irecv(None, rank - 1, TAG_SCAN).wait()
        acc = _fold(op, prev.reshape(acc.shape).astype(acc.dtype, copy=False),
                    acc)
    if rank < size - 1:
        comm._coll_isend(acc, rank + 1, TAG_SCAN).wait()
    return acc


def exscan_linear(comm, sendbuf, op: Op) -> Optional[np.ndarray]:
    """Exclusive prefix reduction: result_r = op(x_0, ..., x_{r-1}); rank 0's
    result is undefined per MPI (returned as None)."""
    rank, size = comm.rank, comm.size
    mine = np.asarray(sendbuf)
    prev: Optional[np.ndarray] = None
    if rank > 0:
        prev = comm._coll_irecv(None, rank - 1, TAG_EXSCAN).wait()
        prev = prev.reshape(mine.shape).astype(mine.dtype, copy=False)
    if rank < size - 1:
        fwd = mine if prev is None else _fold(op, prev, mine)
        comm._coll_isend(fwd, rank + 1, TAG_EXSCAN).wait()
    return prev


# ---------------------------------------------------------------------------
# variable-count (v-) collectives: per-rank blocks of differing axis-0 length
# (same trailing shape/dtype).  Pythonic contract: lists of arrays in/out
# preserve the block boundaries that MPI expresses as count/displacement
# vectors.  Linear exchange, like the basic components in the reference.

def gatherv_linear(comm, sendbuf, root: int) -> Optional[list]:
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if rank == root:
        parts: list[Optional[np.ndarray]] = [None] * size
        parts[rank] = mine
        reqs = {r: comm._coll_irecv(None, r, TAG_GATHERV)
                for r in range(size) if r != root}
        for r, req in reqs.items():
            parts[r] = req.wait()
        return parts  # type: ignore[return-value]
    comm._coll_isend(mine, root, TAG_GATHERV).wait()
    return None


def scatterv_linear(comm, sendparts, root: int) -> np.ndarray:
    size, rank = comm.size, comm.rank
    if rank == root:
        if len(sendparts) != size:
            from ompi_tpu.mpi.constants import MPIException

            raise MPIException(
                f"scatterv: {len(sendparts)} blocks for {size} ranks")
        wait_all([comm._coll_isend(np.asarray(sendparts[r]), r, TAG_SCATTERV)
                  for r in range(size) if r != root])
        return np.asarray(sendparts[rank])
    return comm._coll_irecv(None, root, TAG_SCATTERV).wait()


def allgatherv_ring(comm, sendbuf) -> list:
    """Each rank's block circulates p-1 hops (coll_base_allgatherv ring)."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    out: list[Optional[np.ndarray]] = [None] * size
    out[rank] = mine
    if size == 1:
        return out  # type: ignore[return-value]
    right = (rank + 1) % size
    left = (rank - 1) % size
    send_idx = rank
    for _ in range(size - 1):
        sreq = comm._coll_isend(out[send_idx], right, TAG_ALLGATHERV)
        recv_idx = (send_idx - 1) % size
        recv = comm._coll_irecv(None, left, TAG_ALLGATHERV).wait()
        sreq.wait()
        out[recv_idx] = recv
        send_idx = recv_idx
    return out  # type: ignore[return-value]


def alltoallv_pairwise(comm, sendparts) -> list:
    """sendparts[i] goes to rank i (None ⇒ an empty block — MPI's
    zero-count entry); returns out[i] = block from rank i."""
    size, rank = comm.size, comm.rank
    if len(sendparts) != size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"alltoallv: {len(sendparts)} blocks for {size} ranks")
    # normalize up front (a None part used to reach np.asarray and ship
    # an object scalar): every peer still pairs its send/recv, a
    # zero-count block just travels as an empty frame
    norm = [np.empty(0, np.uint8) if p is None else np.asarray(p)
            for p in sendparts]
    out: list[Optional[np.ndarray]] = [None] * size
    out[rank] = norm[rank]
    if size == 1:
        return out  # type: ignore[return-value]
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step) % size
        sreq = comm._coll_isend(norm[to], to, TAG_ALLTOALLV)
        out[frm] = comm._coll_irecv(None, frm, TAG_ALLTOALLV).wait()
        sreq.wait()
    return out  # type: ignore[return-value]


def pack_spec(spec) -> np.ndarray:
    """(buf, datatype, count) triple → packed uint8 array (None → empty).
    The shared half of the Alltoallw-family wire format."""
    if spec is None:
        return np.empty(0, np.uint8)
    buf, dt, count = spec
    return np.frombuffer(dt.pack(np.asarray(buf), count), np.uint8)


def unpack_spec(spec, data) -> None:
    """Packed bytes → the spec's buffer via its datatype (None → no-op)."""
    if spec is None:
        return
    buf, dt, count = spec
    dt.unpack(np.asarray(data, np.uint8).tobytes(), buf, count)


def alltoallw_pairwise(comm, sendspecs, recvspecs) -> None:
    """≈ MPI_Alltoallw (the fully general alltoall: per-peer datatype +
    count on BOTH sides — ompi/mpi/c/alltoallw.c).  ``sendspecs[i]`` /
    ``recvspecs[i]`` are ``(buf, datatype, count)`` triples (or None for
    an empty exchange with that peer); each block is packed with its send
    datatype and unpacked into the receiver's buffer with the receiver's
    datatype, exercising the full convertor path per pair."""
    size, rank = comm.size, comm.rank
    if len(sendspecs) != size or len(recvspecs) != size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"alltoallw: {len(sendspecs)}/{len(recvspecs)} specs for "
            f"{size} ranks")
    unpack_spec(recvspecs[rank], pack_spec(sendspecs[rank]))
    if size == 1:
        return
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step) % size
        sreq = comm._coll_isend(pack_spec(sendspecs[to]), to, TAG_ALLTOALLW)
        got = comm._coll_irecv(None, frm, TAG_ALLTOALLW).wait()
        sreq.wait()
        unpack_spec(recvspecs[frm], got)


# ---------------------------------------------------------------------------
# extra algorithms from the reference inventory

def alltoall_bruck(comm, sendbuf) -> np.ndarray:
    """coll_base_alltoall.c:191 — lg(p) rounds moving half the blocks each;
    latency-optimal for small messages."""
    size, rank = comm.size, comm.rank
    arr = np.asarray(sendbuf)
    if arr.shape[0] % size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"alltoall: axis 0 ({arr.shape[0]}) not divisible by {size}")
    if size == 1:
        return arr
    parts = np.split(arr, size, axis=0)
    # phase 1: local rotation so blocks[i] targets (rank+i)%size
    blocks = [parts[(rank + i) % size] for i in range(size)]
    # phase 2: lg(p) exchange rounds — round k moves blocks whose index has
    # bit k set, to rank+2^k (they travel toward their target in binary)
    pof = 1
    while pof < size:
        idxs = [i for i in range(size) if i & pof]
        to = (rank + pof) % size
        frm = (rank - pof) % size
        payload = np.concatenate([blocks[i] for i in idxs], axis=0)
        sreq = comm._coll_isend(payload, to, TAG_ALLTOALL)
        recv = comm._coll_irecv(None, frm, TAG_ALLTOALL).wait()
        sreq.wait()
        recv = recv.reshape((len(idxs),) + blocks[0].shape).astype(
            arr.dtype, copy=False)
        for j, i in enumerate(idxs):
            blocks[i] = recv[j]
        pof <<= 1
    # phase 3: inverse rotation — block i holds data *from* (rank-i)%size
    out: list[Optional[np.ndarray]] = [None] * size
    for i in range(size):
        out[(rank - i) % size] = blocks[i]
    return np.concatenate(out, axis=0)  # type: ignore[arg-type]


def allreduce_segmented_ring(comm, sendbuf, op: Op,
                             segsize: int = 1 << 20) -> np.ndarray:
    """coll_base_allreduce.c:615 — the ring with each step's payload split
    into ~segsize-byte segments sent as independent messages, so folding an
    arrived segment overlaps the transfer of the next (the same
    double-buffered overlap pattern as ring attention).  Latency is the same
    2(p-1) steps as the plain ring.  Commutative only."""
    size, rank = comm.size, comm.rank
    arr = np.asarray(sendbuf)
    if size == 1:
        return arr
    flat = arr.reshape(-1)
    seg_elems = max(1, segsize // max(1, arr.dtype.itemsize))
    nseg = -(-flat.size // (seg_elems * size)) if flat.size else 1
    if nseg <= 1:
        return allreduce_ring(comm, sendbuf, op)
    # segs[s] = per-rank chunk list for segment s; per-pair ordering makes
    # the s-th posted irecv match the s-th segment sent each step
    bounds = [min(s * seg_elems * size, flat.size) for s in range(nseg + 1)]
    segs = [[c.copy() for c in np.array_split(flat[bounds[s]:bounds[s + 1]],
                                              size)]
            for s in range(nseg)]
    right = (rank + 1) % size
    left = (rank - 1) % size

    def ring_phase(tag, fold):
        nonlocal segs
        send_idx = rank if fold else (rank + 1) % size
        for _ in range(size - 1):
            recv_idx = (send_idx - 1) % size
            sreqs = [comm._coll_isend(segs[s][send_idx], right, tag)
                     for s in range(nseg)]
            rreqs = [comm._coll_irecv(None, left, tag) for _ in range(nseg)]
            for s in range(nseg):  # fold segment s while s+1 is in flight
                recv = rreqs[s].wait().reshape(-1)
                cur = segs[s][recv_idx]
                recv = recv.astype(cur.dtype, copy=False)
                segs[s][recv_idx] = (np.asarray(op.host(cur, recv)) if fold
                                     else recv)
            wait_all(sreqs)
            send_idx = recv_idx

    ring_phase(TAG_ALLREDUCE, fold=True)    # reduce-scatter phase
    ring_phase(TAG_ALLGATHER, fold=False)   # allgather phase
    out = np.concatenate([c for s in range(nseg) for c in segs[s]])
    return out.reshape(arr.shape)


def bcast_pipeline(comm, buf: Optional[np.ndarray], root: int,
                   segsize: int = 128 * 1024) -> np.ndarray:
    """coll_base_bcast.c:257 — chain pipeline: ranks form a chain rooted at
    root; the message moves in segments so all links stream concurrently."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return np.asarray(buf)
    vrank = (rank - root) % size
    prev = ((vrank - 1) + root) % size
    nxt = ((vrank + 1) + root) % size
    last = vrank == size - 1
    if vrank == 0:
        arr = np.asarray(buf)
        flat = arr.reshape(-1)
        seg_elems = max(1, segsize // max(1, arr.dtype.itemsize))
        nseg = max(1, -(-flat.size // seg_elems))
        # ship a tiny header so receivers know segmentation + final shape
        hdr = np.array([seg_elems] + list(arr.shape), dtype=np.int64)
        comm._coll_isend(hdr, nxt, TAG_BCAST).wait()
        reqs = [comm._coll_isend(flat[i * seg_elems:(i + 1) * seg_elems],
                                 nxt, TAG_BCAST) for i in range(nseg)]
        wait_all(reqs)
        return arr
    hdr = comm._coll_irecv(None, prev, TAG_BCAST).wait()
    seg_elems = int(hdr[0])
    shape = tuple(int(x) for x in hdr[1:])
    total = int(np.prod(shape)) if shape else 1
    nseg = max(1, -(-total // seg_elems))
    if not last:
        comm._coll_isend(hdr, nxt, TAG_BCAST).wait()
    segs = []
    fwd = []
    for _ in range(nseg):
        seg = comm._coll_irecv(None, prev, TAG_BCAST).wait()
        segs.append(seg)
        if not last:
            fwd.append(comm._coll_isend(seg, nxt, TAG_BCAST))
    wait_all(fwd)
    flat = np.concatenate([s.reshape(-1) for s in segs])
    return flat.reshape(shape)
