"""coll/host — the tuned host collective component.

≈ ompi/mca/coll/tuned: wraps the base algorithm library with a size×commsize
decision layer whose crossover points mirror coll_tuned_decision_fixed.c:
44-87 (allreduce: recursive doubling under the small-message threshold, ring
for large commutative payloads), overridable per-collective via config vars
(the reference's coll_tuned_*_algorithm MCA params / dynamic rules file).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component
from ompi_tpu.mpi.coll import base, coll_framework
from ompi_tpu.mpi.op import Op

__all__ = ["HostColl"]


def _nbytes(buf) -> int:
    return np.asarray(buf).nbytes


@coll_framework.component
class HostColl(Component):
    NAME = "host"
    PRIORITY = 40

    def register_params(self) -> None:
        register_var("coll", "host_allreduce_small", VarType.SIZE, 10 * 1024,
                     "allreduce: below this use recursive doubling "
                     "(tuned's 10KB crossover)")
        register_var("coll", "host_allgather_small", VarType.SIZE, 64 * 1024,
                     "allgather: below this use bruck, above ring")
        for name in ("allreduce", "allgather", "bcast", "reduce_scatter"):
            register_var("coll", f"host_{name}_algorithm", VarType.STRING, "",
                         f"force a {name} algorithm (empty = decide by size)")

    def query(self, comm=None, **ctx) -> Optional[int]:
        if comm is not None and comm.size == 1:
            return None  # coll/self owns size-1
        return self.PRIORITY

    # -- table slots ------------------------------------------------------

    def coll_barrier(self, comm) -> None:
        base.barrier_dissemination(comm)

    def coll_bcast(self, comm, buf, root: int):
        forced = var_registry.get("coll_host_bcast_algorithm")
        if forced == "linear":
            return base.bcast_linear(comm, buf, root)
        return base.bcast_binomial(comm, buf, root)

    def coll_reduce(self, comm, sendbuf, op: Op, root: int):
        return base.reduce_binomial(comm, sendbuf, op, root)

    def coll_allreduce(self, comm, sendbuf, op: Op):
        forced = var_registry.get("coll_host_allreduce_algorithm")
        if forced:
            return {
                "recursive_doubling": base.allreduce_recursive_doubling,
                "ring": base.allreduce_ring,
                "linear": base.allreduce_linear,
            }[forced](comm, sendbuf, op)
        # tuned decision (coll_tuned_decision_fixed.c:65-87)
        if (_nbytes(sendbuf) < var_registry.get("coll_host_allreduce_small")
                or not op.commutative):
            return base.allreduce_recursive_doubling(comm, sendbuf, op)
        return base.allreduce_ring(comm, sendbuf, op)

    def coll_gather(self, comm, sendbuf, root: int):
        return base.gather_linear(comm, sendbuf, root)

    def coll_allgather(self, comm, sendbuf):
        forced = var_registry.get("coll_host_allgather_algorithm")
        if forced:
            return {"bruck": base.allgather_bruck,
                    "ring": base.allgather_ring}[forced](comm, sendbuf)
        if _nbytes(sendbuf) < var_registry.get("coll_host_allgather_small"):
            return base.allgather_bruck(comm, sendbuf)
        return base.allgather_ring(comm, sendbuf)

    def coll_scatter(self, comm, sendbuf, root: int):
        return base.scatter_linear(comm, sendbuf, root)

    def coll_alltoall(self, comm, sendbuf):
        return base.alltoall_pairwise(comm, sendbuf)

    def coll_reduce_scatter(self, comm, sendbuf, op: Op):
        forced = var_registry.get("coll_host_reduce_scatter_algorithm")
        if forced == "basic" or not op.commutative:
            return base.reduce_scatter_basic(comm, sendbuf, op)
        return base.reduce_scatter_ring(comm, sendbuf, op)

    def coll_scan(self, comm, sendbuf, op: Op):
        return base.scan_linear(comm, sendbuf, op)
