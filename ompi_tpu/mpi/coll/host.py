"""coll/host — the tuned host collective component.

≈ ompi/mca/coll/tuned: wraps the base algorithm library with a size×commsize
decision layer whose crossover points mirror coll_tuned_decision_fixed.c:
44-87 (allreduce: recursive doubling under the small-message threshold, ring
for large commutative payloads, segmented ring with 1MB segments for very
large ones), overridable per-collective via config vars (the reference's
coll_tuned_*_algorithm MCA params) or a dynamic rules file
(coll_tuned_dynamic_file.c → ompi_tpu.mpi.coll.rules).
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.coll import base, coll_framework, rules
from ompi_tpu.mpi.op import Op

__all__ = ["HostColl"]


def _nbytes(buf) -> int:
    return np.asarray(buf).nbytes


def _timed(coll: str, algo: str, fn, *args, **kw):
    """Run one decided algorithm body, recording its latency into the
    per-(collective, algorithm) histogram — the measured per-rung
    behavior the decision ladder (and an MPI-Advance-style offline
    retune) keys on."""
    if not trace_mod.hist_active:
        return fn(*args, **kw)
    t0 = time.monotonic_ns()
    try:
        return fn(*args, **kw)
    finally:
        trace_mod.record_hist(
            "coll_host_algo_ns", time.monotonic_ns() - t0,
            labels=f'coll="{coll}",algo="{algo}"')


class HostCollBase(Component):
    """Decision plumbing shared by host-collective components."""

    ALGORITHMS: dict[str, tuple[str, ...]] = {}

    def _load_rules(self, path: str) -> rules.RuleSet:
        """The dynamic-rules RuleSet, parsed once per (path, mtime):
        repeated collectives pay one stat + dict hit, never a re-parse
        (``_decide`` runs on EVERY collective invocation when
        ``coll_host_dynamic_rules`` is set).  The hit path is lock-free;
        a miss takes a lock so concurrent in-process ranks touching a
        fresh file parse it exactly once."""
        cache = self.__dict__.setdefault("_rules_cache", {})
        mtime = os.stat(path).st_mtime
        hit = cache.get(path)
        if hit is not None and hit[0] == mtime:
            return hit[1]
        import threading

        lock = self.__dict__.setdefault("_rules_lock", threading.Lock())
        with lock:
            hit = cache.get(path)
            if hit is None or hit[0] != mtime:
                cache[path] = (mtime, rules.load_rules(path))
            return cache[path][1]

    def _decide(self, coll: str, comm, nbytes: int) -> Optional[str]:
        """forced config var > dynamic rules file > None (fixed
        decision) — the shared :func:`rules.decide` ladder, fed by the
        component's lock-guarded RuleSet cache."""
        alg, src = rules.decide(
            coll, comm.size, nbytes,
            forced=var_registry.get(f"coll_host_{coll}_algorithm") or "",
            path=var_registry.get("coll_host_dynamic_rules") or "",
            valid=self.ALGORITHMS.get(coll, ()),
            forced_src=f"config var coll_host_{coll}_algorithm",
            load=self._load_rules)
        self._trace_decision(coll, comm, nbytes, alg, src)
        return alg

    @staticmethod
    def _trace_decision(coll: str, comm, nbytes: int,
                        alg: Optional[str], src: str) -> None:
        """Record the selection layer's verdict on the timeline, so the
        per-algorithm spans carry WHY that algorithm ran (≈ what MPI
        Advance re-benchmarks offline, captured in-band instead)."""
        if trace_mod.active:
            trace_mod.instant(
                "coll", f"decision:{coll}", rank=comm.pml.rank,
                algorithm=alg or "fixed-default", source=src,
                nbytes=nbytes, size=comm.size)


@coll_framework.component
class HostColl(HostCollBase):
    NAME = "host"
    PRIORITY = 40

    # what _decide may name, per collective (also validation + introspection)
    ALGORITHMS = {
        "bcast": ("binomial", "linear", "pipeline"),
        "allreduce": ("recursive_doubling", "ring", "segmented_ring",
                      "linear"),
        "allgather": ("bruck", "ring"),
        "alltoall": ("pairwise", "bruck"),
        "reduce_scatter": ("ring", "basic"),
    }

    def register_params(self) -> None:
        register_var("coll", "host_allreduce_small", VarType.SIZE, 10 * 1024,
                     "allreduce: below this use recursive doubling "
                     "(tuned's 10KB crossover)")
        register_var("coll", "host_allreduce_segment", VarType.SIZE,
                     1 << 20,
                     "allreduce: above this pipeline the ring in 1MB "
                     "segments (tuned's segmented-ring crossover)")
        register_var("coll", "host_bcast_segment", VarType.SIZE, 128 * 1024,
                     "bcast: pipeline segment size for the chain "
                     "algorithm (tuned's coll_tuned_bcast_segmentsize)")
        register_var("coll", "host_allgather_small", VarType.SIZE, 64 * 1024,
                     "allgather: below this use bruck, above ring")
        register_var("coll", "host_alltoall_small", VarType.SIZE, 4 * 1024,
                     "alltoall: below this use bruck (lg p rounds), "
                     "above pairwise")
        register_var("coll", "host_alltoall_bruck_ranks", VarType.SIZE, 8,
                     "alltoall: bruck also needs at least this many "
                     "ranks (its lg p round count only beats pairwise's "
                     "p-1 when p is large; tuned's comm-size gate)")
        register_var("coll", "host_dynamic_rules", VarType.STRING, "",
                     "path to a dynamic collective-selection rules file "
                     "(see ompi_tpu.mpi.coll.rules)")
        for name in self.ALGORITHMS:
            register_var("coll", f"host_{name}_algorithm", VarType.STRING, "",
                         f"force a {name} algorithm (empty = decide by size)")

    def query(self, comm=None, **ctx) -> Optional[int]:
        if comm is not None and comm.size == 1:
            return None  # coll/self owns size-1
        return self.PRIORITY

    # -- table slots ------------------------------------------------------

    def coll_barrier(self, comm) -> None:
        base.barrier_dissemination(comm)

    def coll_bcast(self, comm, buf, root: int):
        # the algorithm choice must agree on every rank, but only the root
        # knows the message size — so unlike the reference (whose receivers
        # learn sizes from fragment headers) the decision here uses only
        # globally-visible config: forced var or a rules entry at msg size 0
        alg = self._decide("bcast", comm, 0)
        if alg == "pipeline":
            return _timed(
                "bcast", "pipeline", base.bcast_pipeline, comm, buf,
                root, segsize=var_registry.get("coll_host_bcast_segment"))
        if alg == "linear":
            return _timed("bcast", "linear", base.bcast_linear,
                          comm, buf, root)
        return _timed("bcast", "binomial", base.bcast_binomial,
                      comm, buf, root)

    def coll_reduce(self, comm, sendbuf, op: Op, root: int):
        return base.reduce_binomial(comm, sendbuf, op, root)

    def coll_allreduce(self, comm, sendbuf, op: Op):
        nbytes = _nbytes(sendbuf)
        segsize = var_registry.get("coll_host_allreduce_segment")
        alg = self._decide("allreduce", comm, nbytes)
        if alg:
            fn = {"recursive_doubling": base.allreduce_recursive_doubling,
                  "ring": base.allreduce_ring,
                  "segmented_ring": base.allreduce_segmented_ring,
                  "linear": base.allreduce_linear}[alg]
            if not op.commutative and fn is not base.allreduce_linear:
                fn, alg = (base.allreduce_recursive_doubling,
                           "recursive_doubling")
            if fn is base.allreduce_segmented_ring:
                return _timed("allreduce", alg, fn, comm, sendbuf, op,
                              segsize=segsize)
            return _timed("allreduce", alg, fn, comm, sendbuf, op)
        # tuned fixed decision (coll_tuned_decision_fixed.c:65-87)
        if (nbytes < var_registry.get("coll_host_allreduce_small")
                or not op.commutative):
            return _timed("allreduce", "recursive_doubling",
                          base.allreduce_recursive_doubling,
                          comm, sendbuf, op)
        if nbytes >= segsize:
            # the registered crossover var IS the segment size (the two
            # were decoupled before: the var gated, 1MB rode hard-coded)
            return _timed("allreduce", "segmented_ring",
                          base.allreduce_segmented_ring, comm, sendbuf,
                          op, segsize=segsize)
        return _timed("allreduce", "ring", base.allreduce_ring,
                      comm, sendbuf, op)

    def coll_gather(self, comm, sendbuf, root: int):
        return base.gather_linear(comm, sendbuf, root)

    def coll_allgather(self, comm, sendbuf):
        alg = self._decide("allgather", comm, _nbytes(sendbuf))
        if not alg:
            alg = ("bruck" if _nbytes(sendbuf)
                   < var_registry.get("coll_host_allgather_small")
                   else "ring")
        return _timed("allgather", alg,
                      {"bruck": base.allgather_bruck,
                       "ring": base.allgather_ring}[alg], comm, sendbuf)

    def coll_scatter(self, comm, sendbuf, root: int):
        return base.scatter_linear(comm, sendbuf, root)

    @staticmethod
    def _alltoall_fixed(comm, nbytes: int) -> str:
        """The fixed rung: bruck is the small-message AND
        high-rank-count pick — lg p rounds moving p/2 blocks each only
        beat pairwise's p-1 single-block rounds when latency dominates
        (small payloads) and p is large enough for lg p << p."""
        return ("bruck"
                if (nbytes < var_registry.get("coll_host_alltoall_small")
                    and comm.size
                    >= var_registry.get("coll_host_alltoall_bruck_ranks"))
                else "pairwise")

    def coll_alltoall(self, comm, sendbuf):
        alg = self._decide("alltoall", comm, _nbytes(sendbuf))
        if not alg:
            alg = self._alltoall_fixed(comm, _nbytes(sendbuf))
        return _timed("alltoall", alg,
                      {"pairwise": base.alltoall_pairwise,
                       "bruck": base.alltoall_bruck}[alg], comm, sendbuf)

    def coll_reduce_scatter(self, comm, sendbuf, op: Op):
        alg = self._decide("reduce_scatter", comm, _nbytes(sendbuf))
        if alg == "basic" or not op.commutative:
            return _timed("reduce_scatter", "basic",
                          base.reduce_scatter_basic, comm, sendbuf, op)
        return _timed("reduce_scatter", "ring",
                      base.reduce_scatter_ring, comm, sendbuf, op)

    def coll_reduce_scatter_block(self, comm, sendbuf, op: Op):
        arr = np.asarray(sendbuf)
        if arr.shape[0] % comm.size:
            from ompi_tpu.mpi.constants import MPIException

            raise MPIException(
                f"reduce_scatter_block: axis 0 ({arr.shape[0]}) not "
                f"divisible by {comm.size}")
        block = arr.shape[0] // comm.size
        out = self.coll_reduce_scatter(comm, arr.reshape(arr.shape[0], -1),
                                       op)
        return out.reshape((block,) + arr.shape[1:])

    def coll_scan(self, comm, sendbuf, op: Op):
        return base.scan_linear(comm, sendbuf, op)

    def coll_exscan(self, comm, sendbuf, op: Op):
        return base.exscan_linear(comm, sendbuf, op)

    def coll_gatherv(self, comm, sendbuf, root: int):
        return base.gatherv_linear(comm, sendbuf, root)

    def coll_scatterv(self, comm, sendparts, root: int):
        return base.scatterv_linear(comm, sendparts, root)

    def coll_allgatherv(self, comm, sendbuf):
        return base.allgatherv_ring(comm, sendbuf)

    def coll_alltoallv(self, comm, sendparts):
        return base.alltoallv_pairwise(comm, sendparts)

    def coll_alltoallw(self, comm, sendspecs, recvspecs):
        return base.alltoallw_pairwise(comm, sendspecs, recvspecs)

    # -- bind-time freezing (coll/persistent) ------------------------------

    def freeze_decision(self, coll: str, comm, nbytes: int, op=None):
        """Resolve the selection layer ONCE and return ``(fn, label)`` —
        the algorithm callable with its tuning (segment sizes, forced
        var, rules-file hit) baked in, so a persistent plan's Start
        never re-pays the per-op decision walk.  ``fn`` keeps the
        per-collective call shape of the ``coll_*`` table slot it
        freezes (bcast: ``fn(comm, buf, root)``; reduce adds ``op``
        before ``root``; allreduce: ``fn(comm, sendbuf, op)``)."""
        if coll == "barrier":
            return base.barrier_dissemination, "dissemination"
        if coll == "reduce":
            return base.reduce_binomial, "binomial"
        if coll == "bcast":
            alg = self._decide("bcast", comm, 0)
            seg = var_registry.get("coll_host_bcast_segment")
            if alg == "pipeline":
                return (lambda c, buf, root: base.bcast_pipeline(
                    c, buf, root, segsize=seg)), f"pipeline(seg={seg})"
            if alg == "linear":
                return base.bcast_linear, "linear"
            return base.bcast_binomial, "binomial"
        if coll == "allreduce":
            segsize = var_registry.get("coll_host_allreduce_segment")
            alg = self._decide("allreduce", comm, nbytes)
            commutative = op is None or op.commutative
            if not alg:
                if (nbytes < var_registry.get("coll_host_allreduce_small")
                        or not commutative):
                    alg = "recursive_doubling"
                elif nbytes >= segsize:
                    alg = "segmented_ring"
                else:
                    alg = "ring"
            if not commutative and alg != "linear":
                alg = "recursive_doubling"
            if alg == "segmented_ring":
                return (lambda c, sb, o: base.allreduce_segmented_ring(
                    c, sb, o, segsize=segsize)
                ), f"segmented_ring(seg={segsize})"
            return {"recursive_doubling": base.allreduce_recursive_doubling,
                    "ring": base.allreduce_ring,
                    "linear": base.allreduce_linear}[alg], alg
        if coll == "allgather":
            alg = self._decide("allgather", comm, nbytes)
            if not alg:
                alg = ("bruck" if nbytes
                       < var_registry.get("coll_host_allgather_small")
                       else "ring")
            return {"bruck": base.allgather_bruck,
                    "ring": base.allgather_ring}[alg], alg
        if coll == "alltoall":
            alg = self._decide("alltoall", comm, nbytes)
            if not alg:
                alg = self._alltoall_fixed(comm, nbytes)
            return {"pairwise": base.alltoall_pairwise,
                    "bruck": base.alltoall_bruck}[alg], alg
        if coll == "reduce_scatter":
            alg = self._decide("reduce_scatter", comm, nbytes)
            if alg == "basic" or (op is not None and not op.commutative):
                return base.reduce_scatter_basic, "basic"
            return base.reduce_scatter_ring, "ring"
        if coll == "alltoallv":
            return base.alltoallv_pairwise, "pairwise"
        if coll == "scan":
            return base.scan_linear, "linear"
        if coll == "exscan":
            return base.exscan_linear, "linear"
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(f"freeze_decision: no persistent plan for "
                           f"{coll!r}")
