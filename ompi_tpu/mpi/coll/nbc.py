"""Nonblocking collectives: round-based schedules.

≈ ompi/mca/coll/libnbc (nbc_internal.h:146-155): each nonblocking collective
is compiled, at call time, into a *schedule* — an ordered list of rounds,
each holding sends, receives, and an end-of-round local computation.  The
schedule progresses without a helper thread: every ``test()``/``wait()`` on
the returned request advances whatever rounds have completed (the reference
progresses schedules from ``opal_progress``; here the request itself is the
progress hook, which matches MPI's weak progress guarantee).

Tag isolation: every operation draws a fresh tag from the communicator's
nbc sequence counter — collective calls are ordered identically on all ranks
(an MPI-mandated property the reference also leans on, nbc_internal.h's
schedule tags), so concurrently-outstanding collectives never cross-match.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Optional

import numpy as np

from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.op import Op
from ompi_tpu.mpi.request import Request

__all__ = [
    "NbcRequest", "ibarrier", "ibcast", "ireduce", "iallreduce", "igather",
    "iallgather", "iscatter", "ialltoall", "ireduce_scatter", "iscan",
    "iexscan", "ialltoallv", "iallgatherv",
    "barrier_schedule", "bcast_schedule", "reduce_schedule",
    "allreduce_schedule", "allgather_schedule",
]

# offset into the reserved collective tag space (blocking collectives use
# low coll-tags; nbc draws from 64 upward, one per outstanding op)
_NBC_TAG_BASE = 64


class Round:
    """One schedule round: post sends+recvs, await all, then compute."""

    __slots__ = ("sends", "recvs", "compute")

    def __init__(self,
                 sends: tuple = (),
                 recvs: tuple = (),
                 compute: Optional[Callable[[dict], None]] = None) -> None:
        # sends: ((buf_fn(state) -> array, peer), ...) — an optional third
        # element is an ABSOLUTE coll tag overriding the schedule's own
        # (neighbor collectives need the edge-slot tag discipline of
        # topo._send_slot); recvs: ((peer, state_key[, abs_tag]), ...)
        self.sends = sends
        self.recvs = recvs
        self.compute = compute


class NbcRequest(Request):
    """A collective request progressed by test()/wait() (libnbc schedule)."""

    def __init__(self, comm, rounds: list[Round],
                 result: Callable[[dict], Any], tag: int,
                 kind: str = "nbc", state: Optional[dict] = None) -> None:
        super().__init__(kind=kind)
        self._comm = comm
        self._rounds = rounds
        self._result_fn = result
        self._tag = tag
        self._state: dict = state if state is not None else {}
        self._ridx = 0
        self._pending: Optional[list] = None  # [(req, key|None), ...]
        self._nbc_lock = threading.Lock()
        # post→completion latency (the nbc rung of the coll dispatch
        # histogram family; persistent Starts ride coll_pstart_ns)
        self._h_t0 = (_time.monotonic_ns()
                      if trace_mod.hist_active else 0)
        # collective flight recorder: nbc schedules post under their
        # "i<kind>" name with their own (rank, cid) op_seq — round
        # advances and completion ride the same seq so the hang doctor
        # can see WHICH round of a wedged schedule never finished.  The
        # signature is kind-only: per-rank schedule shape (round count
        # differs at tree leaves/interior, chain endpoints) is NOT
        # cross-rank-comparable and would read as a false mismatch
        self._rec_rank = comm.pml.rank
        self._rec_closed = False
        self._rec_seq = trace_mod.coll_post(
            self._rec_rank, comm.cid, kind,
            trace_mod.collrec_sig(kind, None, 0), "nbc", 0)
        self._progress(block=False)

    # -- progress engine --------------------------------------------------

    def _start_round(self) -> None:
        rnd = self._rounds[self._ridx]
        pending = []
        # post receives first (the reference posts recvs before sends in a
        # round to keep the unexpected queue short)
        for entry in rnd.recvs:
            peer, key = entry[0], entry[1]
            tag = entry[2] if len(entry) > 2 else self._tag
            pending.append(
                (self._comm._coll_irecv(None, peer, tag), key))
        for entry in rnd.sends:
            buf_fn, peer = entry[0], entry[1]
            tag = entry[2] if len(entry) > 2 else self._tag
            buf = np.asarray(buf_fn(self._state))
            pending.append((self._comm._coll_isend(buf, peer, tag),
                            None))
        self._pending = pending

    def _finish_round(self) -> None:
        rnd = self._rounds[self._ridx]
        for req, key in self._pending:  # type: ignore[union-attr]
            if key is not None:
                self._state[key] = req.wait()  # already complete
        if rnd.compute is not None:
            rnd.compute(self._state)
        self._pending = None
        self._ridx += 1
        trace_mod.coll_event(
            self._rec_rank, self._comm.cid, "round",
            {"r": self._ridx, "of": len(self._rounds)},
            seq=self._rec_seq, kind=self.kind)

    def _progress(self, block: bool,
                  deadline: Optional[float] = None) -> bool:
        """Advance as far as possible; True when the schedule is done."""
        import time

        with self._nbc_lock:
            if self.done():
                return True
            try:
                while self._ridx < len(self._rounds):
                    if self._pending is None:
                        self._start_round()
                    assert self._pending is not None
                    if block:
                        for req, _ in self._pending:
                            if deadline is None:
                                req.wait()
                            else:
                                remaining = deadline - time.monotonic()
                                if remaining <= 0:
                                    raise TimeoutError(
                                        f"{self.kind} timed out in round "
                                        f"{self._ridx}/{len(self._rounds)}")
                                req.wait(timeout=remaining)
                    elif not all(req.test() for req, _ in self._pending):
                        return False
                    self._finish_round()
            except BaseException as e:
                # a failed round (revoked comm, dead peer, timeout) must
                # close the recorder entry — a leaked in-flight head
                # would read as a forever-wedged rank and freeze the
                # @coll top-level gate (once: test() may re-raise)
                if not self._rec_closed:
                    self._rec_closed = True
                    trace_mod.coll_err(
                        self._rec_rank, self._comm.cid, self._rec_seq,
                        self.kind, type(e).__name__)
                raise
            self.complete(self._result_fn(self._state))
            if not self._rec_closed:
                self._rec_closed = True
                trace_mod.coll_done(self._rec_rank, self._comm.cid,
                                    self._rec_seq, self.kind)
            if self._h_t0 and trace_mod.hist_active:
                trace_mod.record_hist(
                    "coll_nbc_ns", _time.monotonic_ns() - self._h_t0,
                    labels=f'kind="{self.kind}"')
            return True

    # -- Request interface ------------------------------------------------

    def test(self) -> bool:
        return self._progress(block=False)

    def wait(self, timeout: Optional[float] = None) -> Any:
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        self._progress(block=True, deadline=deadline)
        return super().wait(timeout=timeout)


# nbc tags live in [64, 500) — below the OSC (500s) and neighbor-collective
# (700-891) blocks; the sequence wraps within the window (collision would
# need 436 simultaneously-outstanding nbc ops on one communicator)
_NBC_TAG_SPAN = 436


def _next_tag(comm) -> int:
    with comm._lock:
        seq = comm._nbc_seq = getattr(comm, "_nbc_seq", 0) + 1
    return _NBC_TAG_BASE + (seq % _NBC_TAG_SPAN)


def _launch(comm, rounds, result, kind, state=None) -> NbcRequest:
    return NbcRequest(comm, rounds, result, _next_tag(comm), kind=kind,
                      state=state)


def _const(x):
    return lambda state: x


# ---------------------------------------------------------------------------
# schedule builders (one per collective).  The *_schedule functions
# return ``(rounds, make_state, result_fn)`` — a REUSABLE template:
# the rounds close over the caller's arrays (re-read on every launch,
# the persistent-request buffer contract) while all per-launch
# mutability lives in the fresh dict ``make_state()`` returns.  The
# one-shot i* wrappers launch a template once; coll/persistent
# pre-materialises a template at *_init time and launches it per Start.

def barrier_schedule(comm):
    """Dissemination barrier, one round per step."""
    size, rank = comm.size, comm.rank
    token = np.zeros(0, dtype=np.uint8)
    rounds = []
    step = 1
    while step < size:
        to = (rank + step) % size
        frm = (rank - step) % size
        rounds.append(Round(sends=((_const(token), to),),
                            recvs=((frm, f"t{step}"),)))
        step <<= 1
    return rounds, dict, lambda s: None


def ibarrier(comm) -> NbcRequest:
    rounds, make_state, result = barrier_schedule(comm)
    return _launch(comm, rounds, result, "ibarrier", state=make_state())


def bcast_schedule(comm, buf, root: int = 0):
    """Binomial tree: one recv round (non-root), one send round."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return [], dict, _const(np.asarray(buf))
    vrank = (rank - root) % size
    recv_mask = 1
    while recv_mask < size and not (vrank & recv_mask):
        recv_mask <<= 1
    rounds = []
    if vrank != 0:
        parent = ((vrank & ~recv_mask) + root) % size
        rounds.append(Round(recvs=((parent, "buf"),)))
        get = lambda s: s["buf"]  # noqa: E731
    else:
        arr = np.asarray(buf)
        get = _const(arr)
    mask = 1
    while mask < size:
        mask <<= 1
    mask >>= 1
    send_mask = recv_mask >> 1 if vrank != 0 else mask
    sends = []
    while send_mask >= 1:
        vchild = vrank | send_mask
        if vchild < size and vchild != vrank:
            sends.append((get, (vchild + root) % size))
        send_mask >>= 1
    if sends:
        rounds.append(Round(sends=tuple(sends)))
    return rounds, dict, get


def ibcast(comm, buf, root: int = 0) -> NbcRequest:
    rounds, make_state, result = bcast_schedule(comm, buf, root)
    return _launch(comm, rounds, result, "ibcast", state=make_state())


def _reduce_rounds(comm, mine: np.ndarray, op: Op,
                   root: int) -> tuple[list[Round], Callable[[], dict]]:
    """Binomial-fold rounds leaving the reduction in state['acc'] on `root`.
    Children cover disjoint ascending vrank ranges, so folding in ascending
    mask order preserves rank order (valid for non-commutative when the
    effective root is 0, mirroring reduce_binomial)."""
    size, rank = comm.size, comm.rank
    rounds: list[Round] = []
    make_state = lambda: {"acc": mine}  # noqa: E731
    if size == 1:
        return rounds, make_state
    eff_root = root if op.commutative else 0
    vrank = (rank - eff_root) % size
    children = []
    parent = None
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = ((vrank & ~mask) + eff_root) % size
            break
        vchild = vrank | mask
        if vchild < size:
            children.append((vchild + eff_root) % size)
        mask <<= 1

    if children:
        def fold(state, keys=tuple(f"c{i}" for i in range(len(children)))):
            acc = state["acc"]
            for k in keys:
                recv = state[k].reshape(acc.shape).astype(acc.dtype,
                                                          copy=False)
                acc = np.asarray(op.host(acc, recv))
            state["acc"] = acc

        rounds.append(Round(
            recvs=tuple((c, f"c{i}") for i, c in enumerate(children)),
            compute=fold))
    if parent is not None:
        rounds.append(Round(sends=(((lambda s: s["acc"]), parent),)))
    # odd-root forwarding for non-commutative ops
    if eff_root != root:
        if rank == eff_root:
            rounds.append(Round(sends=(((lambda s: s["acc"]), root),)))
        elif rank == root:
            rounds.append(Round(recvs=((eff_root, "fwd"),),
                                compute=lambda s: s.__setitem__(
                                    "acc", s["fwd"].reshape(mine.shape))))
    return rounds, make_state


def reduce_schedule(comm, sendbuf, op: Op, root: int = 0):
    mine = np.asarray(sendbuf)
    rounds, make_state = _reduce_rounds(comm, mine, op, root)
    result = (lambda s: s["acc"]) if comm.rank == root else _const(None)
    return rounds, make_state, result


def ireduce(comm, sendbuf, op: Op, root: int = 0) -> NbcRequest:
    rounds, make_state, result = reduce_schedule(comm, sendbuf, op, root)
    return _launch(comm, rounds, result, "ireduce", state=make_state())


def allreduce_schedule(comm, sendbuf, op: Op):
    """Recursive doubling, one round per step.  Non-pof2 folds *adjacent
    pairs* (rank 2r into 2r+1) in pre/post rounds, exactly as the blocking
    allreduce_recursive_doubling, keeping every surviving rank's block
    rank-contiguous — valid for non-commutative ops."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if size == 1:
        return [], dict, _const(mine)
    shape, dtype = mine.shape, mine.dtype
    pof2 = 1
    while pof2 * 2 <= size:
        pof2 *= 2
    rem = size - pof2
    rounds = []

    def as_acc(state, key):
        return state[key].reshape(shape).astype(dtype, copy=False)

    if rank < 2 * rem and rank % 2 == 0:
        # folded-out even rank: contribute, then wait for the result
        rounds.append(Round(sends=(((lambda s: s["acc"]), rank + 1),)))
        rounds.append(Round(recvs=((rank + 1, "fin"),),
                            compute=lambda s: s.__setitem__(
                                "acc", as_acc(s, "fin"))))
    else:
        if rank < 2 * rem:  # odd pre-fold rank: op(d_{rank-1}, d_rank)
            rounds.append(Round(
                recvs=((rank - 1, "r0"),),
                compute=lambda s: s.__setitem__(
                    "acc", np.asarray(op.host(as_acc(s, "r0"), s["acc"])))))
            newrank = rank // 2
        else:
            newrank = rank - rem

        def real_rank(nr: int) -> int:
            return 2 * nr + 1 if nr < rem else nr + rem

        mask = 1
        while mask < pof2:
            partner = real_rank(newrank ^ mask)

            def fold(state, lower=(newrank ^ mask) < newrank,
                     key=f"m{mask}"):
                recv = as_acc(state, key)
                acc = state["acc"]
                state["acc"] = np.asarray(
                    op.host(recv, acc) if lower else op.host(acc, recv))

            rounds.append(Round(sends=(((lambda s: s["acc"]), partner),),
                                recvs=((partner, f"m{mask}"),),
                                compute=fold))
            mask <<= 1
        if rank < 2 * rem:
            rounds.append(Round(sends=(((lambda s: s["acc"]), rank - 1),)))
    return rounds, (lambda: {"acc": mine}), lambda s: s["acc"]


def iallreduce(comm, sendbuf, op: Op) -> NbcRequest:
    rounds, make_state, result = allreduce_schedule(comm, sendbuf, op)
    return _launch(comm, rounds, result, "iallreduce", state=make_state())


def igather(comm, sendbuf, root: int = 0) -> NbcRequest:
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if size == 1:
        return _launch(comm, [], _const(mine[None]), "igather")
    if rank == root:
        def assemble(state):
            parts = [state[f"p{r}"].reshape(mine.shape).astype(
                mine.dtype, copy=False) if r != root else mine
                for r in range(size)]
            state["out"] = np.stack(parts)

        rounds = [Round(recvs=tuple((r, f"p{r}") for r in range(size)
                                    if r != root),
                        compute=assemble)]
        return _launch(comm, rounds, lambda s: s["out"], "igather")
    rounds = [Round(sends=((_const(mine), root),))]
    return _launch(comm, rounds, _const(None), "igather")


def iscatter(comm, sendbuf, root: int = 0) -> NbcRequest:
    size, rank = comm.size, comm.rank
    if size == 1:
        return _launch(comm, [], _const(np.asarray(sendbuf)), "iscatter")
    if rank == root:
        arr = np.asarray(sendbuf)
        if arr.shape[0] % size:
            from ompi_tpu.mpi.constants import MPIException

            raise MPIException(
                f"iscatter: axis 0 ({arr.shape[0]}) not divisible by {size}")
        parts = np.split(arr, size, axis=0)
        rounds = [Round(sends=tuple((_const(parts[r]), r)
                                    for r in range(size) if r != root))]
        return _launch(comm, rounds, _const(parts[root]), "iscatter")
    rounds = [Round(recvs=((root, "p"),))]
    return _launch(comm, rounds, lambda s: s["p"], "iscatter")


def allgather_schedule(comm, sendbuf):
    """Ring: p-1 rounds of neighbor sendrecv."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if size == 1:
        return [], dict, _const(mine[None])
    right = (rank + 1) % size
    left = (rank - 1) % size
    rounds = []
    send_idx = rank
    for _ in range(size - 1):
        recv_idx = (send_idx - 1) % size

        def store(state, recv_idx=recv_idx):
            state[f"b{recv_idx}"] = state.pop("_r").reshape(
                mine.shape).astype(mine.dtype, copy=False)

        rounds.append(Round(
            sends=(((lambda s, i=send_idx: s[f"b{i}"]), right),),
            recvs=((left, "_r"),),
            compute=store))
        send_idx = recv_idx

    def result(state):
        return np.stack([state[f"b{r}"] for r in range(size)])

    return rounds, (lambda: {f"b{rank}": mine}), result


def iallgather(comm, sendbuf) -> NbcRequest:
    rounds, make_state, result = allgather_schedule(comm, sendbuf)
    return _launch(comm, rounds, result, "iallgather", state=make_state())


def ialltoall(comm, sendbuf) -> NbcRequest:
    """Pairwise: p-1 rounds."""
    size, rank = comm.size, comm.rank
    arr = np.asarray(sendbuf)
    if arr.shape[0] % size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"ialltoall: axis 0 ({arr.shape[0]}) not divisible by {size}")
    if size == 1:
        return _launch(comm, [], _const(arr), "ialltoall")
    parts = np.split(arr, size, axis=0)
    rounds = []
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step) % size

        def store(state, frm=frm):
            state[f"b{frm}"] = state.pop("_r").reshape(
                parts[0].shape).astype(arr.dtype, copy=False)

        rounds.append(Round(sends=((_const(parts[to]), to),),
                            recvs=((frm, "_r"),), compute=store))

    def result(state):
        return np.concatenate([state[f"b{r}"] for r in range(size)])

    return _launch(comm, rounds, result, "ialltoall",
                   state={f"b{rank}": parts[rank]})


def ireduce_scatter(comm, sendbuf, op: Op) -> NbcRequest:
    """Ring reduce-scatter: p-1 rounds (commutative; non-commutative ops
    fall back to reduce+scatter rounds)."""
    size, rank = comm.size, comm.rank
    arr = np.asarray(sendbuf)
    if size == 1:
        return _launch(comm, [], _const(arr), "ireduce_scatter")
    if not op.commutative:
        # rank order must be preserved (the ring below folds out of order):
        # one schedule = binomial-reduce rounds + a scatter round
        rounds, make_state = _reduce_rounds(comm, arr, op, 0)
        if rank == 0:
            def part(s, r):
                return np.array_split(s["acc"].reshape(-1), size)[r]

            rounds.append(Round(sends=tuple(
                ((lambda s, r=r: part(s, r)), r) for r in range(1, size))))
            return _launch(comm, rounds, lambda s: part(s, 0),
                           "ireduce_scatter", state=make_state())
        rounds.append(Round(recvs=((0, "p"),)))
        return _launch(comm, rounds, lambda s: s["p"], "ireduce_scatter",
                       state=make_state())
    flat = arr.reshape(-1)
    chunks = [c.copy() for c in np.array_split(flat, size)]
    right = (rank + 1) % size
    left = (rank - 1) % size
    rounds = []
    send_idx = (rank - 1) % size
    for _ in range(size - 1):
        recv_idx = (send_idx - 1) % size

        def fold(state, recv_idx=recv_idx):
            cur = state[f"c{recv_idx}"]
            recv = state.pop("_r").astype(cur.dtype, copy=False)
            state[f"c{recv_idx}"] = np.asarray(op.host(cur, recv))

        rounds.append(Round(
            sends=(((lambda s, i=send_idx: s[f"c{i}"]), right),),
            recvs=((left, "_r"),), compute=fold))
        send_idx = recv_idx
    return _launch(comm, rounds, lambda s: s[f"c{rank}"], "ireduce_scatter",
                   state={f"c{i}": c for i, c in enumerate(chunks)})


def _chain_scan(comm, sendbuf, op: Op, exclusive: bool,
                kind: str) -> NbcRequest:
    rank, size = comm.rank, comm.size
    mine = np.asarray(sendbuf)
    rounds = []
    if rank > 0:
        rounds.append(Round(recvs=((rank - 1, "prev"),)))
    if rank < size - 1:
        def fwd(state):
            prev = state.get("prev")
            if prev is None:
                return mine
            prev = prev.reshape(mine.shape).astype(mine.dtype, copy=False)
            return np.asarray(op.host(prev, mine))

        rounds.append(Round(sends=((fwd, rank + 1),)))

    def result(state):
        prev = state.get("prev")
        if prev is not None:
            prev = prev.reshape(mine.shape).astype(mine.dtype, copy=False)
        if exclusive:
            return prev  # None on rank 0 (undefined per MPI)
        return mine if prev is None else np.asarray(op.host(prev, mine))

    return _launch(comm, rounds, result, kind)


def iscan(comm, sendbuf, op: Op) -> NbcRequest:
    return _chain_scan(comm, sendbuf, op, exclusive=False, kind="iscan")


def iexscan(comm, sendbuf, op: Op) -> NbcRequest:
    return _chain_scan(comm, sendbuf, op, exclusive=True, kind="iexscan")


def iallgatherv(comm, sendbuf) -> NbcRequest:
    """Linear: everyone sends to everyone (variable block sizes)."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if size == 1:
        return _launch(comm, [], _const([mine]), "iallgatherv")
    rounds = [Round(
        sends=tuple((_const(mine), r) for r in range(size) if r != rank),
        recvs=tuple((r, f"b{r}") for r in range(size) if r != rank))]

    def result(state):
        return [state[f"b{r}"] if r != rank else mine for r in range(size)]

    return _launch(comm, rounds, result, "iallgatherv")


def igatherv(comm, sendbuf, root: int = 0) -> NbcRequest:
    """Linear, variable block shapes: root collects one array per rank."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if size == 1:
        return _launch(comm, [], _const([mine]), "igatherv")
    if rank == root:
        def result(state):
            return [state[f"p{r}"] if r != root else mine
                    for r in range(size)]

        rounds = [Round(recvs=tuple((r, f"p{r}") for r in range(size)
                                    if r != root))]
        return _launch(comm, rounds, result, "igatherv")
    return _launch(comm, [Round(sends=((_const(mine), root),))],
                   _const(None), "igatherv")


def iscatterv(comm, sendparts, root: int = 0) -> NbcRequest:
    """Linear, variable block shapes: root sends sendparts[r] to rank r."""
    size, rank = comm.size, comm.rank
    if size == 1:
        return _launch(comm, [], _const(np.asarray(sendparts[0])),
                       "iscatterv")
    if rank == root:
        if len(sendparts) != size:
            from ompi_tpu.mpi.constants import MPIException

            raise MPIException(
                f"iscatterv: {len(sendparts)} blocks for {size} ranks")
        rounds = [Round(sends=tuple(
            (_const(np.asarray(sendparts[r])), r)
            for r in range(size) if r != root))]
        return _launch(comm, rounds, _const(np.asarray(sendparts[root])),
                       "iscatterv")
    return _launch(comm, [Round(recvs=((root, "p"),))], lambda s: s["p"],
                   "iscatterv")


def ireduce_scatter_block(comm, sendbuf, op: Op) -> NbcRequest:
    """Reduce then scatter equal blocks: ireduce to 0 + iscatter rounds
    chained (the libnbc composition for the _block variant)."""
    size, rank = comm.size, comm.rank
    mine = np.asarray(sendbuf)
    if mine.shape[0] % size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"ireduce_scatter_block: axis 0 ({mine.shape[0]}) not "
            f"divisible by {size}")
    if size == 1:
        return _launch(comm, [], _const(mine), "ireduce_scatter_block")
    # stage 1: everyone sends their r-th block to rank r; stage 2 is local
    blocks = np.split(mine, size, axis=0)
    rounds = [Round(
        sends=tuple((_const(blocks[r]), r) for r in range(size)
                    if r != rank),
        recvs=tuple((r, f"b{r}") for r in range(size) if r != rank))]

    def result(state):
        # fold in RANK order — required for non-commutative ops (same
        # contract as ireduce_scatter's non-commutative branch)
        acc = None
        for r in range(size):
            b = blocks[rank] if r == rank else state[f"b{r}"]
            b = np.asarray(b).reshape(blocks[rank].shape).astype(
                blocks[rank].dtype, copy=False)
            acc = b if acc is None else op.host(acc, b)
        return acc

    return _launch(comm, rounds, result, "ireduce_scatter_block")


def ialltoallw(comm, sendspecs, recvspecs) -> NbcRequest:
    """Nonblocking Alltoallw: packed per-peer blocks exchanged in one
    linear round; receive datatypes unpack into the caller's buffers at
    completion."""
    from ompi_tpu.mpi.coll.base import pack_spec, unpack_spec

    size, rank = comm.size, comm.rank
    if len(sendspecs) != size or len(recvspecs) != size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"ialltoallw: {len(sendspecs)}/{len(recvspecs)} specs for "
            f"{size} ranks")
    if size == 1:
        unpack_spec(recvspecs[0], pack_spec(sendspecs[0]))
        return _launch(comm, [], _const(None), "ialltoallw")
    rounds = [Round(
        sends=tuple((_const(pack_spec(sendspecs[r])), r)
                    for r in range(size) if r != rank),
        recvs=tuple((r, f"b{r}") for r in range(size) if r != rank))]

    def result(state):
        unpack_spec(recvspecs[rank], pack_spec(sendspecs[rank]))
        for r in range(size):
            if r != rank:
                unpack_spec(recvspecs[r], state[f"b{r}"])
        return None

    return _launch(comm, rounds, result, "ialltoallw")


def ialltoallv(comm, sendparts) -> NbcRequest:
    size, rank = comm.size, comm.rank
    if len(sendparts) != size:
        from ompi_tpu.mpi.constants import MPIException

        raise MPIException(
            f"ialltoallv: {len(sendparts)} blocks for {size} ranks")
    mine = np.asarray(sendparts[rank])
    if size == 1:
        return _launch(comm, [], _const([mine]), "ialltoallv")
    rounds = [Round(
        sends=tuple((_const(np.asarray(sendparts[r])), r)
                    for r in range(size) if r != rank),
        recvs=tuple((r, f"b{r}") for r in range(size) if r != rank))]

    def result(state):
        return [state[f"b{r}"] if r != rank else mine for r in range(size)]

    return _launch(comm, rounds, result, "ialltoallv")
