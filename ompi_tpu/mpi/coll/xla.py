"""coll/xla — the device collective component (MCA slot ≈ ompi/mca/coll/cuda).

The reference's coll/cuda (coll_cuda_allreduce.c:30-69) intercepts device
buffers, stages them through host bounce buffers, and delegates to the CPU
algorithms.  This component is the TPU-first inversion of that slot: device
buffers NEVER cross to host — every collective lowers to an XLA collective
(lax.psum / all_gather / all_to_all / ppermute) over the communicator's
bound ``DeviceCommunicator`` mesh axes, so the data plane is pure ICI/HBM.

Two buffer kinds reach this component (the CollModule dispatcher routes by
``core.buffer.classify()``; host buffers go to coll/host):

- **TRACED** — the call site is inside ``jit``/``shard_map`` over the mesh:
  delegate straight to the DeviceCommunicator method; the collective fuses
  into the surrounding compiled program.
- **DEVICE** — a committed ``jax.Array`` in driver mode: wrap the same
  method in a one-off ``shard_map``+``jit`` over the bound mesh (the array's
  axis 0 is the concatenation of per-device shards, matching
  ``DeviceCommunicator.run``'s convention).

Selection: ``--mca coll xla`` forces this path exclusively (host buffers
then error); ``--mca coll ^xla`` removes it (device buffers then raise
``BufferLocationError`` at the dispatcher).  Default: stacked above host,
chosen per-buffer — the behavior-gated substitution BASELINE.json names as
the north star.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.core.buffer import BufferKind, BufferLocationError, classify
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component
from ompi_tpu.mpi.coll import coll_framework, rules
from ompi_tpu.mpi.op import Op

__all__ = ["XlaColl"]


def _dev_nbytes(buf) -> int:
    """Static byte size of a jax array OR tracer (shape/dtype are always
    static under jit — no materialization)."""
    try:
        return int(np.prod(buf.shape)) * buf.dtype.itemsize
    except Exception:  # noqa: BLE001 — unshaped input: decide as "small"
        return 0


import os as _os

_MEASURED_PATH = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                               "xla_measured_rules.conf")
_measured_cache: list = []  # [(mtime|None, RuleSet|None)] — len-1 memo


def _measured_rules():
    """The shipped measured-crossover RuleSet, or None when the file is
    absent, empty of rules, or was measured on a different platform than
    the one running now (cpu-measured crossovers must not steer TPU)."""
    import os

    try:
        mtime = os.stat(_MEASURED_PATH).st_mtime
    except OSError:
        return None
    if _measured_cache and _measured_cache[0][0] == mtime:
        return _measured_cache[0][1]
    rs = None
    try:
        loaded = rules.load_rules(_MEASURED_PATH)
        import jax

        if (len(loaded) > 0
                and loaded.meta.get("platform") == jax.default_backend()):
            rs = loaded
    except Exception:  # noqa: BLE001 — a bad shipped file must not break colls
        rs = None
    _measured_cache[:] = [(mtime, rs)]
    return rs


def _device_comm(comm):
    dc = getattr(comm, "device", None)
    if dc is None:
        raise BufferLocationError(
            f"{comm.name}: device buffer in a collective but no device "
            f"communicator is bound; call comm.bind_device(device_comm) "
            f"(e.g. device_world(mesh)) so coll/xla knows the mesh axes")
    return dc


def _run(comm, method: str, buf, *args, **kw):
    """Dispatch traced vs committed-device execution of one collective."""
    dc = _device_comm(comm)
    fn = getattr(dc, method)
    if classify(buf) is BufferKind.TRACED:
        return fn(buf, *args, **kw)
    # driver mode rides the compiled-program cache: repeated collectives
    # with the same (method, args, shapes) reuse one jitted shard_map
    return dc.run_method(method, buf, margs=args,
                         mkw=tuple(sorted(kw.items())))


@coll_framework.component
class XlaColl(Component):
    """Device collectives with a tuned-style decision layer.

    ≈ coll/tuned's fixed decision (coll_tuned_decision_fixed.c:44-87)
    transposed to the device path: per collective the choice is between the
    XLA-native lowering (psum / all_gather — latency-optimal, lets XLA pick
    the ICI algorithm) and an explicit ppermute/2-phase form whose
    communication shape favors bandwidth or a DCN-crossing axis (the
    btl.h:1181-1183 latency/bandwidth ranking axis, SURVEY §2.6).  The
    selection is (bytes × comm size × axis kind), overridable per
    collective by config var or the same dynamic rules file the host path
    honors."""

    NAME = "xla"
    PRIORITY = 60        # above host (40); the dispatcher routes by buffer
    HANDLES = frozenset({"device", "traced"})

    # "qint8" (EQuARX-style int8 wire format, device_comm.allreduce_qint8)
    # is in the menu for forcing/tuning but is LOSSY and never chosen by
    # the auto decision
    ALGORITHMS = {
        "allreduce": ("psum", "rs_ag", "segmented", "qint8"),
        "allgather": ("all_gather", "ring"),
        "bcast": ("psum_mask", "ring"),
    }
    # collective → algorithm → DeviceCommunicator method
    _IMPL = {
        "allreduce": {"psum": "allreduce", "rs_ag": "allreduce_rs_ag",
                      "segmented": "allreduce_segmented",
                      "qint8": "allreduce_qint8"},
        "allgather": {"all_gather": "allgather", "ring": "allgather_ring"},
        "bcast": {"psum_mask": "bcast", "ring": "bcast_ring"},
    }
    # algorithms that change RESULTS, not just schedules: measured and
    # forceable, but never auto-picked (tools/tune excludes them from
    # generated crossover rules; _decide never returns them)
    LOSSY = {"allreduce": frozenset({"qint8"})}

    def register_params(self) -> None:
        register_var("coll", "xla_dcn_axes", VarType.STRING, "",
                     "comma-separated mesh axis names that cross DCN "
                     "(inter-slice); collectives over them prefer "
                     "neighbor-shaped algorithms (ring/2-phase)")
        register_var("coll", "xla_allreduce_large", VarType.SIZE, 32 << 20,
                     "allreduce: at/above this PER-SHARD byte size switch "
                     "to the 2-phase reduce_scatter+all_gather form "
                     "(bandwidth-optimal ring shape; below, XLA's fused "
                     "psum wins on latency)")
        register_var("coll", "xla_dynamic_rules", VarType.STRING, "",
                     "path to a dynamic rules file for the DEVICE path "
                     "(same format as coll_host_dynamic_rules)")
        for name in self.ALGORITHMS:
            register_var("coll", f"xla_{name}_algorithm", VarType.STRING, "",
                         f"force a device {name} algorithm (empty = decide "
                         f"by size/axis kind)")

    def query(self, comm=None, **ctx) -> Optional[int]:
        return self.PRIORITY

    # -- decision layer ----------------------------------------------------

    def _crosses_dcn(self, dc) -> bool:
        spec = var_registry.get("coll_xla_dcn_axes") or ""
        dcn = {a.strip() for a in spec.split(",") if a.strip()}
        return bool(dcn.intersection(dc.axes))

    def _decide(self, coll: str, comm, dc, nbytes: int) -> str:
        """forced var > user rules file > shipped measured rules > fixed
        (bytes × size × axis kind)."""
        valid = self.ALGORITHMS[coll]
        alg = var_registry.get(f"coll_xla_{coll}_algorithm")
        src = f"config var coll_xla_{coll}_algorithm"
        if not alg:
            path = var_registry.get("coll_xla_dynamic_rules")
            if path:
                alg = rules.load_rules(path).lookup(coll, dc.size, nbytes)
                src = f"rules file {path}"
        if not alg and not self._crosses_dcn(dc):
            # measured crossovers from ompi_tpu.tools.tune, shipped next
            # to this component (the reference's fixed tables were also
            # measured numbers, coll_tuned_decision_fixed.c:56-74) —
            # consulted only when the file's provenance platform matches
            # the running backend AND this communicator's size is within
            # 2× of the measured mesh (8-device crossover points must not
            # steer a 2-device comm); DCN-spanning axes keep the
            # neighbor-shaped fixed decision (the measurement was
            # single-slice)
            rs = _measured_rules()
            if rs is not None:
                try:
                    meta_n = int(rs.meta.get("n_devices", 0))
                except ValueError:
                    meta_n = 0
                if meta_n and meta_n / 2 <= dc.size <= meta_n * 2:
                    alg = rs.lookup(coll, dc.size, nbytes)
                    src = "measured rules (xla_measured_rules.conf)"
        if alg:
            from ompi_tpu.mpi.constants import MPIException

            if alg not in valid:
                raise MPIException(
                    f"unknown device {coll} algorithm {alg!r} (from {src}); "
                    f"valid: {', '.join(valid)}")
            if (alg in self.LOSSY.get(coll, frozenset())
                    and not src.startswith("config var")):
                # a rules FILE must not silently change results; lossy
                # algorithms are an explicit per-run opt-in only
                raise MPIException(
                    f"device {coll} algorithm {alg!r} (from {src}) is "
                    f"lossy and may only be forced via the "
                    f"coll_xla_{coll}_algorithm config var")
            return alg
        # fixed decision: neighbor-shaped on DCN axes or huge payloads;
        # XLA-native (fused, ICI-aware) otherwise
        dcn = self._crosses_dcn(dc)
        if coll == "allreduce":
            large = var_registry.get("coll_xla_allreduce_large")
            return "rs_ag" if (dcn or nbytes >= large) else "psum"
        if coll == "allgather":
            return "ring" if dcn else "all_gather"
        return "ring" if dcn else "psum_mask"

    def _run_decided(self, coll: str, comm, buf, *args, **kw):
        dc = _device_comm(comm)
        nbytes = _dev_nbytes(buf)
        # canonical decision unit: PER-SHARD bytes (what each ICI link
        # moves).  A traced call sees the per-shard tracer already; a
        # driver-mode call sees the committed global array — normalize so
        # both modes look up the same rule boundary (and the tuner's
        # measured crossovers, recorded per-shard, apply uniformly).
        if classify(buf) is BufferKind.DEVICE:
            nbytes //= max(1, dc.size)
        alg = self._decide(coll, comm, dc, nbytes)
        return _run(comm, self._IMPL[coll][alg], buf, *args, **kw)

    # -- table slots (device implementations) ------------------------------

    def coll_barrier(self, comm) -> None:
        # host-driven barrier semantics: an empty psum over the mesh,
        # blocking the driver until every device participated (compiled
        # once per mesh via the run_method cache — round-2 weak #5)
        dc = _device_comm(comm)
        dc.run_method("barrier", np.zeros((dc.size,), "int32"))

    def coll_bcast(self, comm, buf, root: int):
        return self._run_decided("bcast", comm, buf, root)

    def coll_reduce(self, comm, sendbuf, op: Op, root: int):
        return _run(comm, "reduce", sendbuf, op, root)

    def coll_allreduce(self, comm, sendbuf, op: Op):
        # both impls take (x, op); rs_ag falls back to psum for non-SUM
        return self._run_decided("allreduce", comm, sendbuf, op)

    def coll_gather(self, comm, sendbuf, root: int):
        return _run(comm, "gather", sendbuf, root)

    def coll_allgather(self, comm, sendbuf):
        return self._run_decided("allgather", comm, sendbuf)

    def coll_scatter(self, comm, sendbuf, root: int):
        return _run(comm, "scatter", sendbuf, root)

    def coll_alltoall(self, comm, sendbuf):
        return _run(comm, "alltoall", sendbuf)

    def coll_reduce_scatter(self, comm, sendbuf, op: Op):
        return _run(comm, "reduce_scatter", sendbuf, op)

    def coll_reduce_scatter_block(self, comm, sendbuf, op: Op):
        return _run(comm, "reduce_scatter", sendbuf, op)

    def coll_scan(self, comm, sendbuf, op: Op):
        return _run(comm, "scan", sendbuf, op)

    def coll_exscan(self, comm, sendbuf, op: Op):
        return _run(comm, "exscan", sendbuf, op)

    # v-collectives: through the MPI API the device path sees one uniform
    # shard per rank (SPMD programs are single-shape), so these lower to
    # the dense forms; ragged counts are first-class on DeviceCommunicator
    # (allgatherv/scatterv/alltoallv with a static counts vector → pad+mask)

    def coll_gatherv(self, comm, sendbuf, root: int):
        return _run(comm, "gatherv", sendbuf, None, root)

    def coll_scatterv(self, comm, sendparts, root: int):
        return _run(comm, "scatterv", sendparts, None, root)

    def coll_allgatherv(self, comm, sendbuf):
        return _run(comm, "allgatherv", sendbuf)

    def coll_alltoallv(self, comm, sendparts):
        return _run(comm, "alltoallv", sendparts)
