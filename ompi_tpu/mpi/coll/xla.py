"""coll/xla — the device collective component (MCA slot ≈ ompi/mca/coll/cuda).

The reference's coll/cuda (coll_cuda_allreduce.c:30-69) intercepts device
buffers, stages them through host bounce buffers, and delegates to the CPU
algorithms.  This component is the TPU-first inversion of that slot: device
buffers NEVER cross to host — every collective lowers to an XLA collective
(lax.psum / all_gather / all_to_all / ppermute) over the communicator's
bound ``DeviceCommunicator`` mesh axes, so the data plane is pure ICI/HBM.

Two buffer kinds reach this component (the CollModule dispatcher routes by
``core.buffer.classify()``; host buffers go to coll/host):

- **TRACED** — the call site is inside ``jit``/``shard_map`` over the mesh:
  delegate straight to the DeviceCommunicator method; the collective fuses
  into the surrounding compiled program.
- **DEVICE** — a committed ``jax.Array`` in driver mode: wrap the same
  method in a one-off ``shard_map``+``jit`` over the bound mesh (the array's
  axis 0 is the concatenation of per-device shards, matching
  ``DeviceCommunicator.run``'s convention).

Selection: ``--mca coll xla`` forces this path exclusively (host buffers
then error); ``--mca coll ^xla`` removes it (device buffers then raise
``BufferLocationError`` at the dispatcher).  Default: stacked above host,
chosen per-buffer — the behavior-gated substitution BASELINE.json names as
the north star.
"""

from __future__ import annotations

from typing import Optional

from ompi_tpu.core.buffer import BufferKind, BufferLocationError, classify
from ompi_tpu.core.mca import Component
from ompi_tpu.mpi.coll import coll_framework
from ompi_tpu.mpi.op import Op

__all__ = ["XlaColl"]


def _device_comm(comm):
    dc = getattr(comm, "device", None)
    if dc is None:
        raise BufferLocationError(
            f"{comm.name}: device buffer in a collective but no device "
            f"communicator is bound; call comm.bind_device(device_comm) "
            f"(e.g. device_world(mesh)) so coll/xla knows the mesh axes")
    return dc


def _run(comm, method: str, buf, *args, **kw):
    """Dispatch traced vs committed-device execution of one collective."""
    dc = _device_comm(comm)
    fn = getattr(dc, method)
    if classify(buf) is BufferKind.TRACED:
        return fn(buf, *args, **kw)
    return dc.run(lambda c, shard: getattr(c, method)(shard, *args, **kw),
                  buf)


@coll_framework.component
class XlaColl(Component):
    NAME = "xla"
    PRIORITY = 60        # above host (40); the dispatcher routes by buffer
    HANDLES = frozenset({"device", "traced"})

    def query(self, comm=None, **ctx) -> Optional[int]:
        return self.PRIORITY

    # -- table slots (device implementations) ------------------------------

    def coll_barrier(self, comm) -> None:
        # host-driven barrier semantics: an empty psum over the mesh,
        # blocking the driver until every device participated
        dc = _device_comm(comm)
        import numpy as np

        dc.run(lambda c, t: c.barrier(t), np.zeros((dc.size,), "int32"))

    def coll_bcast(self, comm, buf, root: int):
        return _run(comm, "bcast", buf, root)

    def coll_reduce(self, comm, sendbuf, op: Op, root: int):
        return _run(comm, "reduce", sendbuf, op, root)

    def coll_allreduce(self, comm, sendbuf, op: Op):
        return _run(comm, "allreduce", sendbuf, op)

    def coll_gather(self, comm, sendbuf, root: int):
        return _run(comm, "gather", sendbuf, root)

    def coll_allgather(self, comm, sendbuf):
        return _run(comm, "allgather", sendbuf)

    def coll_scatter(self, comm, sendbuf, root: int):
        return _run(comm, "scatter", sendbuf, root)

    def coll_alltoall(self, comm, sendbuf):
        return _run(comm, "alltoall", sendbuf)

    def coll_reduce_scatter(self, comm, sendbuf, op: Op):
        return _run(comm, "reduce_scatter", sendbuf, op)

    def coll_reduce_scatter_block(self, comm, sendbuf, op: Op):
        return _run(comm, "reduce_scatter", sendbuf, op)

    def coll_scan(self, comm, sendbuf, op: Op):
        return _run(comm, "scan", sendbuf, op)
