"""coll/self — collectives on size-1 communicators (≈ ompi/mca/coll/self).

Every collective degenerates to a local identity/copy; stacking rules give it
top priority only when size == 1.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.core.mca import Component
from ompi_tpu.mpi.coll import coll_framework
from ompi_tpu.mpi.op import Op


@coll_framework.component
class SelfColl(Component):
    NAME = "self"
    PRIORITY = 90

    def query(self, comm=None, **ctx) -> Optional[int]:
        if comm is not None and comm.size == 1:
            return self.PRIORITY
        return None

    def coll_barrier(self, comm) -> None:
        return None

    def coll_bcast(self, comm, buf, root: int):
        return np.asarray(buf)

    def coll_reduce(self, comm, sendbuf, op: Op, root: int):
        return np.asarray(sendbuf)

    def coll_allreduce(self, comm, sendbuf, op: Op):
        return np.asarray(sendbuf)

    def coll_gather(self, comm, sendbuf, root: int):
        return np.asarray(sendbuf)[None]

    def coll_allgather(self, comm, sendbuf):
        return np.asarray(sendbuf)[None]

    def coll_scatter(self, comm, sendbuf, root: int):
        return np.asarray(sendbuf)

    def coll_alltoall(self, comm, sendbuf):
        return np.asarray(sendbuf)

    def coll_reduce_scatter(self, comm, sendbuf, op: Op):
        return np.asarray(sendbuf).reshape(-1)

    def coll_reduce_scatter_block(self, comm, sendbuf, op: Op):
        return np.asarray(sendbuf)

    def coll_scan(self, comm, sendbuf, op: Op):
        return np.asarray(sendbuf)

    def coll_exscan(self, comm, sendbuf, op: Op):
        return None  # rank 0's exscan result is undefined per MPI

    def coll_gatherv(self, comm, sendbuf, root: int):
        return [np.asarray(sendbuf)]

    def coll_scatterv(self, comm, sendparts, root: int):
        return np.asarray(sendparts[0])

    def coll_allgatherv(self, comm, sendbuf):
        return [np.asarray(sendbuf)]

    def coll_alltoallv(self, comm, sendparts):
        # None is MPI's zero-count entry, here as everywhere else
        if sendparts[0] is None:
            return [np.empty(0, np.uint8)]
        return [np.asarray(sendparts[0])]

    def coll_alltoallw(self, comm, sendspecs, recvspecs):
        from ompi_tpu.mpi.coll.base import pack_spec, unpack_spec

        if sendspecs[0] is not None:
            unpack_spec(recvspecs[0], pack_spec(sendspecs[0]))
        return None
