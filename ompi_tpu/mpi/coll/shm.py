"""coll/shm — single-copy on-node collectives over a shared-memory arena.

≈ ompi/mca/coll/sm (and the HiCCL intra/inter decomposition from
PAPERS.md): every other component moves collective payloads as
2(p-1)-ish framed point-to-point messages through the PML matching
engine — header encode/decode, matching, and a scheduler wakeup per
hop, the measured ~58 µs/hop floor compounding linearly in p.  Ranks
that share a host do not need any of that: this component maps ONE
per-communicator arena (built on ``core.shmseg``, the same framework
the btl/shm rings ride) and turns barrier/bcast/reduce/allreduce/
allgather into single-copy fan-in/fan-out through it — zero PML
frames, zero matching, zero per-hop headers.

Arena layout (one file in ``shmseg.backing_dir()``, unlinked right
after the attach agreement so crash cleanup is free)::

    [ arrive u64 ×p (cacheline-padded) | depart u64 ×p (padded) ]
    [ desc 128B ×p ]  [ slot ×(p+1) ]          # slot p = result slot

``arrive[r]``/``depart[r]`` are **monotonic sequence counters** with a
single writer each (rank r), read by everyone — the sequence-numbered
generalisation of a sense-reversing barrier (a monotonic seq never
needs its sense flipped, and one pair of counters serialises every
collective kind on the communicator).  All counter accesses go through
``memoryview.cast("Q")`` so each is one native aligned 8-byte memory
op — the same store-ordering discipline (x86 TSO) the btl/shm ring
counters use, and the same reason ``struct.pack_into`` must not be
used here.

Data moves by **one copy per side**: writers publish straight into
their slot (``np.copyto`` walks strided sources directly into the
mapped segment — the PR-1 convertor-plan idea with numpy as the run
engine, no staging buffer), readers copy straight out; the fold rank
reduces *views of the mapped slots* in rank order without copying them
at all.  Payloads larger than a slot pipeline through the slot halves
(double-buffered: ranks publish segment k+1 while the fold rank is
still folding segment k — the ``allreduce_segmented_ring`` overlap
idea, fan-in form).

Dispatch ladder per collective:

- all ranks on one host → the flat arena;
- mixed hosts → hierarchical composition (HiCCL-style): the cached
  ``split_type(COMM_TYPE_SHARED)`` node communicator runs the intra
  phases through its arena, the cached leader communicator runs the
  inter phase through coll/host's tuned algorithms;
- fall back to coll/host per-collective when the op is non-commutative,
  the payload exceeds ``coll_shm_arena_size``, an explicit
  ``coll_host_*_algorithm``/rules-file directive names a host
  algorithm (user tuning outranks the shortcut), or no usable shm
  backing dir exists.  Every fallback bumps ``coll_shm_fallback_total``
  and drops a ``decision:<coll>`` instant on the timeline.

For bcast only the root knows the payload, so the root *communicates*
its arena-vs-host verdict through the descriptor round — every rank
takes the same branch without a pre-exchange.

Collective-capable rejoin (errmgr selfheal): the cached state is
stamped with the communicator's **coll epoch**
(``ft.comm_coll_epoch`` — the sum of the members' adopted
incarnations).  A revived member's new life never mapped the old arena
(the segment name was unlinked at build), so the first dispatch at a
stale epoch — or a wait already parked against the dead life's flags
(``StaleCollEpoch`` out of the FT check) — tears the state down and
rebuilds it with the revived rank included.  The rebuild prologue
MAX-agrees the epoch and the parent's cid/tag counters over the base
p2p plane (a revived life's fresh counters would otherwise derive
divergent split cids and the rebuild's own collectives could never
match).  Counted by ``coll_rejoin_total`` / timed by
``coll_rejoin_ns``; pushed to the HNP FT timeline via the PMIx
``coll_rejoin`` RPC.
"""

from __future__ import annotations

import ctypes
import functools
import os
import time
import uuid
import weakref
from typing import Optional

import numpy as np

from ompi_tpu import _native
from ompi_tpu.core import output, shmseg
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component
from ompi_tpu.mpi import op as op_mod
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.coll import base, coll_framework, rules
from ompi_tpu.mpi.constants import (
    COMM_TYPE_SHARED, ERR_PROC_FAILED, UNDEFINED, MPIException,
)
from ompi_tpu.mpi.op import Op

__all__ = ["ShmColl", "Arena", "PersistentSlots", "StaleCollEpoch",
           "make_persistent_slots", "decide_allreduce_algo"]

_log = output.get_stream("coll")

_CACHELINE = 64
_DESC = 128                     # per-rank op-descriptor bytes
_DESC_DATA, _DESC_HOST = 1, 2   # descriptor verdicts (bcast root decides)
_MAX_DIMS = 8                   # descriptor shape capacity
_TOKEN = np.zeros(0, np.uint8)  # gate payload for the arena-less intra path


def _arena_dtype_ok(dtype: np.dtype) -> bool:
    """Raw-byte publishable: fixed-size, no python object indirection."""
    return not dtype.hasobject and dtype.itemsize > 0


def _coll_epoch(comm) -> int:
    """The communicator's collective epoch (``ft.comm_coll_epoch``):
    the monotone generation every cached collective artifact is fenced
    on.  Lazy import — the FT layer must stay optional at import."""
    from ompi_tpu.mpi import ft as ft_mod

    return ft_mod.comm_coll_epoch(comm)


class StaleCollEpoch(MPIException):
    """A cached collective artifact (arena, hierarchy split, pinned
    persistent slots) was built at an older coll epoch than the
    communicator's current one — a member was revived since, and its
    new life never mapped the old segment (the name was unlinked at
    build).  Raised out of arena waits and caught at the coll/shm slot
    boundary, which tears the state down, rebuilds it with the revived
    rank included, and re-runs the op (no rank can have completed it —
    completion needs the life that never arrived).  Carries
    ``ERR_PROC_FAILED`` so the rare escape (a persistent drain mid-
    transition) flows through the FT retry handlers apps already
    have."""

    def __init__(self, msg: str) -> None:
        super().__init__(msg, error_class=ERR_PROC_FAILED)


#: retry bound for the stale-epoch rebuild loop at the slot boundary —
#: each retry requires an actual epoch advance (another adopted
#: revive), so hitting the bound means a bug, not a hot loop; the final
#: attempt runs unguarded so the raise surfaces
_MAX_REJOIN_RETRIES = 8


def _epoch_retries(fn):
    """Slot-boundary rejoin loop: a mid-op ``StaleCollEpoch`` (an arena
    wait observed the epoch advance past the arena's build) re-enters
    the slot, whose ``_route`` → ``_state`` sees the stale epoch, tears
    down and rebuilds the hierarchy with the revived rank included, and
    re-runs the op on fresh counters.  Safe to re-run: the raise means
    a member's publishes can never arrive in the OLD arena, so no rank
    completed the op; the retried publish lands in the NEW segment
    (fresh counters), never double-bumps the old one."""
    @functools.wraps(fn)
    def run(self, comm, *args, **kw):
        for _ in range(_MAX_REJOIN_RETRIES):
            try:
                return fn(self, comm, *args, **kw)
            except StaleCollEpoch:
                continue
        return fn(self, comm, *args, **kw)

    return run


#: live arenas of this process — the hang doctor's capture walks them
#: for the arrive/depart counter snapshots (the "who hasn't arrived"
#: signal); weak so a closed/garbage-collected arena just disappears
_live_arenas: "weakref.WeakSet" = weakref.WeakSet()


def arena_states() -> list[dict]:
    """Each live arena's counter block as a plain dict — what a doctor
    capture embeds.  Best-effort: a concurrently-detached segment
    contributes nothing rather than raising on a reader thread."""
    out = []
    for a in list(_live_arenas):
        try:
            f = a._flags
            out.append({
                "size": a.size,
                "rank": a.rank,
                "world": list(a.world) if a.world is not None else None,
                "arrive": [int(f[r * 8]) for r in range(a.size)],
                "depart": [int(f[(a.size + r) * 8])
                           for r in range(a.size)],
            })
        except (ValueError, IndexError, OSError):
            continue
    return out


# ---------------------------------------------------------------------------
# the native executor (_native/arena.c via ctypes — every call runs with
# the GIL RELEASED, which is the entire point: a rank parked in a flag
# wait or moving a 64 KiB slot no longer serializes the other in-process
# threads.  Python keeps every policy decision: FT checks, probes, and
# deadlines run between bounded native slices)
# ---------------------------------------------------------------------------

#: spin burst inside one native slice (shared across the native data
#: plane — see _native.PARK_SPINS for the small-host rationale and the
#: measured spin sweep)
_NATIVE_SPINS = _native.PARK_SPINS
#: one park slice: the cadence at which the Python FT contract
#: (revocation, detector-dead, writer pid probe, deadline) re-runs
_NATIVE_SLICE_NS = 2_000_000
#: below this a ctypes call costs more than the GIL-held numpy copy
_NATIVE_PUBLISH_MIN = 512

#: a wait this old records its flight-recorder wait-for edge (one park
#: slice: younger waits are normal publish races, and an entry-time
#: edge could name a laggard that long since arrived)
_WAIT_REC_AFTER_S = _NATIVE_SLICE_NS / 1e9

#: physical parallelism available to cooperative folds (tests patch it)
_NCORES = os.cpu_count() or 1


def _exec():
    """The loaded native arena executor, or None (python data plane).
    The var read is per-call by design: benchmarks flip
    ``coll_shm_native`` mid-world for shared-fate comparisons."""
    if not var_registry.get("coll_shm_native"):
        return None
    return _native.arena()


#: segment-base address helper, shared with the btl ring park
_addr_of = _native.addr_of


def _strided_desc(arr: np.ndarray) -> Optional[tuple[int, int, int]]:
    """Describe ``arr``'s memory as ONE strided progression in C order
    — ``(nblocks, bl, stride)``, the convertor plan ABI's vector-class
    shape — or None when the layout needs a full run walk (the numpy
    path handles those)."""
    if arr.nbytes == 0:
        return None
    if arr.flags.c_contiguous:
        return 1, arr.nbytes, arr.nbytes
    dims = [(s, st) for s, st in zip(arr.shape, arr.strides) if s != 1]
    if not dims:
        return 1, arr.itemsize, arr.itemsize
    bl = arr.itemsize
    while dims and dims[-1][1] == bl:     # collapse the contiguous tail
        bl *= dims[-1][0]
        dims.pop()
    if not dims:
        return 1, bl, bl
    if len(dims) == 1 and dims[0][1] > 0:
        return dims[0][0], bl, dims[0][1]
    return None


#: (dtype.kind, itemsize) → arena.c dtype code (native-endian only)
_FOLD_DTYPE_CODES = {
    ("i", 1): 0, ("i", 2): 1, ("i", 4): 2, ("i", 8): 3,
    ("u", 1): 4, ("u", 2): 5, ("u", 4): 6, ("u", 8): 7,
    ("f", 4): 8, ("f", 8): 9,
}

#: the exact builtin Op OBJECTS the native fold reproduces bit-for-bit
#: (identity keyed: a user create_op named "sum" must NOT match)
_NATIVE_OP_CODES = {op_mod.SUM: 0, op_mod.PROD: 1,
                    op_mod.MIN: 2, op_mod.MAX: 3}


def _fold_code(dtype: np.dtype) -> Optional[int]:
    if not dtype.isnative:
        return None
    return _FOLD_DTYPE_CODES.get((dtype.kind, dtype.itemsize))


def _native_fold(ex, dst_addr: int, src_addrs: list, nelems: int,
                 dtype_code: int, op_code: int) -> None:
    """One GIL-released rank-ordered elementwise fold; raises on a
    contract violation (caller pre-validated the codes)."""
    srcs = (ctypes.c_void_p * len(src_addrs))(*src_addrs)
    rc = ex.ompi_tpu_arena_fold(dst_addr, ctypes.addressof(srcs),
                                len(src_addrs), nelems, dtype_code,
                                op_code)
    if rc != 0:
        raise MPIException(
            f"coll/shm: native fold rejected pre-validated plan "
            f"(dtype code {dtype_code}, op code {op_code})")
    trace_mod.count("coll_shm_native_folds_total")


def decide_allreduce_algo(comm, nbytes: int) -> tuple[str, str]:
    """The arena-allreduce fold strategy, resolved by the standard
    selection ladder (forced var > rules file > fixed crossover):

    - ``root_fold``         — one rank folds every slot (the historic
      path; optimal while the fold is cheaper than a second rendezvous)
    - ``segment_parallel``  — every rank reduce-scatters its 1/p
      segment across all slots, then allgathers through the result
      slot: O(n) fold work per rank instead of O(p·n) on one rank.
      The AGGREGATE fold work is unchanged (p·n reads either way), so
      spreading it only pays when the ranks can actually fold
      concurrently — the fixed crossover therefore requires BOTH a
      payload above ``coll_shm_segpar_min`` AND cores >= ranks (PR 10
      measured the python variant losing from spinner interference;
      with the native executor the spinners are gone, but a 1-2 core
      box still has no spare core to fold on, and the measured result
      there is parity-at-best — PERF.md "Segment-parallel allreduce").
      A rules-file hit or the forced var overrides the core gate: the
      operator knows their box.

    Returns ``(algorithm, source)``.
    """
    forced = str(var_registry.get("coll_shm_allreduce_algorithm") or "")
    path = str(var_registry.get("coll_host_dynamic_rules") or "")
    alg, src = rules.decide(rules.SHM_ALLREDUCE, comm.size, nbytes,
                            forced=forced, path=path,
                            valid=rules.SHM_ALLREDUCE_ALGORITHMS)
    if alg is None:
        crossover = int(var_registry.get("coll_shm_segpar_min") or 0)
        alg = ("segment_parallel"
               if crossover and nbytes >= crossover
               and 2 <= comm.size <= _NCORES
               else "root_fold")
        src = (f"fixed crossover (coll_shm_segpar_min={crossover}, "
               f"{comm.size} ranks on {_NCORES} cores)")
    return alg, src


_grace_warned = False


def _probe_grace(timeout: float) -> float:
    """Validated writer-probe grace: must sit strictly inside the
    coll_shm_timeout fallback deadline (a grace at or past the timeout
    would disable the probe exactly when it matters) — clamped to half
    the timeout with a one-time warning, the same hygiene rule the
    heartbeat/gossip windows apply."""
    global _grace_warned
    grace = float(var_registry.get("coll_shm_probe_grace") or 0)
    if grace <= 0:
        return 0.0
    if grace >= timeout:
        if not _grace_warned:
            _grace_warned = True
            _log.verbose(0, "coll/shm: probe grace %.1fs >= timeout "
                         "%.1fs; clamping to %.1fs", grace, timeout,
                         timeout / 2)
        grace = timeout / 2
    return grace


def _desc_dtype_ok(dtype: np.dtype) -> bool:
    """Reconstructible from the 32-byte descriptor field: extension
    dtypes (bfloat16 & co.) stringify to a raw void ('<V2') that would
    NOT round-trip — bcast must ship those via coll/host, whose wire
    headers carry the real dtype."""
    try:
        return len(dtype.str) <= 32 and np.dtype(dtype.str) == dtype
    except Exception:  # noqa: BLE001 — unparseable str: not shippable
        return False


class Arena:
    """One mapped per-communicator arena; ranks are arena slot indices.

    Every wait is ``flags[i] >= v`` on monotonic counters, so the
    protocol is ABA-free by construction; each collective advances
    every rank's arrive (and depart, where used) by the same amount,
    keeping the counters equal at op boundaries — the invariant all
    thresholds are computed from.
    """

    def __init__(self, seg: shmseg.SharedSegment, size: int, rank: int,
                 slot_bytes: int, world=None, pml=None,
                 fence=None) -> None:
        self.seg = seg
        self.size = size
        self.rank = rank
        self.slot_bytes = slot_bytes
        # coll-epoch fence: (epoch this arena was built/bound at, weakref
        # to the comm the epoch is scoped to — the PARENT comm for hier
        # node arenas, so a revive anywhere in the hierarchy breaks the
        # wait).  None ⇒ unfenced (bare test arenas, no FT plane).
        self._fence = fence
        # this rank's WORLD rank (the flight recorder / doctor key; the
        # arena index is node-local)
        self._wr = (pml.rank if pml is not None
                    else (list(world)[rank] if world is not None
                          else rank))
        # arena rank → world rank, plus the pml whose btl owns the
        # pid-liveness probe: a writer dying between flag stores leaves
        # peers nothing to observe but its pid, so the wait loop probes
        # the expected writer after a short grace instead of spinning out
        # the full coll_shm_timeout
        self.world = list(world) if world is not None else None
        self._pml = pml
        self.half = (slot_bytes // 2) & ~7
        self._flags = seg.buf[:2 * size * _CACHELINE].cast("Q")
        self._desc_base = 2 * size * _CACHELINE
        self._slot_base = self._desc_base + size * _DESC
        self._arr = 0   # my arrive counter (mirror of the mapped value)
        self._dep = 0   # my depart counter
        # segment base address for the native executor (flag word i of
        # the mapped u64 view is base + i*8, slot offsets are relative
        # to the same base); None ⇒ python data plane only
        self._base_addr = _addr_of(seg.buf)
        _live_arenas.add(self)   # doctor capture reads arrive/depart

    @staticmethod
    def nbytes_for(size: int, slot_bytes: int) -> int:
        return (2 * size * _CACHELINE + size * _DESC
                + (size + 1) * slot_bytes)

    def close(self) -> None:
        try:
            self._flags.release()
        except (BufferError, ValueError):
            pass
        self.seg.detach()

    # -- flags -------------------------------------------------------------

    def _set_arrive(self, v: int) -> None:
        self._flags[self.rank * 8] = v
        self._arr = v
        self._wake(self.rank * 8)

    def _set_depart(self, v: int) -> None:
        self._flags[(self.size + self.rank) * 8] = v
        self._dep = v
        self._wake((self.size + self.rank) * 8)

    def _wake(self, idx: int) -> None:
        """Futex-wake any native waiter parked on flag ``idx`` — every
        python-side flag store pairs with one so the futex park wakes
        at store time, not at its bounded-timeout backstop.  (Native
        publishes fuse the wake into the same GIL-released call.)"""
        if self._base_addr is None:
            return
        ex = _exec()
        if ex is not None:
            ex.ompi_tpu_arena_wake(self._base_addr, idx)

    # on a 1-2 core host every spin iteration steals the flag-writer's
    # quantum (the btl/shm poller disables its spin window there for the
    # same reason) — escalate to micro-sleeps almost immediately
    _SPIN_MASK = 0xFF if (os.cpu_count() or 1) > 2 else 0xF

    def _wait(self, idx: int, v: int, comm) -> None:
        f = self._flags
        if f[idx] >= v:
            return
        # the straggler signal: every ns burnt in here is this rank
        # waiting on a PEER's flag store — recorded into the arena-wait
        # histogram on completed waits (an already-satisfied flag never
        # reaches this point, so the fast path stays one compare).  The
        # slow paths below additionally record a flight-recorder
        # ``wait`` event naming the world rank whose store the wait is
        # parked on (the hang doctor's wait-for edge) — AFTER the wait
        # has survived ~one park slice, so transient publish races
        # cannot fabricate stale mutual edges (a fake deadlock cycle)
        _h_t0 = time.monotonic_ns() if trace_mod.hist_active else 0
        ex = _exec() if self._base_addr is not None else None
        if ex is not None:
            self._park_native(ex, v, comm, idx=idx)
        else:
            self._wait_py(idx, v, comm)
        if _h_t0 and trace_mod.hist_active:
            trace_mod.record_hist("coll_arena_wait_ns",
                                  time.monotonic_ns() - _h_t0)

    def _wait_py(self, idx: int, v: int, comm) -> None:
        """The pure-python park (native executor off/unavailable)."""
        f = self._flags
        timeout = float(var_registry.get("coll_shm_timeout") or 60)
        grace = _probe_grace(timeout) if (self.world is not None
                                          and self._pml is not None) else 0.0
        now = time.monotonic()
        deadline = now + timeout
        probe_at = now + grace if grace > 0 else None
        stuck_at = self._stuck_at(now)
        rec_at: Optional[float] = now + _WAIT_REC_AFTER_S
        spins = 0
        delay = 2e-5
        while f[idx] < v:
            spins += 1
            if spins & self._SPIN_MASK:
                time.sleep(0)       # yield (in-process ranks share the GIL)
                continue
            time.sleep(delay)       # escalate once the burst window passed
            delay = min(delay * 2, 1e-3)
            if rec_at is not None and time.monotonic() > rec_at:
                rec_at = self._record_wait(comm, idx // 8,
                                           (idx // 8) % self.size, v)
            if comm is not None:
                self._check_ft(comm)
            if probe_at is not None and time.monotonic() > probe_at:
                # the probe itself is rate-limited (shared btl cache), so
                # asking every escalated iteration stays cheap
                self._probe_writer((idx // 8) % self.size, grace, timeout)
            if stuck_at is not None and time.monotonic() > stuck_at:
                stuck_at = self._report_stuck(
                    comm, time.monotonic() - (deadline - timeout),
                    (idx // 8) % self.size)
            if time.monotonic() > deadline:
                raise MPIException(
                    f"coll/shm: arena wait (flag {idx // 8}, want {v}, "
                    f"have {int(f[idx])}) stuck for {timeout:.0f}s on "
                    f"{getattr(comm, 'name', '?')} — peer dead or "
                    f"collective-order mismatch (coll_shm_timeout)")

    def _park_native(self, ex, v: int, comm, idx: Optional[int] = None,
                     all_base: Optional[int] = None) -> None:
        """GIL-released park: bounded native slices (spin burst +
        escalating naps in C, no interpreter involvement) with the FULL
        python-loop FT contract re-run between slices — revocation and
        detector-dead checks, the dead-writer pid probe after the
        grace, and the coll_shm_timeout deadline, all at the same
        ~slice cadence the escalated python loop reached them."""
        trace_mod.count("coll_shm_native_waits_total")
        timeout = float(var_registry.get("coll_shm_timeout") or 60)
        grace = _probe_grace(timeout) if (self.world is not None
                                          and self._pml is not None) else 0.0
        now = time.monotonic()
        deadline = now + timeout
        probe_at = now + grace if grace > 0 else None
        stuck_at = self._stuck_at(now)
        recorded = False
        base = self._base_addr
        while True:
            if all_base is None:
                done = ex.ompi_tpu_arena_wait(
                    base, idx, v, _NATIVE_SPINS, _NATIVE_SLICE_NS)
            else:
                done = ex.ompi_tpu_arena_wait_all(
                    base, all_base, 8, self.size, v, _NATIVE_SPINS,
                    _NATIVE_SLICE_NS)
            if done:
                return
            if comm is not None:
                self._check_ft(comm)
            lag = self._laggard(v, idx=idx, all_base=all_base)
            if not recorded:
                # the wait outlived a whole park slice: record the edge
                # with the laggard as of NOW (not wait entry — the
                # entry-time laggard may have long since arrived)
                recorded = True
                flag = (idx if all_base is None
                        else all_base + lag * 8) // 8
                self._record_wait(comm, flag, lag % self.size, v)
            if probe_at is not None and time.monotonic() > probe_at:
                self._probe_writer(lag % self.size, grace, timeout)
            if stuck_at is not None and time.monotonic() > stuck_at:
                stuck_at = self._report_stuck(
                    comm, time.monotonic() - (deadline - timeout),
                    lag % self.size)
            if time.monotonic() > deadline:
                f = self._flags
                flag = idx if all_base is None else all_base + lag * 8
                raise MPIException(
                    f"coll/shm: arena wait (flag {flag // 8}, want {v}, "
                    f"have {int(f[flag])}) stuck for {timeout:.0f}s on "
                    f"{getattr(comm, 'name', '?')} — peer dead or "
                    f"collective-order mismatch (coll_shm_timeout)")

    def _laggard(self, v: int, idx: Optional[int] = None,
                 all_base: Optional[int] = None) -> int:
        """Arena rank whose flag a stalled wait is parked on (the pid
        the probe should ask about)."""
        if all_base is None:
            return (idx // 8) % self.size
        f = self._flags
        for r in range(self.size):
            if f[all_base + r * 8] < v:
                return r
        return 0

    def _record_wait(self, comm, flag: int, lag: int, v: int) -> None:
        """One flight-recorder ``wait`` edge naming the current laggard
        (called once per wait, after it survived ~a park slice).
        Returns None — the caller's record-once sentinel."""
        trace_mod.coll_event(
            self._wr, comm.cid if comm is not None else -1, "wait",
            {"flag": flag, "want": v,
             "on": self.world[lag] if self.world is not None else lag})
        return None

    def _stuck_at(self, now: float) -> Optional[float]:
        """When this wait should push a stuck event up the uplink
        (None = watchdog disabled via coll_stuck_timeout 0)."""
        stuck = float(var_registry.get("coll_stuck_timeout") or 0)
        return now + stuck if stuck > 0 else None

    def _report_stuck(self, comm, waited_s: float,
                      lag: int) -> Optional[float]:
        """The watchdog fired: record a stuck event naming the laggard
        and force a metrics push (once per wait — returns the cleared
        re-arm sentinel)."""
        trace_mod.coll_stuck(
            self._wr, comm.cid if comm is not None else -1, waited_s,
            self.world[lag] if self.world is not None else lag)
        return None

    def _wait_many(self, all_base: int, v: int, comm) -> None:
        """Wait flag[all_base + r*8] >= v for every arena rank — ONE
        native call when the executor is live, the per-flag python
        loop otherwise."""
        f = self._flags
        r0 = 0
        while r0 < self.size and f[all_base + r0 * 8] >= v:
            r0 += 1
        if r0 >= self.size:
            return
        ex = _exec() if self._base_addr is not None else None
        if ex is None:
            for r in range(r0, self.size):
                self._wait(all_base + r * 8, v, comm)
            return
        _h_t0 = time.monotonic_ns() if trace_mod.hist_active else 0
        self._park_native(ex, v, comm, all_base=all_base)
        if _h_t0 and trace_mod.hist_active:
            trace_mod.record_hist("coll_arena_wait_ns",
                                  time.monotonic_ns() - _h_t0)

    def _probe_writer(self, writer: int, grace: float,
                      timeout: float) -> None:
        """The expected writer's flag has not moved past the grace: ask
        the btl pid-liveness probe (cache shared with the send path —
        one kill(2) per peer per 50ms across all layers) whether the pid
        still exists, and fail the collective in ~the grace window
        instead of the full coll_shm_timeout when it does not."""
        if writer == self.rank:
            return
        w = self.world[writer]
        ep = getattr(self._pml, "endpoint", None)
        if ep is None or ep.peer_alive(w) is not False:
            return
        trace_mod.count("coll_shm_writer_dead_total")
        reason = "coll/shm: writer pid gone mid-collective (arena probe)"
        ft = getattr(self._pml, "ft", None)
        if ft is not None:
            # same dead-set the PMIx path feeds: posted recvs, parked
            # sends, and every later arena wait fail fast too
            if ft.detector.mark_failed(w, reason):
                # ...and the same control-plane push the gossip path
                # makes: under errmgr selfheal the runtime reaps the
                # corpse and revives it (the probe is a detection
                # source of the full recovery cycle, not a local
                # verdict), and every other rank's poll learns the
                # death even with its own probes cold.  getattr: test
                # harnesses install minimal detector stubs.
                report = getattr(ft.detector, "report_to_runtime", None)
                if report is not None:
                    # adopted_inc, not _peer_inc: a transitive adopter's
                    # stamp must carry the gossip-adopted life too, or
                    # its report about a wedged life is stale-gated
                    # forever (getattr: minimal test stubs)
                    inc = getattr(ft, "adopted_inc", None)
                    report(w, reason, inc(w) if inc is not None else 0)
        from ompi_tpu.mpi.constants import ERR_PROC_FAILED

        raise MPIException(
            f"coll/shm: rank {w} (arena writer) died mid-collective — "
            f"pid probe after {grace:.1f}s grace, not the "
            f"{timeout:.0f}s coll_shm_timeout", error_class=ERR_PROC_FAILED)

    def _check_ft(self, comm) -> None:
        """Arena waits bypass the PML, so they must reproduce its
        fail-fast discipline themselves: a revoked communicator or a
        detector-declared-dead member raises instead of spinning out
        the full coll_shm_timeout (the ULFM recovery paths depend on
        collectives failing promptly).  The coll-epoch fence rides the
        same cadence: a wait parked against a peer that was revived
        since this arena was built can never be satisfied (the new life
        never mapped the unlinked segment) — the moment this process
        adopts the new incarnation, the wait raises StaleCollEpoch and
        the slot boundary rebuilds the hierarchy instead of spinning
        out the timeout."""
        if comm.is_revoked():
            from ompi_tpu.mpi.constants import ERR_REVOKED

            raise MPIException(
                f"coll/shm: {comm.name} revoked mid-collective",
                error_class=ERR_REVOKED)
        ft = getattr(comm.pml, "ft", None)
        if ft is not None:
            for w in comm.group.ranks:
                if ft.detector.is_dead(w, poll=False):
                    raise MPIException(
                        f"coll/shm: rank {w} failed mid-collective "
                        f"({ft.detector.reason(w) or 'detector-declared'})",
                        error_class=ERR_PROC_FAILED)
        fence = self._fence
        if fence is not None:
            epoch, cref = fence
            fc = cref()
            if fc is not None and _coll_epoch(fc) > epoch:
                raise StaleCollEpoch(
                    f"coll/shm: arena wait on "
                    f"{getattr(comm, 'name', '?')} fenced — a member "
                    f"was revived since the arena was built (coll "
                    f"epoch {_coll_epoch(fc)} > built {epoch}); the "
                    f"hierarchy rebuilds on retry")

    def _wait_arrive(self, r: int, v: int, comm) -> None:
        self._wait(r * 8, v, comm)

    def _wait_depart(self, r: int, v: int, comm) -> None:
        self._wait((self.size + r) * 8, v, comm)

    def _wait_all_arrive(self, v: int, comm) -> None:
        self._wait_many(0, v, comm)

    def _wait_all_depart(self, v: int, comm) -> None:
        self._wait_many(self.size * 8, v, comm)

    # -- slots / descriptors ------------------------------------------------

    def _slot_off(self, i: int) -> int:
        return self._slot_base + i * self.slot_bytes

    def _slot(self, i: int) -> memoryview:
        off = self._slot_off(i)
        return self.seg.buf[off:off + self.slot_bytes]

    # -- native data movement ------------------------------------------------

    def _publish_native(self, dst_off: int, arr: np.ndarray, fidx: int,
                        fval: int) -> bool:
        """Slot copy + release flag store fused into ONE GIL-released
        call (strided sources ride the convertor plan ABI's vector
        shape).  False ⇒ the caller runs the numpy copy + python flag
        store — exotic layouts and sub-threshold payloads, where the
        ctypes call would cost more than it frees."""
        if arr.nbytes < _NATIVE_PUBLISH_MIN or self._base_addr is None:
            return False
        ex = _exec()
        if ex is None:
            return False
        desc = _strided_desc(arr)
        if desc is None:
            return False
        nblocks, bl, stride = desc
        dst = self._base_addr + dst_off
        if nblocks == 1:
            ex.ompi_tpu_arena_publish(dst, arr.ctypes.data, arr.nbytes,
                                      self._base_addr, fidx, fval)
        else:
            ex.ompi_tpu_arena_publish_strided(
                dst, arr.ctypes.data, nblocks, bl, stride,
                self._base_addr, fidx, fval)
        trace_mod.count("coll_shm_native_publishes_total")
        return True

    def _publish_arrive(self, dst_off: int, arr: np.ndarray,
                        v: int) -> bool:
        """Native publish stamped with MY arrive counter (mirror kept
        in sync); False ⇒ caller copies + ``_set_arrive`` itself."""
        if self._publish_native(dst_off, arr, self.rank * 8, v):
            self._arr = v
            return True
        return False

    def _copy_out_native(self, src_off: int, dst: np.ndarray) -> bool:
        """Mapped slot → caller buffer as one GIL-released copy (the
        drain-side mirror of ``_publish_native``, no flag store)."""
        if (dst.nbytes < _NATIVE_PUBLISH_MIN or self._base_addr is None
                or not dst.flags.c_contiguous):
            return False
        ex = _exec()
        if ex is None:
            return False
        ex.ompi_tpu_arena_publish(dst.ctypes.data,
                                  self._base_addr + src_off, dst.nbytes,
                                  None, 0, 0)
        return True

    def _write_desc(self, code: int, arr: Optional[np.ndarray],
                    nseg: int) -> None:
        off = self._desc_base + self.rank * _DESC
        head = np.zeros(12, np.uint64)
        head[0] = code
        dts = b""
        if arr is not None:
            head[1] = arr.nbytes
            head[2] = nseg
            head[3] = arr.ndim
            head[4:4 + arr.ndim] = np.array(arr.shape, np.uint64)
            dts = arr.dtype.str.encode()
        self.seg.buf[off:off + 96] = head.tobytes()
        self.seg.buf[off + 96:off + _DESC] = dts.ljust(32, b"\0")

    def _read_desc(self, r: int):
        off = self._desc_base + r * _DESC
        head = np.frombuffer(self.seg.buf[off:off + 96], np.uint64)
        code, nbytes, nseg, ndim = (int(head[0]), int(head[1]),
                                    int(head[2]), int(head[3]))
        shape = tuple(int(x) for x in head[4:4 + ndim])
        raw = bytes(self.seg.buf[off + 96:off + _DESC]).rstrip(b"\0")
        dtype = np.dtype(raw.decode()) if raw else np.dtype(np.uint8)
        return code, nbytes, nseg, shape, dtype

    @staticmethod
    def _copy_in(dst_mv: memoryview, arr: np.ndarray) -> None:
        """THE send-side copy: user buffer → mapped slot.  Strided
        sources walk directly (numpy is the run engine — no staging)."""
        if arr.nbytes == 0:
            return
        dst = np.frombuffer(dst_mv, dtype=arr.dtype, count=arr.size)
        np.copyto(dst.reshape(arr.shape), arr, casting="no")

    # -- barrier -------------------------------------------------------------

    def barrier(self, comm) -> None:
        s = self._arr + 1
        self._set_arrive(s)
        self._wait_all_arrive(s, comm)

    def gate_in(self, comm, nroot: int = 0) -> None:
        """Fan-in half of a hierarchical barrier: everyone signals
        arrival, only the gate root waits for all of them."""
        s = self._arr + 1
        self._set_arrive(s)
        if self.rank == nroot:
            self._wait_all_arrive(s, comm)

    def gate_out(self, comm, nroot: int = 0) -> None:
        """Release half: the gate root signals, everyone else waits."""
        s = self._dep + 1
        if self.rank == nroot:
            self._set_depart(s)
        else:
            self._wait_depart(nroot, s, comm)
            self._set_depart(s)

    # -- bcast ---------------------------------------------------------------

    def bcast(self, comm, nroot: int, buf, cap: int) -> Optional[np.ndarray]:
        """Single-copy fan-out, pipelined through the root slot's halves.
        Returns None on every rank when the root judged the payload
        host-bound (oversized/unsupported) — the verdict travels in the
        descriptor, so non-roots (who cannot see the payload) take the
        same branch with no extra exchange."""
        if self.rank == nroot:
            arr = np.asarray(buf)
            ok = (_arena_dtype_ok(arr.dtype) and arr.ndim <= _MAX_DIMS
                  and _desc_dtype_ok(arr.dtype) and arr.nbytes <= cap)
            nseg = max(1, -(-arr.nbytes // self.half)) if ok else 1
            self._write_desc(_DESC_DATA if ok else _DESC_HOST,
                             arr if ok else None, nseg)
            s0 = self._arr
            if not ok:
                self._set_arrive(s0 + 1)
                self._wait_all_arrive(s0 + 1, comm)
                return None
            u8 = (arr if arr.flags.c_contiguous
                  else np.ascontiguousarray(arr)).reshape(-1).view(np.uint8)
            slot = self._slot(nroot)
            for k in range(nseg):
                if k >= 2:   # readers done with the previous half occupant
                    self._wait_all_arrive(s0 + k - 1, comm)
                lo = k * self.half
                hi = min(lo + self.half, arr.nbytes)
                hoff = (k % 2) * self.half
                if not self._publish_arrive(self._slot_off(nroot) + hoff,
                                            u8[lo:hi], s0 + k + 1):
                    slot[hoff:hoff + hi - lo] = u8[lo:hi].data
                    self._set_arrive(s0 + k + 1)
            self._wait_all_arrive(s0 + nseg, comm)
            return arr
        s0 = self._arr
        self._wait_arrive(nroot, s0 + 1, comm)
        code, nbytes, nseg, shape, dtype = self._read_desc(nroot)
        if code == _DESC_HOST:
            self._set_arrive(s0 + 1)
            return None
        out = np.empty(nbytes, np.uint8)
        slot = self._slot(nroot)
        for k in range(nseg):
            self._wait_arrive(nroot, s0 + k + 1, comm)
            lo = k * self.half
            hi = min(lo + self.half, nbytes)
            hoff = (k % 2) * self.half
            if not self._copy_out_native(self._slot_off(nroot) + hoff,
                                         out[lo:hi]):
                out[lo:hi] = np.frombuffer(slot[hoff:hoff + hi - lo],
                                           np.uint8)
            self._set_arrive(s0 + k + 1)
        return out.view(dtype).reshape(shape)

    # -- reduce / allreduce --------------------------------------------------

    def reduce(self, comm, nroot: int, arr: np.ndarray, op: Op,
               bcast_result: bool) -> Optional[np.ndarray]:
        """Rank-ordered fan-in at ``nroot`` folding *views of the mapped
        slots* (zero read copies), pipelined through slot halves;
        ``bcast_result`` adds the fan-out phase (allreduce).  The caller
        pre-validated op commutativity, dtype, and the arena cap — those
        checks use globally-agreed inputs, so every rank gets here (or
        not) together."""
        arr = np.asarray(arr)
        dtype, itemsize = arr.dtype, arr.dtype.itemsize
        n = arr.size
        seg_elems = max(1, self.half // itemsize)
        nseg = max(1, -(-n // seg_elems))
        s0a, s0d = self._arr, self._dep
        me = self.rank
        myslot = self._slot(me)
        res = self._slot(self.size)
        flat = None
        if nseg > 1:
            flat = (arr if arr.flags.c_contiguous
                    else np.ascontiguousarray(arr)).reshape(-1)

        # native fold eligibility, resolved once per op: builtin op
        # (identity match) + native-endian fixed width + a payload the
        # ctypes call amortizes over
        ex = _exec() if self._base_addr is not None else None
        dc = _fold_code(dtype) if ex is not None else None
        oc = _NATIVE_OP_CODES.get(op) if ex is not None else None
        nat_fold = (dc is not None and oc is not None
                    and arr.nbytes >= _NATIVE_PUBLISH_MIN)

        def seg_bounds(k: int):
            lo = k * seg_elems
            hi = min(lo + seg_elems, n)
            return lo, hi, (k % 2) * self.half

        def publish_my_seg(k: int, v: int) -> None:
            lo, hi, hoff = seg_bounds(k)
            src = arr if nseg == 1 else flat[lo:hi]
            if self._publish_arrive(self._slot_off(me) + hoff, src, v):
                return
            dst = myslot[hoff:hoff + (hi - lo) * itemsize]
            if nseg == 1:
                self._copy_in(dst, arr)   # strided sources walk directly
            else:
                np.copyto(np.frombuffer(dst, dtype, count=hi - lo),
                          flat[lo:hi], casting="no")
            self._set_arrive(v)

        if me == nroot:
            out = np.empty(n, dtype)
            for k in range(nseg):
                lo, hi, hoff = seg_bounds(k)
                publish_my_seg(k, s0a + k + 1)
                self._wait_all_arrive(s0a + k + 1, comm)
                if bcast_result and k >= 2:
                    # readers finished with this result half's previous
                    # occupant (segment k-2) — must precede the result
                    # write, which the native fold lands directly
                    self._wait_all_depart(s0d + k - 1, comm)
                count = hi - lo
                if nat_fold:
                    # rank-ordered fold straight over the mapped slots,
                    # GIL released — into the result slot (allreduce) or
                    # the root's output buffer
                    if bcast_result:
                        dst_addr = (self._base_addr
                                    + self._slot_off(self.size) + hoff)
                    else:
                        dst_addr = out.ctypes.data + lo * itemsize
                    _native_fold(
                        ex, dst_addr,
                        [self._base_addr + self._slot_off(i) + hoff
                         for i in range(self.size)], count, dc, oc)
                    if bcast_result:
                        # read the root's own copy back GIL-released
                        # too (same helper as every other drain site)
                        if not self._copy_out_native(
                                self._slot_off(self.size) + hoff,
                                out[lo:hi]):
                            out[lo:hi] = np.frombuffer(
                                res[hoff:hoff + count * itemsize],
                                dtype)
                else:
                    # fold straight from the mapped slots, in rank order
                    acc = np.frombuffer(self._slot(0)[hoff:], dtype,
                                        count=count)
                    for i in range(1, self.size):
                        acc = op.host(acc, np.frombuffer(
                            self._slot(i)[hoff:], dtype, count=count))
                    acc = np.asarray(acc)
                    out[lo:hi] = acc.reshape(-1)
                    if bcast_result:
                        np.copyto(np.frombuffer(res[hoff:], dtype,
                                                count=count), acc,
                                  casting="no")
                self._set_depart(s0d + k + 1)
            if bcast_result:
                self._wait_all_depart(s0d + nseg, comm)
            return out.reshape(arr.shape).astype(dtype, copy=False)
        # non-root: publish segments one ahead of the root's fold, and
        # (for allreduce) drain result segments one behind it
        out = np.empty(n, dtype) if bcast_result else None
        res_off = self._slot_off(self.size)
        for k in range(nseg):
            if not bcast_result and k >= 2:
                self._wait_depart(nroot, s0d + k - 1, comm)
            publish_my_seg(k, s0a + k + 1)
            if bcast_result and k >= 1:
                lo, hi, hoff = seg_bounds(k - 1)
                self._wait_depart(nroot, s0d + k, comm)
                if not self._copy_out_native(res_off + hoff, out[lo:hi]):
                    out[lo:hi] = np.frombuffer(res[hoff:], dtype,
                                               count=hi - lo)
                self._set_depart(s0d + k)
        self._wait_depart(nroot, s0d + nseg, comm)
        if bcast_result:
            lo, hi, hoff = seg_bounds(nseg - 1)
            if not self._copy_out_native(res_off + hoff, out[lo:hi]):
                out[lo:hi] = np.frombuffer(res[hoff:], dtype,
                                           count=hi - lo)
        self._set_depart(s0d + nseg)
        return out.reshape(arr.shape) if bcast_result else None

    # -- allgather -----------------------------------------------------------

    def allgather(self, comm, arr: np.ndarray) -> np.ndarray:
        """Everyone publishes a slot, everyone copies all slots; result
        indexed by arena rank.  Caller checked nbytes <= slot_bytes."""
        arr = np.asarray(arr)
        s0a, s0d = self._arr, self._dep
        if not self._publish_arrive(self._slot_off(self.rank), arr,
                                    s0a + 1):
            self._copy_in(self._slot(self.rank)[:max(arr.nbytes, 1)], arr)
            self._set_arrive(s0a + 1)
        self._wait_all_arrive(s0a + 1, comm)
        out = np.empty((self.size,) + arr.shape, arr.dtype)
        rows = out.reshape(self.size, -1)
        for i in range(self.size):
            if not self._copy_out_native(self._slot_off(i), rows[i]):
                src = np.frombuffer(self._slot(i), arr.dtype,
                                    count=arr.size)
                out[i] = src.reshape(arr.shape)
        self._set_depart(s0d + 1)
        self._wait_all_depart(s0d + 1, comm)
        return out

    # -- dense exchange ------------------------------------------------------
    #
    # alltoall/v, reduce_scatter and scan/exscan share ONE protocol
    # round: every rank publishes its whole payload into its own slot
    # (one copy), waits for all arrivals, then reads/folds exactly the
    # bytes addressed to it straight out of the mapped peer slots — the
    # p² small PML frames of the pairwise loops collapse into p slot
    # publishes plus per-rank strided reads, all through the same
    # arrive/depart counters (and the same FT fail-fast waits) the
    # fan-out collectives ride.

    def _publish_slot(self, comm, arr: np.ndarray, v: int) -> None:
        """Whole-payload publish into MY slot stamped arrive=v — the
        fused native publish when eligible, numpy copy + python flag
        store otherwise (the allgather discipline, factored out for the
        dense family)."""
        if not self._publish_arrive(self._slot_off(self.rank), arr, v):
            self._copy_in(self._slot(self.rank), arr)
            self._set_arrive(v)

    def _copy_blocks_native(self, dsts: list, srcs: list, lens: list,
                            fidx: Optional[int] = None,
                            fval: int = 0) -> bool:
        """N scattered (dst, src, len) copies as ONE GIL-released call,
        optionally fused with a release arrive store + wake.  False ⇒
        the caller runs the per-block numpy path (executor off, or a
        total payload the ctypes crossing would not amortize).  Callers
        pass absolute addresses (``_base_addr`` pre-checked)."""
        ex = _exec()
        if ex is None or sum(lens) < _NATIVE_PUBLISH_MIN:
            return False
        n = len(dsts)
        da = (ctypes.c_void_p * n)(*dsts)
        sa = (ctypes.c_void_p * n)(*srcs)
        ln = (ctypes.c_int64 * n)(*lens)
        ex.ompi_tpu_arena_copy_blocks(
            ctypes.addressof(da), ctypes.addressof(sa),
            ctypes.addressof(ln), n,
            self._base_addr if fidx is not None else None,
            fidx if fidx is not None else 0, fval)
        trace_mod.count("coll_shm_native_publishes_total")
        return True

    def _fold_slots(self, dtype: np.dtype, op: Op, lo: int, hi: int,
                    order: list) -> np.ndarray:
        """Chain-fold elements [lo, hi) of the listed slots, in list
        order — native when eligible (bit-identical chain, GIL
        released), the rank-ordered numpy chain otherwise.  The order
        list is the CALLER's (comm-rank chain for reduce_scatter, the
        0..r prefix for scan), so non-commutative prefix folds stay
        order-correct."""
        count = hi - lo
        if count <= 0:
            return np.empty(0, dtype)
        boff = lo * dtype.itemsize
        ex = _exec() if self._base_addr is not None else None
        dc = _fold_code(dtype) if ex is not None else None
        oc = _NATIVE_OP_CODES.get(op) if ex is not None else None
        if (dc is not None and oc is not None
                and count * dtype.itemsize >= _NATIVE_PUBLISH_MIN):
            out = np.empty(count, dtype)
            _native_fold(ex, out.ctypes.data,
                         [self._base_addr + self._slot_off(j) + boff
                          for j in order], count, dc, oc)
            return out
        acc = np.frombuffer(self._slot(order[0])[boff:], dtype,
                            count=count)
        for j in order[1:]:
            acc = np.asarray(op.host(acc, np.frombuffer(
                self._slot(j)[boff:], dtype, count=count)))
        # a single-source chain aliases the mapped slot — copy before
        # the depart barrier releases it for reuse
        return np.array(acc, copy=True).reshape(-1)

    def alltoall(self, comm, arr: np.ndarray) -> np.ndarray:
        """``arr`` = p equal blocks (C order) keyed by DEST arena rank;
        returns ``(p, block)`` rows keyed by SRC arena rank.  One
        publish per rank; the gather side reads its column out of every
        peer slot as one native block plan.  Caller checked
        divisibility, dtype and nbytes <= slot_bytes."""
        arr = np.asarray(arr)
        p = self.size
        blk = arr.size // p
        bb = blk * arr.dtype.itemsize
        s0a, s0d = self._arr, self._dep
        self._publish_slot(comm, arr, s0a + 1)
        self._wait_all_arrive(s0a + 1, comm)
        out = np.empty((p, blk), arr.dtype)
        moff = self.rank * bb
        rows = out.reshape(p, -1)
        done = False
        if self._base_addr is not None and bb:
            done = self._copy_blocks_native(
                [out.ctypes.data + i * bb for i in range(p)],
                [self._base_addr + self._slot_off(i) + moff
                 for i in range(p)], [bb] * p)
        if not done:
            for i in range(p):
                rows[i] = np.frombuffer(self._slot(i)[moff:moff + bb],
                                        arr.dtype, count=blk)
        self._set_depart(s0d + 1)
        self._wait_all_depart(s0d + 1, comm)
        return out

    def alltoallv(self, comm, parts: list) -> Optional[list]:
        """``parts``: one array per DEST arena rank (None ⇒ empty).
        Per-dest header entries (length, offset, shape, dtype) lead the
        packed blocks in each slot, so readers address exactly their
        block.  The fits/describable verdict travels in the descriptor
        round — ANY host verdict makes every rank return None together
        (the bcast communicated-verdict discipline, generalized to all
        writers: v-counts are per-rank knowledge, so no local gate is
        collectively safe).  Returns received arrays keyed by SRC arena
        rank, dtype/shape preserved like the pairwise wire."""
        p = self.size
        parts = [np.empty(0, np.uint8) if a is None else np.asarray(a)
                 for a in parts]
        hdr = p * _VHDR
        offs, off = [], hdr
        for a in parts:
            offs.append(off)
            off += (a.nbytes + 7) & ~7
        ok = (off <= self.slot_bytes
              and all(_arena_dtype_ok(a.dtype) and _desc_dtype_ok(a.dtype)
                      and a.ndim <= _MAX_DIMS for a in parts))
        s0a, s0d = self._arr, self._dep
        self._write_desc(_DESC_DATA if ok else _DESC_HOST, None, 0)
        if not ok:
            self._set_arrive(s0a + 1)
        else:
            head = np.zeros(hdr, np.uint8)
            hu = head.view(np.uint64).reshape(p, _VHDR // 8)
            for i, a in enumerate(parts):
                hu[i, 0] = a.nbytes
                hu[i, 1] = offs[i]
                hu[i, 2] = a.ndim
                if a.ndim:
                    hu[i, 3:3 + a.ndim] = np.asarray(a.shape, np.uint64)
                ds = a.dtype.str.encode()
                head[i * _VHDR + 88:i * _VHDR + 88 + len(ds)] = \
                    np.frombuffer(ds, np.uint8)
            srcs = [head] + [np.ascontiguousarray(a) for a in parts]
            done = False
            if self._base_addr is not None:
                dst0 = self._base_addr + self._slot_off(self.rank)
                done = self._copy_blocks_native(
                    [dst0] + [dst0 + o for o in offs],
                    [a.ctypes.data for a in srcs],
                    [a.nbytes for a in srcs],
                    fidx=self.rank * 8, fval=s0a + 1)
                if done:
                    self._arr = s0a + 1
            if not done:
                slot = self._slot(self.rank)
                self._copy_in(slot[:hdr], head)
                for a, o in zip(parts, offs):
                    if a.nbytes:
                        self._copy_in(slot[o:o + a.nbytes], a)
                self._set_arrive(s0a + 1)
        self._wait_all_arrive(s0a + 1, comm)
        verdict_host = any(self._read_desc(i)[0] == _DESC_HOST
                           for i in range(p))
        out: Optional[list] = None
        if not verdict_host:
            me = self.rank
            out = []
            natd, nats, natl = [], [], []
            py = []   # (arr, abs slot offset, nbytes) for the numpy path
            for i in range(p):
                eoff = self._slot_off(i) + me * _VHDR
                ent = np.frombuffer(self.seg.buf[eoff:eoff + 88],
                                    np.uint64)
                nb, boff, nd = int(ent[0]), int(ent[1]), int(ent[2])
                shape = tuple(int(x) for x in ent[3:3 + nd])
                raw = bytes(
                    self.seg.buf[eoff + 88:eoff + 120]).rstrip(b"\0")
                dt = np.dtype(raw.decode()) if raw else np.dtype(np.uint8)
                a = np.empty(shape, dt)
                out.append(a)
                if nb:
                    natd.append(a.ctypes.data)
                    nats.append(self._base_addr + self._slot_off(i) + boff
                                if self._base_addr is not None else 0)
                    natl.append(nb)
                    py.append((a, self._slot_off(i) + boff, nb))
            if not (self._base_addr is not None and natl
                    and self._copy_blocks_native(natd, nats, natl)):
                for a, aoff, nb in py:
                    a.reshape(-1)[...] = np.frombuffer(
                        self.seg.buf[aoff:aoff + nb], a.dtype,
                        count=a.size)
        self._set_depart(s0d + 1)
        self._wait_all_depart(s0d + 1, comm)
        return out

    def reduce_scatter(self, comm, arr: np.ndarray, op: Op, lo: int,
                       hi: int, order: list) -> np.ndarray:
        """Publish the whole payload, fold elements [lo, hi) of every
        slot in the caller's slot order (its comm-rank chain — native
        and numpy folds are bit-identical on it); returns the folded
        1-D segment.  Caller checked dtype and nbytes <= slot_bytes."""
        arr = np.asarray(arr)
        s0a, s0d = self._arr, self._dep
        self._publish_slot(comm, arr, s0a + 1)
        self._wait_all_arrive(s0a + 1, comm)
        out = self._fold_slots(arr.dtype, op, lo, hi, order)
        self._set_depart(s0d + 1)
        self._wait_all_depart(s0d + 1, comm)
        return out

    def scan(self, comm, arr: np.ndarray, op: Op,
             order: list) -> Optional[np.ndarray]:
        """Prefix fold: publish the whole payload, fold the listed
        slots (the caller's 0..r comm-rank prefix, so non-commutative
        ops stay order-correct) over the full element range.  An empty
        order participates in the round and returns None (exscan rank
        0's MPI-undefined result)."""
        arr = np.asarray(arr)
        s0a, s0d = self._arr, self._dep
        self._publish_slot(comm, arr, s0a + 1)
        self._wait_all_arrive(s0a + 1, comm)
        out = None
        if order:
            out = self._fold_slots(arr.dtype, op, 0, arr.size, order)
            out = out.reshape(arr.shape)
        self._set_depart(s0d + 1)
        self._wait_all_depart(s0d + 1, comm)
        return out


#: per-dest header entry bytes in an alltoallv slot: u64 nbytes, u64
#: offset, u64 ndim, u64 shape[_MAX_DIMS], 32B dtype str, pad to 128
_VHDR = 128


class PersistentSlots(Arena):
    """Pinned, parity-double-buffered slots for ONE bound persistent
    plan (coll/persistent).

    Layout: the Arena counter block (arrive/depart u64 ×p, cacheline
    padded) followed by TWO full slot sets — no descriptor region (the
    descriptor's job, shipping shape/dtype/verdict, was done once at
    bind time).  Parity q = op-sequence mod 2 indexes the slot set, so
    op k+1's publish lands in the slots op k is NOT draining: a rank
    that finished waiting op k may immediately Start op k+1 while
    slower ranks still read op k's parity — the double-buffered
    overlap the btl rings and ``allreduce_segmented_ring`` use, lifted
    to whole-operation granularity.  Slot reuse is guarded by the
    depart counters two ops back (same-parity predecessor), never by a
    per-op full barrier.

    The counters keep the Arena semantics (monotonic u64, single
    writer, ``memoryview.cast("Q")`` aligned stores), so every
    inherited wait — including the FT fail-fast checks and the dead
    -writer pid probe — applies unchanged.
    """

    def __init__(self, seg: shmseg.SharedSegment, size: int, rank: int,
                 slot_bytes: int, nslots: int, world=None,
                 pml=None, fence=None) -> None:
        super().__init__(seg, size, rank, slot_bytes, world=world, pml=pml,
                         fence=fence)
        self.nslots = nslots              # slots per parity set
        self._slot_base = 2 * size * _CACHELINE   # no desc region

    @staticmethod
    def pnbytes_for(size: int, slot_bytes: int, nslots: int) -> int:
        return 2 * size * _CACHELINE + 2 * nslots * slot_bytes

    def pslot_off(self, parity: int, i: int) -> int:
        return self._slot_base + (parity * self.nslots + i) * self.slot_bytes

    def pslot(self, parity: int, i: int) -> memoryview:
        off = self.pslot_off(parity, i)
        return self.seg.buf[off:off + self.slot_bytes]

    # non-blocking peeks (the poll half of a persistent op's test())
    def arrive_at(self, r: int) -> int:
        return int(self._flags[r * 8])

    def depart_at(self, r: int) -> int:
        return int(self._flags[(self.size + r) * 8])


def make_persistent_slots(comm, slot_bytes: int,
                          nslots: int) -> Optional["PersistentSlots"]:
    """Collectively map a dedicated parity-slot segment for one bound
    plan (the pinned-slot half of a persistent-collective bind).  None
    ⇒ mapping failed somewhere — every rank falls back together.  The
    slots are epoch-fenced on the bound comm (the local epoch here; the
    bind's incarnation agreement re-stamps it with the agreed value)."""
    slot_bytes = max(0, (slot_bytes + 63) & ~63)
    seg = _map_shared(
        comm, max(PersistentSlots.pnbytes_for(comm.size, slot_bytes,
                                              nslots), 1))
    if seg is None:
        return None
    return PersistentSlots(seg, comm.size, comm.rank, slot_bytes, nslots,
                           world=list(comm.group.ranks), pml=comm.pml,
                           fence=(_coll_epoch(comm), weakref.ref(comm)))


# ---------------------------------------------------------------------------
# bootstrap + per-communicator state
# ---------------------------------------------------------------------------

def _slot_bytes(size: int) -> int:
    slot = min(int(var_registry.get("coll_shm_slot_size")),
               int(var_registry.get("coll_shm_arena_size")) // (size + 1))
    return max(slot & ~15, 256)


def _map_shared(comm, nbytes: int) -> Optional[shmseg.SharedSegment]:
    """Collective over ``comm`` (whose ranks all share a host): rank 0
    creates a segment of ``nbytes``, the path rides a base-algorithm
    bcast (plain p2p — the arena cannot carry its own bootstrap),
    everyone attaches, and a MIN-allreduce agrees the mapping is usable
    everywhere before the creator unlinks the name (mappings survive;
    crash cleanup is free, like the btl/shm rings).  None ⇒ some rank
    could not map — every rank gets None together."""
    from ompi_tpu.mpi import op as op_mod

    seg = None
    path = ""
    if comm.rank == 0:
        try:
            name = f"otpu-collshm-{os.getpid()}-{uuid.uuid4().hex[:10]}"
            seg = shmseg.create(name, nbytes)
            path = seg.path
        except OSError as e:
            _log.verbose(1, "coll/shm: segment create failed (%s)", e)
    got = base.bcast_binomial(
        comm, np.frombuffer(path.encode(), np.uint8)
        if comm.rank == 0 else None, 0)
    path = bytes(bytearray(np.asarray(got, np.uint8))).decode()
    mine: Optional[shmseg.SharedSegment] = None
    ok = 0
    if comm.rank == 0:
        if seg is not None:
            mine, ok = seg, 1
    elif path:
        try:
            mine = shmseg.attach_retry(path, timeout=10.0)
            ok = 1
        except OSError as e:
            _log.verbose(1, "coll/shm: segment attach failed (%s)", e)
    allok = base.allreduce_recursive_doubling(
        comm, np.array([ok], np.int64), op_mod.MIN)
    if comm.rank == 0 and seg is not None:
        seg.unlink()   # attach agreement passed (or failed): name done
    if int(allok[0]) != 1:
        if mine is not None:
            mine.detach()
        return None
    return mine


def _make_arena(comm, fence=None) -> Optional[Arena]:
    """The one-shot dispatch arena: one ``_map_shared`` bootstrap with
    the classic flags+desc+slots layout."""
    p = comm.size
    slot = _slot_bytes(p)
    seg = _map_shared(comm, Arena.nbytes_for(p, slot))
    if seg is None:
        return None
    return Arena(seg, p, comm.rank, slot,
                 world=list(comm.group.ranks), pml=comm.pml, fence=fence)


class _HostFallback:
    """Per-communicator fallback marker (no co-located ranks, no usable
    shm dir, or arena bootstrap failed) — epoch-stamped like ``_State``
    so a comm that settled on host BEFORE a revive re-runs the split
    with the revived rank included instead of staying host forever."""

    mode = "host"

    def __init__(self, epoch: int = 0) -> None:
        self.epoch = epoch

    def close(self) -> None:
        pass


_SETUP = object()   # reentrancy sentinel: setup's own collectives → host


class _State:
    """Cached per-communicator dispatch state (rides ``comm._coll_shm_state``;
    ``Communicator.free`` closes it; a coll-epoch advance past ``epoch``
    — an adopted selfheal revive — invalidates it)."""

    def __init__(self, mode: str, node, leader, arena,
                 c2n=None, node_blocks=None, node_idx_of=None,
                 epoch: int = 0) -> None:
        self.mode = mode              # "arena" (flat) | "hier"
        self.node = node              # split_type(COMM_TYPE_SHARED) cache
        self.leader = leader          # node-rank-0 communicator (or None)
        self.arena = arena            # this node's Arena (or None)
        self.c2n = c2n                # flat: comm rank → arena rank
        self.node_blocks = node_blocks  # hier: per node, comm ranks by node rank
        self.node_idx_of = node_idx_of  # hier: comm rank → node index
        self.epoch = epoch            # agreed coll epoch at build

    def close(self) -> None:
        if self.arena is not None:
            self.arena.close()
            self.arena = None


# ---------------------------------------------------------------------------
# the component
# ---------------------------------------------------------------------------

@coll_framework.component
class ShmColl(Component):
    NAME = "shm"
    PRIORITY = 50    # above host (40): same-host ranks take the arena

    def register_params(self) -> None:
        register_var("coll", "shm_enable", VarType.BOOL, True,
                     "use the on-node shared-memory collective arena "
                     "when ranks share a host (0 = coll/host everywhere)")
        register_var("coll", "shm_arena_size", VarType.SIZE, 4 << 20,
                     "max payload routed through the arena; larger "
                     "collectives fall back to coll/host (whose ring/"
                     "pipeline algorithms are bandwidth-optimal there)")
        register_var("coll", "shm_slot_size", VarType.SIZE, 256 << 10,
                     "per-rank arena slot; payloads above half a slot "
                     "pipeline through the slot halves (double-buffered)")
        register_var("coll", "shm_timeout", VarType.SIZE, 60,
                     "seconds an arena flag wait may stall before raising "
                     "(a dead peer or collective-order mismatch leaves "
                     "flags behind forever)")
        register_var("coll", "stuck_timeout", VarType.DOUBLE, 5.0,
                     "seconds an arena flag wait may stall before the "
                     "rank records a 'stuck' event on the collective "
                     "flight recorder and forces an out-of-cadence "
                     "metrics push (the HNP hang doctor's watchdog "
                     "trigger for an automatic cross-rank capture).  "
                     "0 disables the watchdog; the wait itself still "
                     "fails at coll_shm_timeout")
        register_var("coll", "shm_probe_grace", VarType.DOUBLE, 1.0,
                     "seconds an arena wait stalls before probing the "
                     "expected writer's pid via the btl liveness probe "
                     "(0 = disabled); a SIGKILLed writer then fails its "
                     "peers in ~this window instead of coll_shm_timeout. "
                     "Validated to stay below coll_shm_timeout")
        register_var("coll", "shm_native", VarType.BOOL, True,
                     "run the arena steady state (flag waits, slot "
                     "publishes, segment folds) through the native "
                     "GIL-released executor (_native/arena.c). Off, a "
                     "failed build, or OMPI_TPU_NO_NATIVE=1 -> the "
                     "pure-python data plane (bit-identical results)")
        register_var("coll", "shm_allreduce_algorithm", VarType.STRING,
                     "", "force the persistent arena allreduce fold "
                     "strategy: root_fold | segment_parallel (empty = "
                     "rules file / payload crossover)")
        register_var("coll", "shm_segpar_min", VarType.SIZE, 1 << 20,
                     "payload crossover above which a persistent arena "
                     "allreduce binds the cooperative segment-parallel "
                     "reduce-scatter+allgather instead of the "
                     "single-rank root fold (0 = never)")

    def query(self, comm=None, **ctx) -> Optional[int]:
        if not var_registry.get("coll_shm_enable"):
            return None
        if comm is None or comm.size <= 1 or comm.test_inter():
            return None
        d = shmseg.backing_dir()
        if not (os.path.isdir(d) and os.access(d, os.W_OK)):
            return None
        return self.PRIORITY

    # -- state -------------------------------------------------------------

    def _host(self):
        return coll_framework.lookup("host")

    def _state(self, comm):
        st = getattr(comm, "_coll_shm_state", None)
        if st is _SETUP:
            return None          # setup's own collectives ride coll/host
        if st is not None:
            cur = _coll_epoch(comm)
            if getattr(st, "epoch", 0) >= cur:
                return st
            # epoch-fenced rejoin: a member was revived (its adopted
            # incarnation advanced the coll epoch past the build's) —
            # the cached node/leader splits, arena slot state and
            # frozen hierarchy decisions are survivors-only artifacts
            # now.  Tear them down (the failed op already drained: no
            # rank can complete an op the missing life never published
            # into) and rebuild with the revived rank included.  The
            # pending-rejoin marker rides the COMM, not a local: if the
            # epoch agreement below fails fast (another member dead)
            # the dispatch retries with no cached state, and the
            # eventual successful rebuild must still record the rejoin
            # (first-teardown timestamp kept — honest latency).
            if getattr(comm, "_coll_rejoin_pending", None) is None:
                comm._coll_rejoin_pending = (getattr(st, "epoch", 0),
                                             time.monotonic_ns())
            st.close()
            comm._coll_shm_state = st = None
        # the epoch every rank stamps the rebuilt state with is AGREED
        # first (MAX-allreduce on the base p2p plane, which is
        # incarnation-transparent) — run OUTSIDE the fallback guard: a
        # dead member fails it fast and the dispatch retries, instead
        # of settling on host with a divergent epoch
        epoch = self._agree_epoch(comm)
        comm._coll_shm_state = _SETUP
        built = None
        try:
            t0 = trace_mod.begin() if trace_mod.active else 0
            built = self._build_state(comm, epoch)
            if t0:
                trace_mod.complete("coll", "shm_setup", t0,
                                   rank=comm.pml.rank, cid=comm.cid,
                                   mode=built.mode, size=comm.size)
        except MPIException as e:
            # e.g. a merged intercomm whose per-viewer namespace ids
            # cannot survive split_type — the raise is deterministic
            # (every rank computes the same partition), so settling
            # on coll/host is collectively consistent
            _log.verbose(1, "coll/shm: setup on %s fell back to host "
                         "(%s)", comm.name, e)
        finally:
            # the freed check and the cache assignment must be ONE
            # atomic step against Comm.free() (which sets the flag and
            # clears the cache under the same comm lock): a check-then-
            # assign window would let a racing free() run to completion
            # between them and the freshly-built arena would be cached
            # onto the freed comm — the exact leak this guards against
            with comm._lock:
                freed = getattr(comm, "_coll_freed", False)
                if not freed:
                    comm._coll_shm_state = (built if built is not None
                                            else _HostFallback(epoch))
            if freed:
                # Comm.free() ran while this build was in flight (it
                # saw the _SETUP sentinel and had nothing to close):
                # close the half-built state instead of caching it
                if built is not None:
                    built.close()
                    built = None
                comm._coll_shm_state = None
        st = comm._coll_shm_state
        pending = getattr(comm, "_coll_rejoin_pending", None)
        if pending is not None and st is not None:
            # record FIRST: the hierarchy rebuild itself completed, so
            # the rejoin must be counted (and coll_rejoin_ns scoped to
            # the rebuild, not the plan rebinds below) even if an eager
            # plan rebind then fails fast — the dispatch retry must not
            # double-record it
            comm._coll_rejoin_pending = None
            self._record_rejoin(comm, pending, st)
            self._rebind_stale_plans(comm)
        return st

    def _rebind_stale_plans(self, comm) -> None:
        """Eagerly recompile every stale, inactive persistent plan
        bound on this comm as the LAST step of the rejoin, in bind
        order.  Ordering is the point: the revived life re-executes its
        prologue ``*_init`` calls BEFORE its first loop collective, so
        the survivors must pair those binds HERE — inside the rejoin,
        before the op that triggered it re-runs.  Deferring each rebind
        to its plan's next Start (the Start-gate backstop, which still
        covers plans used without any one-shot dispatch in between)
        would interleave the bind collectives AFTER one-shot ops the
        revived life has not issued yet: a collective-order divergence
        that deadlocks mixed one-shot + persistent apps — found driving
        exactly that app shape end-to-end."""
        for ref in list(getattr(comm, "_persistent_colls", ())):
            req = ref()
            if req is None:
                continue
            rebind = getattr(req, "_rebind_if_stale", None)
            if rebind is not None:
                rebind()

    def _agree_epoch(self, comm) -> int:
        """The coll epoch the (re)built state is stamped with, agreed
        across every member: a MAX-allreduce of the local epochs over
        the base p2p plane.  Unconditional (epoch 0 at job start agrees
        instantly) so participation can never diverge — a rank that has
        not yet adopted a revived life still pairs the prologue, stamps
        the agreed (higher) epoch, and its later adoption then reads as
        already-included instead of spuriously re-triggering.  The
        base-plane allreduce IS the agreement here: ``Comm.agree``'s
        per-(cid, seq) protocol state restarts at 0 in a revived life,
        so its sequence numbers cannot pair across lives — p2p tags
        (incarnation-fenced, msglog-replayed) can.

        The same exchange MAX-agrees the parent's deterministic-cid
        allocator and persistent-tag counters (``_counter_merge``): a
        revived life's fresh counters sit at their base while the
        survivors' advanced with every earlier build, and the rebuilt
        node/leader splits' counter-derived cids (and a re-bound nbc
        plan's tags) MUST land identically on every member or the
        rebuild's own collectives never match."""
        if comm.size <= 1:
            return _coll_epoch(comm)
        cid_next, pseq = comm._counter_snapshot()
        agreed = np.asarray(base.allreduce_recursive_doubling(
            comm, np.array([_coll_epoch(comm), cid_next, pseq],
                           np.int64), op_mod.MAX))
        comm._counter_merge(int(agreed[1]), int(agreed[2]))
        return int(agreed[0])

    def _record_rejoin(self, comm, pending, st) -> None:
        """One completed epoch-fenced rebuild: pvar + latency histogram
        + flight-recorder event locally, and a best-effort one-way PMIx
        push so the HNP's FT timeline (and the /status + --dvm-ps
        rejoins column) shows the rejoin."""
        old_epoch, t0 = pending
        dur = time.monotonic_ns() - t0
        trace_mod.count("coll_rejoin_total")
        if trace_mod.hist_active:
            trace_mod.record_hist("coll_rejoin_ns", dur)
        trace_mod.coll_event(
            comm.pml.rank, comm.cid, "rejoin",
            {"oe": old_epoch, "ne": getattr(st, "epoch", 0),
             "mode": getattr(st, "mode", "?")})
        _log.verbose(1, "coll/shm: %s rebuilt the coll hierarchy at "
                     "epoch %d (from %d, %.1f ms, mode %s)", comm.name,
                     getattr(st, "epoch", 0), old_epoch, dur / 1e6,
                     getattr(st, "mode", "?"))
        ft = comm.pml.ft
        client = ft.detector._client if ft is not None else None
        rej = getattr(client, "coll_rejoin", None)
        if rej is not None:
            try:    # app thread (coll dispatch), RPC allowed; best-effort
                rej(old_epoch, int(getattr(st, "epoch", 0)),
                    int(dur // 1_000_000))
            except Exception:  # noqa: BLE001 — observability, not recovery
                pass

    def _build_state(self, comm, epoch: int = 0):
        node = comm.split_type(COMM_TYPE_SHARED,
                               name=f"{comm.name}.shmnode")
        leader = comm.split(0 if node.rank == 0 else UNDEFINED,
                            key=comm.rank, name=f"{comm.name}.shmldr")
        # the fence comm is the PARENT: a revive anywhere in the
        # hierarchy must break node-arena waits, not just node-local ones
        fence = (epoch, weakref.ref(comm))
        arena = _make_arena(node, fence=fence) if node.size > 1 else None
        if node.size == comm.size:                      # one host: flat
            if arena is None:
                return _HostFallback(epoch)
            c2n = np.array([node.group.rank_of(comm.world_rank(r))
                            for r in range(comm.size)], np.int64)
            return _State("arena", node, leader, arena, c2n=c2n,
                          epoch=epoch)
        # mixed hosts: leaders exchange their node's comm-rank blocks
        # (ordered by node rank — i.e. by leader-comm rank across nodes),
        # then fan the table out intra-node; base algorithms only (the
        # arena protocol must not bootstrap itself)
        if leader is not None:
            my_block = np.array([comm.group.rank_of(w)
                                 for w in node.group.ranks], np.int64)
            blocks = base.allgatherv_ring(leader, my_block)
            lens = np.array([len(b) for b in blocks], np.int64)
            meta = np.concatenate(
                [[len(blocks)], lens] + [np.asarray(b, np.int64)
                                         for b in blocks])
        else:
            meta = None
        if node.size > 1:
            meta = base.bcast_binomial(
                node, meta if node.rank == 0 else None, 0)
        meta = np.asarray(meta, np.int64)
        nnodes = int(meta[0])
        lens = meta[1:1 + nnodes]
        node_blocks, off = [], 1 + nnodes
        for ln in lens:
            node_blocks.append([int(x) for x in meta[off:off + int(ln)]])
            off += int(ln)
        if all(len(b) == 1 for b in node_blocks):
            if arena is not None:
                arena.close()
            # nobody shares a host: pure coll/host ground (epoch-stamped
            # so a later revive still re-evaluates the partition)
            return _HostFallback(epoch)
        node_idx_of = {r: i for i, blk in enumerate(node_blocks)
                       for r in blk}
        return _State("hier", node, leader, arena,
                      node_blocks=node_blocks, node_idx_of=node_idx_of,
                      epoch=epoch)

    # -- decision helpers ----------------------------------------------------

    def _cap(self) -> int:
        return int(var_registry.get("coll_shm_arena_size"))

    def _host_directive(self, coll: str, comm, nbytes: int) -> Optional[str]:
        """An explicit host-algorithm force or a rules-file hit is user
        tuning the on-node shortcut must not override."""
        if coll in ("bcast", "allreduce", "allgather", "alltoall",
                    "reduce_scatter"):
            if var_registry.get(f"coll_host_{coll}_algorithm"):
                return f"forced coll_host_{coll}_algorithm"
            path = var_registry.get("coll_host_dynamic_rules")
            if path:
                try:
                    hit = self._host()._load_rules(path).lookup(
                        coll, comm.size, nbytes)
                except Exception:  # noqa: BLE001 — let host surface the error
                    return f"unreadable rules file {path}"
                if hit:
                    return f"rules file {path}"
        return None

    def _fallback(self, comm, coll: str, reason: str, nbytes: int = 0):
        trace_mod.count("coll_shm_fallback_total")
        if trace_mod.active:
            trace_mod.instant(
                "coll", f"decision:{coll}", rank=comm.pml.rank,
                algorithm="fallback:host", source=f"coll/shm: {reason}",
                nbytes=nbytes, size=comm.size)
        return self._host()

    def _route(self, comm, coll: str, nbytes: int = 0):
        """(state, None) to run the arena/hier path, or (None, host
        component) to fall back — every branch driven by inputs all
        ranks agree on."""
        st = self._state(comm)
        if st is None:
            return None, self._host()   # setup reentry: silent host
        if st.mode == "host":
            return None, self._fallback(comm, coll, "no arena (single-rank "
                                        "hosts or bootstrap failed)", nbytes)
        src = self._host_directive(coll, comm, nbytes)
        if src is not None:
            return None, self._fallback(comm, coll, src, nbytes)
        return st, None

    # -- intra-node phase helpers (hier mode) --------------------------------

    def _intra_gate_in(self, st) -> None:
        if st.node.size == 1:
            return
        if st.arena is not None:
            trace_mod.count("coll_shm_fanin_total")
            st.arena.gate_in(st.node, 0)
        else:
            base.gather_linear(st.node, _TOKEN, 0)

    def _intra_gate_out(self, st) -> None:
        if st.node.size == 1:
            return
        if st.arena is not None:
            trace_mod.count("coll_shm_fanout_total")
            st.arena.gate_out(st.node, 0)
        else:
            base.bcast_binomial(st.node,
                                _TOKEN if st.node.rank == 0 else None, 0)

    def _intra_bcast(self, st, buf, nroot: int):
        node = st.node
        if node.size == 1:
            return np.asarray(buf)
        if st.arena is not None:
            out = st.arena.bcast(node, nroot, buf, self._cap())
            if out is not None:
                trace_mod.count("coll_shm_fanout_total")
                return out
            trace_mod.count("coll_shm_fallback_total")
        return self._host().coll_bcast(node, buf, nroot)

    def _intra_reduce(self, st, arr, op: Op):
        """Fold to node rank 0; returns the partial there, None elsewhere."""
        node = st.node
        if node.size == 1:
            return np.asarray(arr)
        if st.arena is not None and self._reducible(arr, op, st.arena):
            trace_mod.count("coll_shm_fanin_total")
            return st.arena.reduce(node, 0, arr, op, bcast_result=False)
        trace_mod.count("coll_shm_fallback_total")
        return self._host().coll_reduce(node, arr, op, 0)

    def _reducible(self, arr: np.ndarray, op: Op, arena: Arena) -> bool:
        return (op.commutative and _arena_dtype_ok(arr.dtype)
                and arr.dtype.itemsize <= arena.half
                and arr.nbytes <= self._cap())

    # -- table slots ---------------------------------------------------------

    @_epoch_retries
    def coll_barrier(self, comm) -> None:
        st, host = self._route(comm, "barrier")
        if host is not None:
            return host.coll_barrier(comm)
        if st.mode == "arena":
            trace_mod.count("coll_shm_fanin_total")
            return st.arena.barrier(comm)
        self._intra_gate_in(st)
        if st.leader is not None:
            self._host().coll_barrier(st.leader)
        self._intra_gate_out(st)

    @_epoch_retries
    def coll_bcast(self, comm, buf, root: int):
        st, host = self._route(comm, "bcast")
        if host is not None:
            return host.coll_bcast(comm, buf, root)
        if st.mode == "arena":
            out = st.arena.bcast(comm, int(st.c2n[root]), buf, self._cap())
            if out is None:   # the root's verdict, learned via the desc
                return self._fallback(
                    comm, "bcast", "payload above coll_shm_arena_size or "
                    "unsupported dtype (root's descriptor verdict)"
                ).coll_bcast(comm, buf, root)
            trace_mod.count("coll_shm_fanout_total")
            return out
        my_idx = st.node_idx_of[comm.rank]
        root_idx = st.node_idx_of[root]
        data = buf
        if my_idx == root_idx and st.node.size > 1:
            nroot = st.node.group.rank_of(comm.world_rank(root))
            data = self._intra_bcast(st, data, nroot)
        if st.leader is not None:
            data = self._host().coll_bcast(
                st.leader, data if my_idx == root_idx else None, root_idx)
        if my_idx != root_idx:
            data = self._intra_bcast(st, data, 0)
        return np.asarray(data)

    @_epoch_retries
    def coll_reduce(self, comm, sendbuf, op: Op, root: int):
        arr = np.asarray(sendbuf)
        st, host = self._route(comm, "reduce", arr.nbytes)
        if host is not None:
            return host.coll_reduce(comm, arr, op, root)
        if not op.commutative:
            return self._fallback(comm, "reduce", "non-commutative op",
                                  arr.nbytes).coll_reduce(comm, arr, op,
                                                          root)
        if st.mode == "arena":
            if not self._reducible(arr, op, st.arena):
                return self._fallback(
                    comm, "reduce", "payload above coll_shm_arena_size or "
                    "unsupported dtype", arr.nbytes
                ).coll_reduce(comm, arr, op, root)
            trace_mod.count("coll_shm_fanin_total")
            return st.arena.reduce(comm, int(st.c2n[root]), arr, op,
                                   bcast_result=False)
        root_idx = st.node_idx_of[root]
        partial = self._intra_reduce(st, arr, op)
        out = None
        if st.leader is not None:
            out = self._host().coll_reduce(st.leader, partial, op, root_idx)
        root_leader = st.node_blocks[root_idx][0]
        if root_leader != root:   # root is not its node's leader: one hop
            if comm.rank == root_leader:
                comm._coll_isend(out, root, base.TAG_REDUCE).wait()
                out = None
            elif comm.rank == root:
                out = comm._coll_irecv(None, root_leader,
                                       base.TAG_REDUCE).wait()
                out = out.reshape(arr.shape).astype(arr.dtype, copy=False)
        return out if comm.rank == root else None

    @_epoch_retries
    def coll_allreduce(self, comm, sendbuf, op: Op):
        arr = np.asarray(sendbuf)
        st, host = self._route(comm, "allreduce", arr.nbytes)
        if host is not None:
            return host.coll_allreduce(comm, arr, op)
        if not op.commutative:
            return self._fallback(comm, "allreduce", "non-commutative op",
                                  arr.nbytes).coll_allreduce(comm, arr, op)
        if st.mode == "arena":
            if not self._reducible(arr, op, st.arena):
                return self._fallback(
                    comm, "allreduce", "payload above coll_shm_arena_size "
                    "or unsupported dtype", arr.nbytes
                ).coll_allreduce(comm, arr, op)
            trace_mod.count("coll_shm_fanin_total")
            trace_mod.count("coll_shm_fanout_total")
            return st.arena.reduce(comm, 0, arr, op, bcast_result=True)
        partial = self._intra_reduce(st, arr, op)
        total = partial
        if st.leader is not None:
            total = self._host().coll_allreduce(st.leader, partial, op)
        out = self._intra_bcast(st, total, 0)
        return np.asarray(out).reshape(arr.shape).astype(arr.dtype,
                                                         copy=False)

    @_epoch_retries
    def coll_allgather(self, comm, sendbuf):
        arr = np.asarray(sendbuf)
        st, host = self._route(comm, "allgather", arr.nbytes)
        if host is not None:
            return host.coll_allgather(comm, arr)
        if st.mode == "arena":
            if not (_arena_dtype_ok(arr.dtype)
                    and arr.nbytes <= st.arena.slot_bytes
                    and arr.nbytes * comm.size <= self._cap()):
                return self._fallback(
                    comm, "allgather", "payload above the slot/arena cap "
                    "or unsupported dtype", arr.nbytes
                ).coll_allgather(comm, arr)
            trace_mod.count("coll_shm_fanin_total")
            trace_mod.count("coll_shm_fanout_total")
            out = st.arena.allgather(comm, arr)
            c2n = st.c2n
            if not np.array_equal(c2n, np.arange(comm.size)):
                out = out[c2n]
            return out
        # hier: node gather → leader allgatherv → reorder → node bcast
        node = st.node
        if node.size > 1:
            if (st.arena is not None and _arena_dtype_ok(arr.dtype)
                    and arr.nbytes <= st.arena.slot_bytes):
                trace_mod.count("coll_shm_fanin_total")
                block = st.arena.allgather(node, arr)
            else:
                block = self._host().coll_allgather(node, arr)
        else:
            block = arr[None]
        full = None
        if st.leader is not None:
            rows = self._host().coll_allgatherv(
                st.leader, np.ascontiguousarray(block).reshape(
                    block.shape[0], -1))
            full = np.empty((comm.size, max(arr.size, 0)), arr.dtype)
            for bi, blk in enumerate(rows):
                full[np.asarray(st.node_blocks[bi])] = np.asarray(
                    blk, arr.dtype).reshape(len(st.node_blocks[bi]), -1)
        full = self._intra_bcast(st, full, 0)
        return np.asarray(full, arr.dtype).reshape(
            (comm.size,) + arr.shape)

    # -- dense exchange slots ------------------------------------------------
    #
    # alltoall/v/w, reduce_scatter and scan/exscan — the last collective
    # class still PML-bound.  Flat comms run the one-round arena
    # protocols; hier comms run the MPI-Advance locality split (node
    # leaders aggregate per-node blocks, exchange O(nodes) large frames
    # over the btl rings, scatter intra-node over the arena) for the
    # patterns whose counts every rank can derive (alltoall,
    # reduce_scatter, contiguous-block scan).  v/w counts are rank-local
    # knowledge, so multi-node v/w falls back to host rather than guess
    # a split no rank can verify collectively.

    @_epoch_retries
    def coll_alltoall(self, comm, sendbuf):
        arr = np.asarray(sendbuf)
        st, host = self._route(comm, "alltoall", arr.nbytes)
        if host is not None:
            return host.coll_alltoall(comm, arr)
        p = comm.size
        if arr.ndim == 0 or arr.shape[0] % p:
            return self._host().coll_alltoall(comm, arr)  # host's error
        if st.mode == "arena":
            if not (_arena_dtype_ok(arr.dtype)
                    and arr.nbytes <= st.arena.slot_bytes
                    and arr.nbytes <= self._cap()):
                return self._fallback(
                    comm, "alltoall", "payload above the slot/arena cap "
                    "or unsupported dtype", arr.nbytes
                ).coll_alltoall(comm, arr)
            trace_mod.count("coll_shm_fanin_total")
            trace_mod.count("coll_shm_fanout_total")
            c2n = st.c2n
            ident = bool(np.array_equal(c2n, np.arange(p)))
            a = np.ascontiguousarray(arr)
            if not ident:
                inv = np.empty(p, np.int64)
                inv[c2n] = np.arange(p)
                a = np.ascontiguousarray(a.reshape(p, -1)[inv])
            out = st.arena.alltoall(comm, a)
            if not ident:
                out = out[c2n]
            return np.ascontiguousarray(out).reshape(arr.shape)
        if arr.nbytes > self._cap():
            return self._fallback(
                comm, "alltoall", "payload above coll_shm_arena_size",
                arr.nbytes).coll_alltoall(comm, arr)
        # locality-aware aggregation: everyone shares its full sendbuf
        # intra-node, leaders exchange ONE frame per peer node carrying
        # every (src member, dst member) block for that node pair, then
        # one intra bcast fans the reassembled table out — O(nodes)
        # large btl frames instead of O(p²) small ones
        node = st.node
        bb = arr.size // p
        a = np.ascontiguousarray(arr)
        if node.size > 1:
            trace_mod.count("coll_shm_fanin_total")
            if (st.arena is not None and _arena_dtype_ok(a.dtype)
                    and a.nbytes <= st.arena.slot_bytes):
                gathered = st.arena.allgather(node, a)
            else:
                gathered = self._host().coll_allgather(node, a)
        else:
            gathered = a[None]
        full = None
        if st.leader is not None:
            mat = np.ascontiguousarray(gathered).reshape(node.size, p, bb)
            frames = [np.ascontiguousarray(
                mat[:, np.asarray(blk)]).reshape(-1)
                for blk in st.node_blocks]
            got = self._host().coll_alltoallv(st.leader, frames)
            full = np.empty((p, node.size, bb), arr.dtype)
            for i, blk in enumerate(st.node_blocks):
                full[np.asarray(blk)] = np.asarray(
                    got[i], arr.dtype).reshape(len(blk), node.size, bb)
        full = self._intra_bcast(st, full, 0)
        mine = np.asarray(full, arr.dtype).reshape(
            p, node.size, bb)[:, st.node.rank]
        return np.ascontiguousarray(mine).reshape(arr.shape)

    @_epoch_retries
    def coll_alltoallv(self, comm, sendparts):
        st, host = self._route(comm, "alltoallv")
        if host is not None:
            return host.coll_alltoallv(comm, sendparts)
        if st.mode != "arena":
            return self._fallback(
                comm, "alltoallv", "multi-node: v-counts are rank-local "
                "(no collectively-derivable aggregation split)"
            ).coll_alltoallv(comm, sendparts)
        p = comm.size
        if len(sendparts) != p:
            return self._host().coll_alltoallv(comm, sendparts)
        c2n = st.c2n
        ident = bool(np.array_equal(c2n, np.arange(p)))
        send = list(sendparts)
        if not ident:
            inv = np.empty(p, np.int64)
            inv[c2n] = np.arange(p)
            send = [sendparts[int(inv[j])] for j in range(p)]
        got = st.arena.alltoallv(comm, send)
        if got is None:
            return self._fallback(
                comm, "alltoallv", "peer verdict: part above the slot "
                "cap or undescribable dtype (descriptor round)"
            ).coll_alltoallv(comm, sendparts)
        trace_mod.count("coll_shm_fanin_total")
        trace_mod.count("coll_shm_fanout_total")
        return got if ident else [got[int(c2n[r])] for r in range(p)]

    @_epoch_retries
    def coll_alltoallw(self, comm, sendspecs, recvspecs):
        st, host = self._route(comm, "alltoallw")
        if host is not None:
            return host.coll_alltoallw(comm, sendspecs, recvspecs)
        if st.mode != "arena":
            return self._fallback(
                comm, "alltoallw", "multi-node: w-specs are rank-local "
                "(no collectively-derivable aggregation split)"
            ).coll_alltoallw(comm, sendspecs, recvspecs)
        p = comm.size
        if len(sendspecs) != p or len(recvspecs) != p:
            return self._host().coll_alltoallw(comm, sendspecs, recvspecs)
        # pack with the send datatypes, ride the byte alltoallv, unpack
        # with the receive datatypes — the pairwise wire, minus the PML
        packed = [base.pack_spec(s) for s in sendspecs]
        c2n = st.c2n
        ident = bool(np.array_equal(c2n, np.arange(p)))
        send = packed
        if not ident:
            inv = np.empty(p, np.int64)
            inv[c2n] = np.arange(p)
            send = [packed[int(inv[j])] for j in range(p)]
        got = st.arena.alltoallv(comm, send)
        if got is None:
            return self._fallback(
                comm, "alltoallw", "peer verdict: packed part above the "
                "slot cap (descriptor round)"
            ).coll_alltoallw(comm, sendspecs, recvspecs)
        trace_mod.count("coll_shm_fanin_total")
        trace_mod.count("coll_shm_fanout_total")
        for r in range(p):
            base.unpack_spec(recvspecs[r],
                             got[r] if ident else got[int(c2n[r])])
        return None

    @staticmethod
    def _rs_bounds(n: int, p: int) -> list:
        """np.array_split boundaries over a flat n-element payload —
        the reduce_scatter chunk contract shared with coll/host."""
        q, rmd = divmod(n, p)
        return [r * q + min(r, rmd) for r in range(p + 1)]

    @_epoch_retries
    def coll_reduce_scatter(self, comm, sendbuf, op: Op):
        arr = np.asarray(sendbuf)
        st, host = self._route(comm, "reduce_scatter", arr.nbytes)
        if host is not None:
            return host.coll_reduce_scatter(comm, arr, op)
        p = comm.size
        if st.mode == "arena":
            if not (_arena_dtype_ok(arr.dtype)
                    and arr.nbytes <= st.arena.slot_bytes
                    and arr.nbytes <= self._cap()):
                return self._fallback(
                    comm, "reduce_scatter", "payload above the slot/arena "
                    "cap or unsupported dtype", arr.nbytes
                ).coll_reduce_scatter(comm, arr, op)
            trace_mod.count("coll_shm_fanin_total")
            trace_mod.count("coll_shm_fanout_total")
            # comm-rank fold order: canonical for non-commutative ops
            # too, unlike the host ring
            bnds = self._rs_bounds(arr.size, p)
            order = [int(st.c2n[r]) for r in range(p)]
            return st.arena.reduce_scatter(
                comm, arr, op, bnds[comm.rank], bnds[comm.rank + 1], order)
        if not op.commutative:
            return self._fallback(
                comm, "reduce_scatter", "non-commutative op (cross-node "
                "folds reorder)", arr.nbytes
            ).coll_reduce_scatter(comm, arr, op)
        # locality split: fold intra-node first, then leaders exchange
        # ONE frame per peer node (that node's members' chunks,
        # concatenated), fold across nodes, and one intra bcast + local
        # slice scatters the result
        partial = self._intra_reduce(st, arr, op)
        bnds = self._rs_bounds(arr.size, p)
        stack = None
        if st.leader is not None:
            flatp = np.ascontiguousarray(partial).reshape(-1)
            frames = [np.concatenate([flatp[bnds[r]:bnds[r + 1]]
                                      for r in blk])
                      for blk in st.node_blocks]
            got = self._host().coll_alltoallv(st.leader, frames)
            acc = np.asarray(got[0], arr.dtype)
            for fr in got[1:]:
                acc = np.asarray(op.host(
                    acc, np.asarray(fr).astype(acc.dtype, copy=False)))
            stack = acc
        stack = self._intra_bcast(st, stack, 0)
        blk = st.node_blocks[st.node_idx_of[comm.rank]]
        off = sum(bnds[r + 1] - bnds[r] for r in blk[:st.node.rank])
        ln = bnds[comm.rank + 1] - bnds[comm.rank]
        out = np.asarray(stack, arr.dtype).reshape(-1)[off:off + ln]
        return np.ascontiguousarray(out)

    @_epoch_retries
    def coll_reduce_scatter_block(self, comm, sendbuf, op: Op):
        arr = np.asarray(sendbuf)
        if arr.ndim == 0 or arr.shape[0] % comm.size:
            return self._host().coll_reduce_scatter_block(comm, arr, op)
        rows = arr.shape[0] // comm.size
        out = self.coll_reduce_scatter(
            comm, arr.reshape(arr.shape[0], -1), op)
        return np.asarray(out).reshape((rows,) + arr.shape[1:])

    @_epoch_retries
    def coll_scan(self, comm, sendbuf, op: Op):
        arr = np.asarray(sendbuf)
        st, host = self._route(comm, "scan", arr.nbytes)
        if host is not None:
            return host.coll_scan(comm, arr, op)
        if st.mode == "arena":
            if not (_arena_dtype_ok(arr.dtype)
                    and arr.nbytes <= st.arena.slot_bytes
                    and arr.nbytes <= self._cap()):
                return self._fallback(
                    comm, "scan", "payload above the slot/arena cap or "
                    "unsupported dtype", arr.nbytes
                ).coll_scan(comm, arr, op)
            trace_mod.count("coll_shm_fanin_total")
            order = [int(st.c2n[r]) for r in range(comm.rank + 1)]
            return st.arena.scan(comm, arr, op, order)
        return self._scan_hier(comm, st, arr, op, exclusive=False)

    @_epoch_retries
    def coll_exscan(self, comm, sendbuf, op: Op):
        arr = np.asarray(sendbuf)
        st, host = self._route(comm, "exscan", arr.nbytes)
        if host is not None:
            return host.coll_exscan(comm, arr, op)
        if st.mode == "arena":
            if not (_arena_dtype_ok(arr.dtype)
                    and arr.nbytes <= st.arena.slot_bytes
                    and arr.nbytes <= self._cap()):
                return self._fallback(
                    comm, "exscan", "payload above the slot/arena cap or "
                    "unsupported dtype", arr.nbytes
                ).coll_exscan(comm, arr, op)
            trace_mod.count("coll_shm_fanin_total")
            order = [int(st.c2n[r]) for r in range(comm.rank)]
            return st.arena.scan(comm, arr, op, order)
        return self._scan_hier(comm, st, arr, op, exclusive=True)

    def _scan_hier(self, comm, st, arr: np.ndarray, op: Op,
                   exclusive: bool):
        """Hierarchical prefix: intra-node prefixes + the node TOTAL at
        each leader (one arena round — the leader just folds a longer
        slot order), an exscan of node totals across the leader chain,
        one intra bcast of the node base, one local combine.  Valid only
        when the node blocks tile the comm contiguously (the prefix
        order must not cross hosts); gates are all derived from inputs
        every rank agrees on."""
        kind = "exscan" if exclusive else "scan"

        def _host_run(reason):
            h = self._fallback(comm, kind, reason, arr.nbytes)
            return (h.coll_exscan(comm, arr, op) if exclusive
                    else h.coll_scan(comm, arr, op))

        flat = [r for blk in st.node_blocks for r in blk]
        if flat != list(range(comm.size)):
            return _host_run("non-contiguous node blocks (prefix order "
                            "crosses hosts)")
        # _slot_bytes is non-increasing in size, so the comm-size floor
        # bounds every node arena's slot: one globally-uniform gate
        if not (_arena_dtype_ok(arr.dtype)
                and arr.nbytes <= _slot_bytes(comm.size)
                and arr.nbytes <= self._cap()):
            return _host_run("payload above the slot/arena cap or "
                            "unsupported dtype")
        node = st.node
        nr = node.rank
        intra = None
        if node.size > 1:
            trace_mod.count("coll_shm_fanin_total")
            if st.arena is not None:
                # one round, per-rank fold orders: the leader folds ALL
                # slots (the node total); members fold their prefix
                order = (list(range(node.size)) if nr == 0 else
                         list(range(nr + 1) if not exclusive
                              else range(nr)))
                intra = st.arena.scan(node, arr, op, order)
            else:
                if exclusive:
                    ex = base.exscan_linear(node, arr, op)
                    intra = ex
                    if nr == node.size - 1:
                        tot = np.asarray(op.host(ex, arr))
                        node._coll_isend(tot, 0, base.TAG_SCAN).wait()
                else:
                    intra = base.scan_linear(node, arr, op)
                    if nr == node.size - 1:
                        node._coll_isend(intra, 0, base.TAG_SCAN).wait()
                if nr == 0:
                    intra = node._coll_irecv(
                        None, node.size - 1, base.TAG_SCAN).wait().reshape(
                            arr.shape).astype(arr.dtype, copy=False)
        # own intra prefix: leaders carried the node TOTAL in ``intra``,
        # but their own prefix is trivial (first member of the block)
        own = ((None if exclusive else np.asarray(arr)) if nr == 0
               else intra)
        my_idx = st.node_idx_of[comm.rank]
        base_pref = None
        if st.leader is not None:
            total = intra if node.size > 1 else np.asarray(arr)
            base_pref = base.exscan_linear(
                st.leader, np.ascontiguousarray(total), op)
        if my_idx == 0:
            return own
        bp = self._intra_bcast(st, base_pref if nr == 0 else None, 0)
        bp = np.asarray(bp, arr.dtype).reshape(arr.shape)
        if own is None:
            return bp
        return np.asarray(op.host(bp, own)).reshape(arr.shape)
