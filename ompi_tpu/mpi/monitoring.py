"""Communication monitoring: per-peer, per-class message/byte counts and a
PMPI-style timing profiler.

≈ the reference's monitoring stack — pml/coll/osc ``monitoring``
interposition components + ompi/mca/common/monitoring (counts messages and
bytes per peer per class, exported as MPI_T pvars, dumped as a
communication matrix by profile2mat.pl) and the PMPI profiling layer
(ompi/mpi/c/send.c:36-38 weak symbols).

Redesign: instead of interposing a whole component layer, a Monitor
subscribes to the PML's PERUSE-style event hooks (pml.py EVT_*) and
classifies traffic by the reserved wire-tag ranges the frameworks already
use — user p2p (tag ≥ 0), collectives (blocking + nonblocking + neighbor
internal tags), one-sided (the OSC tag window).  The same numbers the
reference gathers, with zero per-call overhead when no monitor is
attached (one list check in the PML hot path).

The :class:`Profiler` wraps a Communicator like the PMPI shim wraps MPI_*
symbols: every public method is timed and counted, the object is otherwise
transparent.
"""

from __future__ import annotations

import io as _stdio
import threading
import time
from typing import Any, Optional

import numpy as np

from ompi_tpu.mpi import pml as pml_mod
from ompi_tpu.mpi.mpit import Pvar, PvarClass, pvar_registry

__all__ = ["Monitor", "Profiler", "CLASSES", "classify_tag"]

CLASSES = ("pt2pt", "coll", "osc")

# wire tags are _INTERNAL_TAG_BASE - coll_tag for internal traffic (see
# comm.py); the coll-tag windows are: blocking coll 1..63, nbc 64..499,
# osc 500..699, neighbor 700..891
_OSC_LO, _OSC_HI = 500, 699


def classify_tag(wire_tag: int) -> str:
    """Map a wire tag to a monitoring class (≈ the reference attributing
    traffic to the pml/coll/osc monitoring component that saw it)."""
    if wire_tag >= 0:
        return "pt2pt"
    coll_tag = -1000 - wire_tag          # invert comm.py's encoding
    if _OSC_LO <= coll_tag <= _OSC_HI:
        return "osc"
    return "coll"


class Monitor:
    """Attached to one rank's PML; counts sent/received messages+bytes per
    peer per class (the common_monitoring matrices)."""

    def __init__(self, pml, nranks: int,
                 register_pvars: bool = False) -> None:
        self.pml = pml
        self.nranks = nranks
        self._lock = threading.Lock()
        z = lambda: np.zeros(nranks, dtype=np.int64)  # noqa: E731
        self.sent_count = {c: z() for c in CLASSES}
        self.sent_bytes = {c: z() for c in CLASSES}
        self.recv_count = {c: z() for c in CLASSES}
        self.recv_bytes = {c: z() for c in CLASSES}
        self.unexpected = 0              # frames queued unmatched
        self.matched = 0
        self._attached = False
        self._register = register_pvars
        self._pvar_names: list[str] = []

    # -- attachment --------------------------------------------------------

    def attach(self) -> "Monitor":
        if not self._attached:
            if self._register and not self._pvar_names:
                self._register_pvars()  # re-export on every (re)attach
            self.pml.add_listener(self._on_event)
            self._attached = True
        return self

    def detach(self) -> None:
        # flip the flag under our own lock FIRST: an event already drained
        # from the PML queue on another thread then becomes a no-op, so
        # counts are deterministically frozen when detach() returns
        with self._lock:
            self._attached = False
        try:
            self.pml.remove_listener(self._on_event)
        except ValueError:
            pass
        for name in self._pvar_names:
            pvar_registry.unregister(name)
        self._pvar_names.clear()

    def __enter__(self) -> "Monitor":
        return self.attach()

    def __exit__(self, *exc) -> None:
        self.detach()

    # -- event sink --------------------------------------------------------

    def _on_event(self, event: str, info: dict) -> None:
        with self._lock:
            if not self._attached:   # late dispatch after detach(): drop
                return
            if event == pml_mod.EVT_SEND_POST:
                cls = classify_tag(info["tag"])
                peer = info["peer"]
                if 0 <= peer < self.nranks:
                    self.sent_count[cls][peer] += 1
                    self.sent_bytes[cls][peer] += info["nbytes"]
            elif event == pml_mod.EVT_DELIVER:
                cls = classify_tag(info["tag"])
                peer = info["peer"]
                if 0 <= peer < self.nranks:
                    self.recv_count[cls][peer] += 1
                    self.recv_bytes[cls][peer] += info["nbytes"]
            elif event == pml_mod.EVT_UNEXPECTED:
                self.unexpected += 1
            elif event == pml_mod.EVT_MATCH:
                self.matched += 1

    # -- MPI_T export ------------------------------------------------------

    def _register_pvars(self) -> None:
        rank = self.pml.rank
        # both directions + the matching-engine counters, so the MPI_T
        # view carries the same information as the matrices (the
        # reference's common_monitoring exports recv pvars too)
        specs = [
            (f"pml_monitoring_messages_count_{rank}", "messages",
             lambda m: int(sum(a.sum() for a in m.sent_count.values()))),
            (f"pml_monitoring_messages_size_{rank}", "bytes",
             lambda m: int(sum(a.sum() for a in m.sent_bytes.values()))),
            (f"pml_monitoring_messages_recv_count_{rank}", "messages",
             lambda m: int(sum(a.sum() for a in m.recv_count.values()))),
            (f"pml_monitoring_messages_recv_size_{rank}", "bytes",
             lambda m: int(sum(a.sum() for a in m.recv_bytes.values()))),
            (f"pml_monitoring_unexpected_{rank}", "messages",
             lambda m: m.unexpected),
            (f"pml_monitoring_matched_{rank}", "messages",
             lambda m: m.matched),
        ]
        try:
            for name, unit, fn in specs:
                # strict register: a second exporting Monitor on the same
                # rank would otherwise read (and on detach, destroy) the
                # first one's pvars — make the conflict loud instead
                pvar_registry.register(Pvar(
                    name, PvarClass.COUNTER, unit=unit,
                    description="monitoring counter",
                    read_fn=lambda m, fn=fn: fn(m if m is not None
                                                else self),
                ))
                self._pvar_names.append(name)
        except Exception:
            for name in self._pvar_names:
                pvar_registry.unregister(name)
            self._pvar_names.clear()
            raise

    # -- reporting (profile2mat equivalent) --------------------------------

    def totals(self) -> dict:
        with self._lock:
            return {
                "sent_count": {c: int(v.sum())
                               for c, v in self.sent_count.items()},
                "sent_bytes": {c: int(v.sum())
                               for c, v in self.sent_bytes.items()},
                "recv_count": {c: int(v.sum())
                               for c, v in self.recv_count.items()},
                "recv_bytes": {c: int(v.sum())
                               for c, v in self.recv_bytes.items()},
                "unexpected": self.unexpected,
                "matched": self.matched,
            }

    def matrices(self) -> dict:
        """All four per-peer matrices as one nested dict —
        ``{what: {class: int64 array of len nranks}}`` plus the scalar
        engine counters.  Copies, taken under the lock: callers may keep
        the result across a detach()/attach() cycle."""
        with self._lock:
            out: dict = {
                what: {c: getattr(self, what)[c].copy() for c in CLASSES}
                for what in ("sent_count", "sent_bytes",
                             "recv_count", "recv_bytes")
            }
            out["unexpected"] = self.unexpected
            out["matched"] = self.matched
        return out

    def row(self, what: str = "sent_bytes",
            cls: Optional[str] = None) -> np.ndarray:
        """This rank's row of the communication matrix: per-peer totals
        (sum over classes unless one is named)."""
        store = getattr(self, what)
        with self._lock:
            if cls is not None:
                return store[cls].copy()
            return sum(store.values()).astype(np.int64)

    def dump(self, stream=None) -> str:
        """Human-readable per-peer table (≈ profile2mat.pl output)."""
        out = stream or _stdio.StringIO()
        print(f"# monitoring rank {self.pml.rank} "
              f"({self.nranks} peers)", file=out)
        for cls in CLASSES:
            sc, sb = self.sent_count[cls], self.sent_bytes[cls]
            if sc.sum() == 0:
                continue
            for peer in range(self.nranks):
                if sc[peer]:
                    print(f"{cls} -> {peer}: {int(sc[peer])} msgs "
                          f"{int(sb[peer])} B", file=out)
        return out.getvalue() if stream is None else ""


def gather_matrix(comm, monitor: Monitor,
                  what: str = "sent_bytes") -> Optional[np.ndarray]:
    """Collectively assemble the full N×N communication matrix on rank 0
    (row r = what rank r sent to each peer)."""
    rows = comm.gather(monitor.row(what), root=0)
    if comm.rank != 0:
        return None
    return np.asarray(rows).reshape(comm.size, monitor.nranks)


class Profiler:
    """PMPI-layer equivalent: a transparent Communicator proxy that counts
    calls and accumulates wall time per method name."""

    def __init__(self, comm) -> None:
        self._comm = comm
        self._lock = threading.Lock()
        self.calls: dict[str, int] = {}
        self.seconds: dict[str, float] = {}

    def __getattr__(self, name: str) -> Any:
        target = getattr(self._comm, name)
        if not callable(target):
            return target

        def timed(*a, **kw):
            t0 = time.perf_counter()
            try:
                return target(*a, **kw)
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self.calls[name] = self.calls.get(name, 0) + 1
                    self.seconds[name] = self.seconds.get(name, 0.0) + dt

        return timed

    def report(self) -> dict[str, tuple[int, float]]:
        with self._lock:
            return {k: (self.calls[k], self.seconds[k])
                    for k in sorted(self.calls)}
