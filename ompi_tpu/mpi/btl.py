"""BTL — byte transfer layer for the host path.

≈ opal/mca/btl (btl.h:1170-1228; send :891): moves opaque frames between
ranks.  The PML above it owns MPI semantics (matching, protocols); a BTL just
delivers (header, payload) frames reliably and in order per sender.

Components:
- ``tcp``  — sockets between ranks; addresses exchanged via the PMIx modex
  (the reference's btl/tcp + business-card flow).  Each rank dials peers
  lazily and uses dialed connections for sending only; inbound connections
  (identified by a hello frame) are receive-only.  Two simplex pipes per pair
  avoid connection races entirely.
- ``self`` — loopback fast path (≈ btl/self): frames to one's own rank are
  delivered by direct callback, no sockets.

Device buffers never travel through a BTL: the device path is XLA collectives
(SURVEY.md §2.6 — the btl/tpu role is played by ICI itself).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Optional

from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.mpi.constants import MPIException

__all__ = ["btl_framework", "TcpBTL", "SelfBTL", "ShmBTLComponent",
           "BtlEndpoint"]

_log = output.get_stream("btl")

btl_framework = Framework("btl", "byte transfer layer")

register_var("btl", "tcp_sndbuf", VarType.SIZE, 0,
             "SO_SNDBUF for btl/tcp sockets (0 = OS default)")
register_var("btl", "tcp_rcvbuf", VarType.SIZE, 0,
             "SO_RCVBUF for btl/tcp sockets (0 = OS default)")

# frame = 4B LE total length | DSS(header dict) | raw payload
# header keys are short strings; payload is raw bytes (not DSS-wrapped, to
# avoid copying large buffers through the serializer)

OnFrame = Callable[[int, dict, bytes], None]


def _send_all(sock: socket.socket, *parts: bytes) -> None:
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class TcpBTL:
    """TCP frame transport between the ranks of one job."""

    def __init__(self, rank: int, on_frame: OnFrame,
                 host: str = "127.0.0.1") -> None:
        self.rank = rank
        self.on_frame = on_frame
        self._listener = socket.create_server((host, 0), backlog=64)
        self._addr = f"{host}:{self._listener.getsockname()[1]}"
        self._out: dict[int, socket.socket] = {}
        self._out_locks: dict[int, threading.Lock] = {}
        self._peers: dict[int, str] = {}
        self._alias: dict[int, int] = {}  # peer → my id in peer's namespace
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, name=f"btl-accept-{rank}",
                             daemon=True)
        t.start()
        self._threads.append(t)

    @property
    def address(self) -> str:
        """The business card to publish in the modex."""
        return self._addr

    def set_peers(self, peers: dict[int, str]) -> None:
        """Install the modex results: world rank → address."""
        with self._lock:
            self._peers.update(peers)

    def set_alias(self, peer: int, my_id: int) -> None:
        """Announce myself to `peer` as `my_id` instead of my own rank.

        Needed by dynamic process management: two independently-launched
        jobs each number their ranks from 0, so a connected job's procs
        are installed under translated ids (offset past the local world)
        — and must introduce themselves under that translated id when
        dialing (the hello frame is what the acceptor keys frames by).
        """
        with self._lock:
            self._alias[peer] = my_id

    # -- sending -----------------------------------------------------------

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        """Deliver one frame to `peer`. Blocking on socket backpressure;
        in-order per (self → peer)."""
        sock, lock = self._peer_sock(peer)
        hdr = dss.pack(header)
        total = len(hdr) + len(payload)
        with lock:
            _send_all(sock, struct.pack("<II", total, len(hdr)), hdr, payload)

    def _peer_sock(self, peer: int) -> tuple[socket.socket, threading.Lock]:
        with self._lock:
            sock = self._out.get(peer)
            if sock is not None:
                return sock, self._out_locks[peer]
            addr = self._peers.get(peer)
        if addr is None:
            raise ConnectionError(
                f"btl/tcp: no address for rank {peer} (modex incomplete)")
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        for opt, var in ((socket.SO_SNDBUF, "btl_tcp_sndbuf"),
                         (socket.SO_RCVBUF, "btl_tcp_rcvbuf")):
            v = var_registry.get(var)
            if v:
                sock.setsockopt(socket.SOL_SOCKET, opt, v)
        # hello frame identifies us to the acceptor (under the alias the
        # acceptor knows us by, for cross-job connections)
        with self._lock:
            my_id = self._alias.get(peer, self.rank)
        hello = dss.pack({"hello": my_id})
        _send_all(sock, struct.pack("<II", len(hello), len(hello)), hello)
        with self._lock:
            # lost the race with another sender thread? keep the first
            existing = self._out.get(peer)
            if existing is not None:
                sock.close()
                return existing, self._out_locks[peer]
            self._out[peer] = sock
            self._out_locks[peer] = threading.Lock()
            return sock, self._out_locks[peer]

    # -- receiving ---------------------------------------------------------

    def _accept_loop(self) -> None:
        try:
            self._listener.settimeout(0.2)
        except OSError:
            return   # close() won the race before the thread started
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _read_loop(self, conn: socket.socket) -> None:
        peer = -1
        with conn:
            while not self._stop.is_set():
                hdr8 = _recv_exact(conn, 8)
                if hdr8 is None:
                    return
                total, hdr_len = struct.unpack("<II", hdr8)
                blob = _recv_exact(conn, total)
                if blob is None:
                    return
                header = dss.unpack(blob[:hdr_len], n=1)[0]
                payload = blob[hdr_len:]
                if "hello" in header:
                    peer = header["hello"]
                    continue
                self.on_frame(peer, header, payload)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            for sock in self._out.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._out.clear()


class SelfBTL:
    """Loopback delivery (≈ btl/self): frames to self never touch a socket."""

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        self.rank = rank
        self.on_frame = on_frame

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        assert peer == self.rank
        self.on_frame(self.rank, header, payload)


@btl_framework.component
class TcpBTLComponent(Component):
    NAME = "tcp"
    PRIORITY = 10

    def create(self, rank: int, on_frame: OnFrame) -> TcpBTL:
        return TcpBTL(rank, on_frame)


@btl_framework.component
class SelfBTLComponent(Component):
    NAME = "self"
    PRIORITY = 90

    def create(self, rank: int, on_frame: OnFrame) -> SelfBTL:
        return SelfBTL(rank, on_frame)


@btl_framework.component
class ShmBTLComponent(Component):
    """Shared-memory rings for same-host ranks (≈ btl/vader — priority
    between self and tcp, exactly the reference's exclusivity ordering:
    btl_vader_component.c:61-69)."""

    NAME = "shm"
    PRIORITY = 50

    def create(self, rank: int, on_frame: OnFrame):
        from ompi_tpu.mpi.btl_shm import ShmBTL

        return ShmBTL(rank, on_frame)


class BtlEndpoint:
    """Per-job BTL multiplexer (≈ bml/r2, bml.h:220-232): routes each frame
    to the best reachable BTL — self for loopback, shm rings for same-host
    peers, tcp otherwise.  MCA selection on the btl framework (``--mca btl
    ^shm``, ``--mca btl self,tcp``) gates which transports are built; the
    self BTL is always on (loopback is load-bearing for COMM_SELF and
    collective self-sends, like coll/self in the reference)."""

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        self.rank = rank
        enabled = {c.NAME for c in btl_framework._eligible()}
        self.self_btl = SelfBTL(rank, on_frame)
        self.tcp_btl = TcpBTL(rank, on_frame) if "tcp" in enabled else None
        self.shm_btl = None
        if "shm" in enabled:
            from ompi_tpu.mpi.btl_shm import ShmBTL

            self.shm_btl = ShmBTL(rank, on_frame)
        if self.tcp_btl is None and self.shm_btl is None:
            raise MPIException(
                "btl selection leaves no transport for remote peers "
                "(need tcp and/or shm)")
        self._cards: dict[int, str] = {}   # peer → full business card
        self._shm_ok: set[int] = set()     # peers with a live shm route

    @property
    def address(self) -> str:
        """The combined business card: tcp address (``-`` when tcp is
        disabled), plus the shm card when that transport is enabled."""
        tcp = self.tcp_btl.address if self.tcp_btl is not None else "-"
        if self.shm_btl is None:
            return tcp
        return f"{tcp};shm={self.shm_btl.address}"

    @staticmethod
    def _split_card(card: str) -> tuple[str, Optional[str]]:
        tcp, _, rest = card.partition(";shm=")
        return tcp, (rest or None)

    def set_peers(self, peers: dict[int, str]) -> None:
        self._cards.update(peers)
        if self.tcp_btl is not None:
            self.tcp_btl.set_peers(
                {p: self._split_card(c)[0] for p, c in peers.items()})

    def set_alias(self, peer: int, my_id: int) -> None:
        if self.tcp_btl is not None:
            self.tcp_btl.set_alias(peer, my_id)
        if self.shm_btl is not None:
            self.shm_btl.set_alias(peer, my_id)

    def max_peer_id(self) -> int:
        """Highest peer id this endpoint knows (for dpm namespace bases)."""
        if self.tcp_btl is None:
            return max(self._cards, default=-1)
        with self.tcp_btl._lock:
            return max(self.tcp_btl._peers, default=-1)

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        if peer == self.rank:
            self.self_btl.send(peer, header, payload)
            return
        if self.shm_btl is not None:
            # steady state: one set lookup, then straight into the ring
            if peer in self._shm_ok or self._shm_route(peer):
                from ompi_tpu.mpi.btl_shm import FrameTooBig

                try:
                    self.shm_btl.send(peer, header, payload)
                    return
                except FrameTooBig:
                    pass   # oversize frame rides tcp; PML seq reorders
        if self.tcp_btl is None:
            raise MPIException(
                f"no btl route to rank {peer}: tcp is disabled and the "
                f"peer is not shm-reachable")
        self.tcp_btl.send(peer, header, payload)

    def _shm_route(self, peer: int) -> bool:
        shm_card = self._split_card(self._cards.get(peer, ""))[1]
        if shm_card and self.shm_btl.connect(peer, shm_card):
            self._shm_ok.add(peer)
            return True
        return False

    def close(self) -> None:
        if self.tcp_btl is not None:
            self.tcp_btl.close()
        if self.shm_btl is not None:
            self.shm_btl.close()
