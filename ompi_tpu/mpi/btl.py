"""BTL — byte transfer layer for the host path.

≈ opal/mca/btl (btl.h:1170-1228; send :891): moves opaque frames between
ranks.  The PML above it owns MPI semantics (matching, protocols); a BTL just
delivers (header, payload) frames reliably and in order per sender.

Components:
- ``tcp``  — sockets between ranks; addresses exchanged via the PMIx modex
  (the reference's btl/tcp + business-card flow).  Each rank dials peers
  lazily and uses dialed connections for sending only; inbound connections
  (identified by a hello frame) are receive-only.  Two simplex pipes per pair
  avoid connection races entirely.
- ``self`` — loopback fast path (≈ btl/self): frames to one's own rank are
  delivered by direct callback, no sockets.

Device buffers never travel through a BTL: the device path is XLA collectives
(SURVEY.md §2.6 — the btl/tpu role is played by ICI itself).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Optional

from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.constants import MPIException

__all__ = ["btl_framework", "TcpBTL", "SelfBTL", "ShmBTLComponent",
           "BtlEndpoint"]

_log = output.get_stream("btl")

btl_framework = Framework("btl", "byte transfer layer")

register_var("btl", "tcp_sndbuf", VarType.SIZE, 0,
             "SO_SNDBUF for btl/tcp sockets (0 = OS default)")
register_var("btl", "tcp_rcvbuf", VarType.SIZE, 0,
             "SO_RCVBUF for btl/tcp sockets (0 = OS default)")

# frame = 4B LE total length | DSS(header dict) | raw payload
# header keys are short strings; payload is raw bytes (not DSS-wrapped, to
# avoid copying large buffers through the serializer)

OnFrame = Callable[[int, dict, bytes], None]


def _send_all(sock: socket.socket, *parts) -> None:
    """Scatter-gather send: no join copy of the payload (a rendezvous
    fragment is ~1MiB — the old b''.join doubled its memory traffic).
    Falls back across partial sends by re-slicing the iovec."""
    iov = [memoryview(p).cast("B") for p in parts if len(p)]
    while iov:
        try:
            sent = sock.sendmsg(iov)
        except AttributeError:  # platform without sendmsg
            sock.sendall(b"".join(iov))
            return
        # drop fully-sent buffers, trim the partial one
        while iov and sent >= len(iov[0]):
            sent -= len(iov[0])
            iov.pop(0)
        if iov and sent:
            iov[0] = iov[0][sent:]


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class TcpBTL:
    """TCP frame transport between the ranks of one job."""

    def __init__(self, rank: int, on_frame: OnFrame,
                 host: str = "127.0.0.1") -> None:
        self.rank = rank
        self.on_frame = on_frame
        self._listener = socket.create_server((host, 0), backlog=64)
        self._addr = f"{host}:{self._listener.getsockname()[1]}"
        self._out: dict[int, socket.socket] = {}
        self._out_locks: dict[int, threading.Lock] = {}
        self._peers: dict[int, str] = {}
        self._alias: dict[int, int] = {}  # peer → my id in peer's namespace
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, name=f"btl-accept-{rank}",
                             daemon=True)
        t.start()
        self._threads.append(t)

    @property
    def address(self) -> str:
        """The business card to publish in the modex."""
        return self._addr

    def set_peers(self, peers: dict[int, str]) -> None:
        """Install the modex results: world rank → address."""
        with self._lock:
            self._peers.update(peers)

    def set_alias(self, peer: int, my_id: int) -> None:
        """Announce myself to `peer` as `my_id` instead of my own rank.

        Needed by dynamic process management: two independently-launched
        jobs each number their ranks from 0, so a connected job's procs
        are installed under translated ids (offset past the local world)
        — and must introduce themselves under that translated id when
        dialing (the hello frame is what the acceptor keys frames by).
        """
        with self._lock:
            self._alias[peer] = my_id

    # -- sending -----------------------------------------------------------

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        """Deliver one frame to `peer`. Blocking on socket backpressure;
        in-order per (self → peer)."""
        sock, lock = self._peer_sock(peer)
        hdr = dss.pack(header)
        total = len(hdr) + len(payload)
        with lock:
            _send_all(sock, struct.pack("<II", total, len(hdr)), hdr, payload)

    def _peer_sock(self, peer: int) -> tuple[socket.socket, threading.Lock]:
        with self._lock:
            sock = self._out.get(peer)
            if sock is not None:
                return sock, self._out_locks[peer]
            addr = self._peers.get(peer)
        if addr is None:
            raise ConnectionError(
                f"btl/tcp: no address for rank {peer} (modex incomplete)")
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        for opt, var in ((socket.SO_SNDBUF, "btl_tcp_sndbuf"),
                         (socket.SO_RCVBUF, "btl_tcp_rcvbuf")):
            v = var_registry.get(var)
            if v:
                sock.setsockopt(socket.SOL_SOCKET, opt, v)
        # hello frame identifies us to the acceptor (under the alias the
        # acceptor knows us by, for cross-job connections)
        with self._lock:
            my_id = self._alias.get(peer, self.rank)
        hello = dss.pack({"hello": my_id})
        _send_all(sock, struct.pack("<II", len(hello), len(hello)), hello)
        with self._lock:
            # lost the race with another sender thread? keep the first
            existing = self._out.get(peer)
            if existing is not None:
                sock.close()
                return existing, self._out_locks[peer]
            self._out[peer] = sock
            self._out_locks[peer] = threading.Lock()
            return sock, self._out_locks[peer]

    # -- receiving ---------------------------------------------------------

    def _accept_loop(self) -> None:
        try:
            self._listener.settimeout(0.2)
        except OSError:
            return   # close() won the race before the thread started
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _read_loop(self, conn: socket.socket) -> None:
        peer = -1
        with conn:
            while not self._stop.is_set():
                hdr8 = _recv_exact(conn, 8)
                if hdr8 is None:
                    return
                total, hdr_len = struct.unpack("<II", hdr8)
                blob = _recv_exact(conn, total)
                if blob is None:
                    return
                header = dss.unpack(blob[:hdr_len], n=1)[0]
                payload = blob[hdr_len:]
                if "hello" in header:
                    peer = header["hello"]
                    continue
                self.on_frame(peer, header, payload)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            for sock in self._out.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._out.clear()


class SelfBTL:
    """Loopback delivery (≈ btl/self): frames to self never touch a socket."""

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        self.rank = rank
        self.on_frame = on_frame

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        assert peer == self.rank
        self.on_frame(self.rank, header, payload)


class ProcBTL:
    """Same-process direct delivery — the degenerate single-copy case of
    vader's xpmem mode (btl_vader_component.c:61-69): when two ranks share
    an address space (threads-as-ranks harness, in-process jobs) a frame
    is ONE direct call into the peer's frame handler — no ring, no poller
    wakeup, no serialization of the payload.  The PML's per-(peer, cid)
    sequence numbers keep ordering correct when mixed with other BTLs.

    Endpoints register in a process-global table under a unique token;
    the business card is ``pid:token`` and reachability is pid equality.
    """

    _registry: dict[int, "ProcBTL"] = {}
    _next_token = iter(range(1, 1 << 62))
    _reg_lock = threading.Lock()

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        import os

        self.rank = rank
        self.on_frame = on_frame
        # optional compiled fast lane: (peer, tag, cid, seq, payload) →
        # bool, installed by the owning PML when its matching engine is
        # native — delivers with no header object at all
        self.on_fast = None
        self._alias: dict[int, int] = {}
        self._peer_tokens: dict[int, int] = {}
        # honor simulated host identities: sim-plm ranks on different
        # fake hosts must NOT short-circuit through the address space
        from ompi_tpu.core.sysinfo import host_identity

        self.hostname = host_identity()
        with ProcBTL._reg_lock:
            self.token = next(ProcBTL._next_token)
            ProcBTL._registry[self.token] = self
        self.address = f"{os.getpid()}:{self.token}:{self.hostname}"

    def set_alias(self, peer: int, my_id: int) -> None:
        self._alias[peer] = my_id

    def can_reach(self, card: str) -> bool:
        import os

        try:
            pid, token, host = card.split(":", 2)
        except ValueError:
            return False
        return (pid == str(os.getpid()) and host == self.hostname
                and int(token) in ProcBTL._registry)

    def connect(self, peer: int, card: str) -> bool:
        if not self.can_reach(card):
            return False
        self._peer_tokens[peer] = int(card.split(":", 2)[1])
        return True

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        target = ProcBTL._registry.get(self._peer_tokens[peer])
        if target is None:
            raise ConnectionError(f"btl/proc: peer {peer} endpoint closed")
        target.on_frame(self._alias.get(peer, self.rank), header, payload)

    def send_fast(self, peer: int, tag: int, cid: int, seq: int,
                  payload, dt, elems: int, shp) -> bool:
        """Header-free delivery into the peer's compiled engine; False ⇒
        the peer declined (no engine, fencing active, out-of-order) and
        the caller re-sends the same frame via the header path.  dt/
        elems/shp are the scalar header fields the engine materializes
        only when it must (unexpected storage, allocate-on-match)."""
        target = ProcBTL._registry.get(self._peer_tokens.get(peer, -1))
        if target is None or target.on_fast is None:
            return False
        return target.on_fast(self._alias.get(peer, self.rank),
                              tag, cid, seq, payload, dt, elems, shp)

    def close(self) -> None:
        with ProcBTL._reg_lock:
            ProcBTL._registry.pop(self.token, None)


@btl_framework.component
class TcpBTLComponent(Component):
    NAME = "tcp"
    PRIORITY = 10

    def create(self, rank: int, on_frame: OnFrame) -> TcpBTL:
        return TcpBTL(rank, on_frame)


@btl_framework.component
class SelfBTLComponent(Component):
    NAME = "self"
    PRIORITY = 90

    def create(self, rank: int, on_frame: OnFrame) -> SelfBTL:
        return SelfBTL(rank, on_frame)


@btl_framework.component
class ProcBTLComponent(Component):
    """Same-address-space direct delivery (≈ vader's xpmem single-copy
    mode degenerated to zero-copy calls) — priority above shm: when ranks
    share a process, a function call beats a ring."""

    NAME = "proc"
    PRIORITY = 70

    def create(self, rank: int, on_frame: OnFrame) -> ProcBTL:
        return ProcBTL(rank, on_frame)


@btl_framework.component
class ShmBTLComponent(Component):
    """Shared-memory rings for same-host ranks (≈ btl/vader — priority
    between self and tcp, exactly the reference's exclusivity ordering:
    btl_vader_component.c:61-69)."""

    NAME = "shm"
    PRIORITY = 50

    def create(self, rank: int, on_frame: OnFrame):
        from ompi_tpu.mpi.btl_shm import ShmBTL

        return ShmBTL(rank, on_frame)


class BtlEndpoint:
    """Per-job BTL multiplexer (≈ bml/r2, bml.h:220-232): routes each frame
    to the best reachable BTL — self for loopback, shm rings for same-host
    peers, tcp otherwise.  MCA selection on the btl framework (``--mca btl
    ^shm``, ``--mca btl self,tcp``) gates which transports are built; the
    self BTL is always on (loopback is load-bearing for COMM_SELF and
    collective self-sends, like coll/self in the reference)."""

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        self.rank = rank
        enabled = {c.NAME for c in btl_framework._eligible()}
        self.self_btl = SelfBTL(rank, on_frame)
        self.tcp_btl = TcpBTL(rank, on_frame) if "tcp" in enabled else None
        self.shm_btl = None
        if "shm" in enabled:
            from ompi_tpu.mpi.btl_shm import ShmBTL

            self.shm_btl = ShmBTL(rank, on_frame)
        self.proc_btl = ProcBTL(rank, on_frame) if "proc" in enabled else None
        if self.tcp_btl is None and self.shm_btl is None:
            raise MPIException(
                "btl selection leaves no transport for remote peers "
                "(need tcp and/or shm)")
        self._cards: dict[int, str] = {}   # peer → full business card
        self._shm_ok: set[int] = set()     # peers with a live shm route
        self._proc_ok: set[int] = set()    # peers in my address space
        self._proc_no: set[int] = set()    # known peers that are NOT
        # deterministic chaos (ompi_tpu.testing.faultinject): when a
        # fault plan is armed, every header-path frame gets a seeded
        # drop/delay/dup verdict at this boundary.  None in production —
        # the hot path pays one attribute check.
        self._fault = None
        from ompi_tpu.testing import faultinject

        if faultinject.active():
            self._fault = faultinject.injector_for(rank)

    @property
    def address(self) -> str:
        """The combined business card: tcp address (``-`` when tcp is
        disabled), plus a segment per enabled same-host transport."""
        card = self.tcp_btl.address if self.tcp_btl is not None else "-"
        if self.shm_btl is not None:
            card += f";shm={self.shm_btl.address}"
        if self.proc_btl is not None:
            card += f";proc={self.proc_btl.address}"
        return card

    @staticmethod
    def _split_card(card: str) -> tuple[str, Optional[str], Optional[str]]:
        """→ (tcp, shm segment, proc segment)."""
        parts = card.split(";")
        tcp, shm, proc = parts[0], None, None
        for p in parts[1:]:
            if p.startswith("shm="):
                shm = p[4:]
            elif p.startswith("proc="):
                proc = p[5:]
        return tcp, shm, proc

    def set_peers(self, peers: dict[int, str]) -> None:
        self._cards.update(peers)
        if self.tcp_btl is not None:
            self.tcp_btl.set_peers(
                {p: self._split_card(c)[0] for p, c in peers.items()})

    def set_alias(self, peer: int, my_id: int) -> None:
        if self.tcp_btl is not None:
            self.tcp_btl.set_alias(peer, my_id)
        if self.shm_btl is not None:
            self.shm_btl.set_alias(peer, my_id)
        if self.proc_btl is not None:
            self.proc_btl.set_alias(peer, my_id)

    def peer_alive(self, peer: int) -> Optional[bool]:
        """Same-host pid-liveness: route the question to the shm BTL's
        shared, rate-limited probe (the pid travels in the peer's shm
        business-card segment).  None when unknowable — remote peer, shm
        disabled, or no pid in the card — True/False otherwise."""
        if self.shm_btl is None or peer == self.rank:
            return None if self.shm_btl is None else True
        card = self._cards.get(peer)
        shm_seg = self._split_card(card)[1] if card else None
        return self.shm_btl.probe_alive(peer, shm_seg)

    def max_peer_id(self) -> int:
        """Highest peer id this endpoint knows (for dpm namespace bases)."""
        if self.tcp_btl is None:
            return max(self._cards, default=-1)
        with self.tcp_btl._lock:
            return max(self.tcp_btl._peers, default=-1)

    def try_send_inline(self, peer: int, header: dict,
                        payload: bytes = b"") -> bool:
        """Inline fast path (≈ mca_bml_base_sendi → btl_sendi,
        pml_ob1_isend.c:89-119): deliver the frame on the CALLER's thread
        when it cannot block — self loopback always, shm when the ring has
        room.  False ⇒ caller enqueues for the send worker.  Safe to mix
        with queued sends: the PML reorders by per-(peer,cid) sequence."""
        if self._fault is not None and peer != self.rank:
            verdict = self._fault.on_frame(peer, header)
            if verdict != "send":
                # the verdict is identity-hashed: the worker path would
                # draw the SAME verdict, so resolve it here (True = the
                # frame's fate is sealed; nothing for the worker to do)
                self._apply_fault(verdict, peer, header, payload)
                return True
        ok = self._try_send_inline(peer, header, payload)
        if ok and trace_mod.active:
            # AFTER success only: a declined inline attempt is re-sent by
            # the worker (whose endpoint.send emits its own instant) — an
            # entry-time emit would trace that frame twice
            trace_mod.instant("btl", "send_inline", rank=self.rank,
                              peer=peer, nbytes=len(payload),
                              t=header.get("t"))
        return ok

    def _try_send_inline(self, peer: int, header: dict,
                         payload: bytes = b"") -> bool:
        if peer == self.rank:
            self.self_btl.send(peer, header, payload)
            return True
        if self.proc_btl is not None and (peer in self._proc_ok
                                          or self._proc_route(peer)):
            self.proc_btl.send(peer, header, payload)
            return True
        if self.shm_btl is not None and (peer in self._shm_ok
                                         or self._shm_route(peer)):
            from ompi_tpu.mpi.btl_shm import FrameTooBig, PeerDeadError

            try:
                return self.shm_btl.try_send(peer, header, payload)
            except FrameTooBig:
                return False   # worker path reroutes oversize over tcp
            except PeerDeadError:
                self._drop_shm(peer)
                return False   # worker path surfaces/retries it
        return False

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        if self._fault is not None and peer != self.rank:
            verdict = self._fault.on_frame(peer, header)
            if verdict != "send":
                self._apply_fault(verdict, peer, header, payload)
                return
        self._send_routed(peer, header, payload)

    def _apply_fault(self, verdict, peer: int, header: dict,
                     payload) -> None:
        """Execute a non-"send" chaos verdict.  drop: the frame vanishes
        (the caller believes it was sent — exactly a lossy wire).  dup:
        delivered twice (the PML's seq gate holds the duplicate).  delay:
        re-sent later off a timer, payload copied first (zero-copy views
        alias user buffers the caller is free to reuse at completion).

        Never raises: callers include try_send_inline, whose contract is
        a non-raising bool — a verdict-sealed frame that then hits a
        dead route degrades to a drop (the lossy-wire semantics the
        verdict already committed to), it does not surface a raw
        ConnectionError into application code."""
        if verdict == "drop":
            return
        if verdict == "dup":
            try:
                self._send_routed(peer, header, payload)
                self._send_routed(peer, header, payload)
            except Exception:  # noqa: BLE001 — degrade to drop
                pass
            return
        _, ms = verdict
        data = bytes(payload)

        def later() -> None:
            try:
                self._send_routed(peer, header, data)
            except Exception:  # noqa: BLE001 — a dead route ends the delay
                pass

        t = threading.Timer(ms / 1000.0, later)
        t.daemon = True
        t.start()

    def _send_routed(self, peer: int, header: dict,
                     payload: bytes = b"") -> None:
        if trace_mod.active:
            trace_mod.instant("btl", "send", rank=self.rank, peer=peer,
                              nbytes=len(payload), t=header.get("t"))
        if peer == self.rank:
            self.self_btl.send(peer, header, payload)
            return
        if self.proc_btl is not None:
            if peer in self._proc_ok or self._proc_route(peer):
                self.proc_btl.send(peer, header, payload)
                return
        oversize: Optional[BaseException] = None
        if self.shm_btl is not None:
            # steady state: one set lookup, then straight into the ring
            if peer in self._shm_ok or self._shm_route(peer):
                from ompi_tpu.mpi.btl_shm import FrameTooBig, PeerDeadError

                try:
                    self.shm_btl.send(peer, header, payload)
                    return
                except FrameTooBig as e:
                    oversize = e   # oversize frame rides tcp; PML reorders
                except PeerDeadError:
                    # stale ring of a dead/respawning peer: drop the route
                    # and surface a retryable failure — the frame must NOT
                    # be silently lost in the orphaned mapping
                    self._drop_shm(peer)
                    raise ConnectionError(
                        f"rank {peer} died (shm ring orphaned); routes "
                        f"dropped pending rebind")
        if self.tcp_btl is None:
            if oversize is not None:
                raise MPIException(
                    f"frame to rank {peer} exceeds the shm ring's "
                    f"single-frame limit ({oversize}) and tcp is disabled "
                    f"— raise --mca btl_shm_ring_size or re-enable tcp "
                    f"for oversize fallback") from oversize
            raise MPIException(
                f"no btl route to rank {peer}: tcp is disabled and the "
                f"peer is not shm-reachable")
        self.tcp_btl.send(peer, header, payload)

    def _shm_route(self, peer: int) -> bool:
        shm_card = self._split_card(self._cards.get(peer, ""))[1]
        if shm_card and self.shm_btl.connect(peer, shm_card):
            self._shm_ok.add(peer)
            return True
        return False

    def _drop_shm(self, peer: int) -> None:
        self._shm_ok.discard(peer)
        self.shm_btl.drop_peer(peer)

    def _proc_route(self, peer: int) -> bool:
        proc_card = self._split_card(self._cards.get(peer, ""))[2]
        if proc_card and self.proc_btl.connect(peer, proc_card):
            self._proc_ok.add(peer)
            return True
        if peer in self._cards:
            # a known peer that is NOT in my address space stays that
            # way — cache the miss so per-send fast-lane checks are one
            # set lookup (a respawn rebind clears it via drop routes)
            self._proc_no.add(peer)
        return False

    def rebind(self, peer: int, card: str) -> None:
        """Re-point every transport at a peer's NEW business card (the
        peer was respawned by errmgr/respawn and re-announced itself).
        Stale sockets/rings are dropped; the next send redials lazily."""
        self._cards[peer] = card
        tcp_addr, _, _ = self._split_card(card)
        if self.tcp_btl is not None:
            with self.tcp_btl._lock:
                self.tcp_btl._peers[peer] = tcp_addr
                sock = self.tcp_btl._out.pop(peer, None)
                self.tcp_btl._out_locks.pop(peer, None)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        if self.shm_btl is not None:
            self._drop_shm(peer)
        if self.proc_btl is not None:
            self._proc_ok.discard(peer)
            self._proc_no.discard(peer)
            self.proc_btl._peer_tokens.pop(peer, None)

    def close(self) -> None:
        if self.tcp_btl is not None:
            self.tcp_btl.close()
        if self.shm_btl is not None:
            self.shm_btl.close()
        if self.proc_btl is not None:
            self.proc_btl.close()
