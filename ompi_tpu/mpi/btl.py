"""BTL — byte transfer layer for the host path.

≈ opal/mca/btl (btl.h:1170-1228; send :891): moves opaque frames between
ranks.  The PML above it owns MPI semantics (matching, protocols); a BTL just
delivers (header, payload) frames reliably and in order per sender.

Components:
- ``tcp``  — sockets between ranks; addresses exchanged via the PMIx modex
  (the reference's btl/tcp + business-card flow).  Each rank dials peers
  lazily and uses dialed connections for sending only; inbound connections
  (identified by a hello frame) are receive-only.  Two simplex pipes per pair
  avoid connection races entirely.
- ``self`` — loopback fast path (≈ btl/self): frames to one's own rank are
  delivered by direct callback, no sockets.

Device buffers never travel through a BTL: the device path is XLA collectives
(SURVEY.md §2.6 — the btl/tpu role is played by ICI itself).
"""

from __future__ import annotations

import collections
import ctypes
import errno
import os
import select
import socket
import struct
import threading
import time
from typing import Callable, Optional

import numpy as np

from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.constants import MPIException

__all__ = ["btl_framework", "TcpBTL", "SelfBTL", "ShmBTLComponent",
           "BtlEndpoint"]

_log = output.get_stream("btl")

btl_framework = Framework("btl", "byte transfer layer")

register_var("btl", "tcp_sndbuf", VarType.SIZE, 0,
             "SO_SNDBUF for btl/tcp sockets (0 = OS default)")
register_var("btl", "tcp_rcvbuf", VarType.SIZE, 0,
             "SO_RCVBUF for btl/tcp sockets (0 = OS default)")
register_var("btl", "tcp_native", VarType.BOOL, True,
             "use the native GIL-released tcp plane (submission-ring "
             "writer + parked poller) when _native/net.c built; read "
             "per call, so flipping mid-run moves traffic between "
             "planes frame-by-frame (the shared-fate bench lever)")
register_var("btl", "tcp_ring_bytes", VarType.SIZE, 4 << 20,
             "per-peer submission-ring byte cap: senders park "
             "(GIL-released, FT-checked between slices) while a peer's "
             "unsent backlog sits above this")
register_var("btl", "tcp_pull", VarType.BOOL, (os.cpu_count() or 1) > 2,
             "receiver-pull progress (opal_progress style): a blocked "
             "recv waiter drains its own sockets via TcpBTL.progress() "
             "instead of sleeping on the poller's wake; wins when "
             "waiter and poller run on separate cores, loses on tiny "
             "hosts where the dual poll() wakeups just thrash")
register_var("btl", "tcp_copy_limit", VarType.SIZE, 64 << 10,
             "payload views at or below this are copied into the ring "
             "entry so send() returns immediately; larger views ride "
             "zero-copy and the sender parks until the writer drains "
             "them (buffer-reuse safety, = the eager size in practice)")

#: native-plane slice bounds — every GIL-released park is bounded and
#: the full Python FT contract re-runs between slices (Arena._wait's
#: discipline applied to the inter-node transport)
_PARK_SLICE_NS = 1_000_000        # sender backpressure / writer doorbell
_WRITER_IDLE_NS = 20_000_000      # writer idle park (futex-woken anyway)
_POLL_SLICE_NS = 50_000_000       # receive poller (poll() wakes on data)
_WRITE_SLICE_NS = 20_000_000      # one writev drain call's POLLOUT bound
_LAND_SLICE_NS = 20_000_000       # one rndv direct-landing recv bound
_SCAN_MAX = 128                   # frames per native framing scan
#: burst detector for the opportunistic same-thread write: >= _BURST_MIN
#: consecutive sends to one peer each < _BURST_GAP_NS apart are a burst
#: and route through the submission ring (batched writev amortizes the
#: syscalls); lone sends (the pingpong latency path — inter-send gap is
#: a full RTT, >= ~150us through the PML) write directly on the calling
#: thread, skipping the writer-thread hop entirely
_BURST_GAP_NS = 100_000
_BURST_MIN = 4
_CONN_BUF = 256 << 10             # per-connection staging buffer
#: a trailing partial frame at least this big lands straight into its
#: destination (rndv fragments); smaller ones (eager frames) finish in
#: the staging buffer — must stay below _CONN_BUF or staging deadlocks
_LAND_MIN = 96 << 10


#: biggest frame sent through the GIL-held (PyDLL) crossing — must fit
#: the default sndbuf so the nonblocking sendmsg all but never EAGAINs
#: while holding the interpreter
_NOGIL_MAX = 256 << 10


def _net_lib():
    """The native network executor, or None (pure-python plane)."""
    from ompi_tpu import _native

    return _native.net()


def _net_nogil_lib():
    """The GIL-held (PyDLL) handle to the same library — small-frame
    send3 only, always called with slice_ns=0 (never blocks)."""
    from ompi_tpu import _native

    return _native.net_nogil()


def _park_lib():
    """The arena executor whose futex waits back the ring doorbells."""
    from ompi_tpu import _native

    return _native.arena()

# frame = 4B LE total length | DSS(header dict) | raw payload
# header keys are short strings; payload is raw bytes (not DSS-wrapped, to
# avoid copying large buffers through the serializer)

OnFrame = Callable[[int, dict, bytes], None]


def _send_all(sock: socket.socket, *parts) -> None:
    """Scatter-gather send: no join copy of the payload (a rendezvous
    fragment is ~1MiB — the old b''.join doubled its memory traffic).
    Falls back across partial sends by re-slicing the iovec."""
    iov = [memoryview(p).cast("B") for p in parts if len(p)]
    while iov:
        try:
            sent = sock.sendmsg(iov)
        except AttributeError:  # platform without sendmsg
            sock.sendall(b"".join(iov))
            return
        # drop fully-sent buffers, trim the partial one
        while iov and sent >= len(iov[0]):
            sent -= len(iov[0])
            iov.pop(0)
        if iov and sent:
            iov[0] = iov[0][sent:]


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class _TxRing:
    """Per-peer submission ring: senders append (prefix, header,
    payload) iovec descriptors; the writer thread drains the whole
    backlog in batched GIL-released sendmsg calls.  ``ctr`` is the
    drained-ticket counter — a u64 futex word parked senders wait on
    (ring-full backpressure and zero-copy buffer-reuse waits)."""

    __slots__ = ("mu", "entries", "pending_bytes", "enq", "ctr",
                 "ctr_addr", "error", "last_send", "burst_n")

    def __init__(self) -> None:
        self.mu = threading.Lock()
        # (parts tuple, nbytes, ticket, cid) — parts are the wire
        # segments in order; cid rides along for mid-park FT checks
        self.entries: collections.deque = collections.deque()
        self.pending_bytes = 0
        self.enq = 0                       # tickets issued
        self.ctr = (ctypes.c_uint64 * 1)()  # tickets drained (futex word)
        self.ctr_addr = ctypes.addressof(self.ctr)
        self.error: Optional[BaseException] = None
        self.last_send = 0                 # monotonic ns, burst detector
        self.burst_n = 0                   # consecutive close-gap sends

    def in_burst(self) -> bool:
        """Update the burst detector with this send; True ⇒ route via
        the ring/writer (batch), False ⇒ direct write is the win.
        Racy by design (monotonic per caller is enough — a miscount
        just routes one frame the other way)."""
        now = time.monotonic_ns()
        if now - self.last_send < _BURST_GAP_NS:
            self.burst_n += 1
        else:
            self.burst_n = 0
        self.last_send = now
        return self.burst_n >= _BURST_MIN


class _Conn:
    """One accepted (receive-only) connection's poller state: a fixed
    staging buffer (never resized — its address is pinned for the
    native reads) plus the in-flight direct-landing frame, if any."""

    __slots__ = ("sock", "fd", "peer", "buf", "mv", "addr", "used",
                 "pending")

    def __init__(self, sock: socket.socket) -> None:
        from ompi_tpu import _native

        self.sock = sock
        self.fd = sock.fileno()
        self.peer = -1
        self.buf = bytearray(_CONN_BUF)
        self.mv = memoryview(self.buf)
        self.addr = _native.addr_of(self.mv)
        self.used = 0
        # [hdr, dst memoryview, dst addr, filled, payload_len, staged]
        self.pending: Optional[list] = None


class TcpBTL:
    """TCP frame transport between the ranks of one job.

    Two data planes over the SAME sockets and the same wire format:

    - the pure-python plane: per-frame ``sendmsg`` under the GIL on the
      send side, one ``_read_loop`` thread per accepted connection on
      the receive side (the pre-native behavior, kept bit-identical);
    - the native plane (``btl_tcp_native``, default on when
      ``_native/net.c`` builds): senders enqueue onto per-peer
      submission rings and a single writer thread drains whole backlogs
      in GIL-released batched ``sendmsg`` calls, while a single parked
      poller replaces every reader thread with one GIL-released
      ``poll()`` — length-prefix framing parsed natively and oversize
      (rendezvous) payloads landed straight into the plan-registered
      buffer via ``recv_sink``.

    The var is re-read per call, so the planes can be flipped
    frame-by-frame inside a live world; ``OMPI_TPU_NO_NATIVE=1`` or a
    missing toolchain pins the python plane at construction.
    """

    def __init__(self, rank: int, on_frame: OnFrame,
                 host: str = "127.0.0.1") -> None:
        self.rank = rank
        self.on_frame = on_frame
        self._listener = socket.create_server((host, 0), backlog=64)
        self._addr = f"{host}:{self._listener.getsockname()[1]}"
        self._out: dict[int, socket.socket] = {}
        self._out_locks: dict[int, threading.Lock] = {}
        self._peers: dict[int, str] = {}
        self._alias: dict[int, int] = {}  # peer → my id in peer's namespace
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # -- native-plane state --------------------------------------------
        self._native_ok = _net_lib() is not None
        # handles cached per-instance: the per-send import-machinery
        # lookup is measurable on the latency path
        self._net_h = _net_lib()
        self._net_ng = _net_nogil_lib() if self._native_ok else None
        self._rings: dict[int, _TxRing] = {}
        self._svc_mu = threading.Lock()   # one conn servicer at a time
        #: count of recv-waiters currently pulling (progress()); while
        #: nonzero the poller parks on the wake pipe only — two threads
        #: parked in poll() on the SAME fds would both wake per frame
        #: and thrash the interpreter on small hosts
        self.pull_depth = 0
        self._wctr = (ctypes.c_uint64 * 1)()   # writer doorbell futex word
        self._wctr_addr = ctypes.addressof(self._wctr)
        self._wlock = threading.Lock()
        self._writer: Optional[threading.Thread] = None
        self._writer_parked = False        # doorbell-syscall elision
        self._conns: list[_Conn] = []
        self._poller: Optional[threading.Thread] = None
        self._wake_r = self._wake_w = -1       # poller wake pipe (lazy)
        self._scan_out = (ctypes.c_uint64 * (3 * _SCAN_MAX))()
        self._scan_addr = ctypes.addressof(self._scan_out)
        # FT contract + zero-copy landing hooks, installed by the owning
        # PmlFT / PML (None ⇒ stop-flag-only parks, staged landing)
        self.ft_check: Optional[Callable[[int, Optional[int]], None]] = None
        self.recv_sink: Optional[Callable[[dict, int], object]] = None
        self.recv_sink_done: Optional[Callable[[dict, int], None]] = None
        t = threading.Thread(target=self._accept_loop, name=f"btl-accept-{rank}",
                             daemon=True)
        t.start()
        self._threads.append(t)

    @property
    def address(self) -> str:
        """The business card to publish in the modex."""
        return self._addr

    def set_peers(self, peers: dict[int, str]) -> None:
        """Install the modex results: world rank → address."""
        with self._lock:
            self._peers.update(peers)

    def set_alias(self, peer: int, my_id: int) -> None:
        """Announce myself to `peer` as `my_id` instead of my own rank.

        Needed by dynamic process management: two independently-launched
        jobs each number their ranks from 0, so a connected job's procs
        are installed under translated ids (offset past the local world)
        — and must introduce themselves under that translated id when
        dialing (the hello frame is what the acceptor keys frames by).
        """
        with self._lock:
            self._alias[peer] = my_id

    # -- sending -----------------------------------------------------------

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        """Deliver one frame to `peer`. Blocking on socket backpressure;
        in-order per (self → peer)."""
        sock, lock = self._peer_sock(peer)
        hdr = dss.pack(header)
        total = len(hdr) + len(payload)
        prefix = struct.pack("<II", total, len(hdr))
        if self._native_ok and var_registry.get("btl_tcp_native"):
            self._send_native(peer, header.get("cid"), prefix, hdr,
                              payload, sock, lock)
            return
        with lock:
            # FIFO across plane flips: anything the native plane still
            # holds for this peer goes out first, under the same lock
            self._flush_ring_locked(peer, sock)
            _send_all(sock, prefix, hdr, payload)

    def try_send(self, peer: int, header: dict,
                 payload: bytes = b"") -> bool:
        """Nonblocking inline enqueue onto the native submission ring
        (≈ btl_sendi): True ⇒ the frame is queued for the writer and the
        caller's buffer is immediately reusable (bytes ride as-is, small
        views are copied).  False ⇒ no native plane, no live socket yet
        (dialing blocks), ring full, or an oversize view — the caller
        takes the worker path."""
        if not self._native_ok or not var_registry.get("btl_tcp_native"):
            return False
        nbytes = len(payload)
        with self._lock:
            if peer not in self._out:
                return False
        ring = self._ring(peer)
        hdr = dss.pack(header)
        prefix = struct.pack("<II", len(hdr) + nbytes, len(hdr))
        parts = (prefix, hdr, payload) if nbytes else (prefix, hdr)
        if not ring.in_burst():
            # synchronous write ⇒ no copy needed even for views: the
            # caller's buffer is back in its hands before we return
            done = self._direct_write(peer, ring, parts,
                                      raise_errors=False)
            if done is not None:
                return done
        # ring path: the entry outlives this call, so views need an
        # owned copy (bounded by copy_limit; bigger views park in
        # send(), which inline must not)
        if nbytes and not isinstance(payload, bytes):
            if nbytes > int(var_registry.get("btl_tcp_copy_limit") or 0):
                return False
            payload = bytes(payload)
            parts = (prefix, hdr, payload)
        nb = 8 + len(hdr) + nbytes
        cap = int(var_registry.get("btl_tcp_ring_bytes") or (4 << 20))
        with ring.mu:
            if ring.error is not None:
                return False   # worker path surfaces the failure
            if ring.entries and ring.pending_bytes + nb > cap:
                return False
            ring.enq += 1
            ring.entries.append((parts, nb, ring.enq,
                                 header.get("cid")))
            ring.pending_bytes += nb
        self._kick_writer()
        return True

    def _direct_write(self, peer: int, ring: _TxRing, parts,
                      raise_errors: bool, cid: Optional[int] = None,
                      sl=None) -> Optional[bool]:
        """Opportunistic same-thread drain — the latency path.  When
        the peer's ring is idle and the out lock is free, the frame
        goes on the wire right here in GIL-released writev calls: no
        writer-thread hop, no doorbell, exactly the python plane's
        blocking cost minus the GIL and the join copy.

        Returns True (frame fully written), False (socket error — the
        ring is failed; with raise_errors the error raises instead),
        or None (contended / ring busy: the caller enqueues)."""
        net = self._net_h
        if sl is not None:
            sock, lock = sl
        else:
            with self._lock:
                sock = self._out.get(peer)
                lock = self._out_locks.get(peer)
        if sock is None or lock is None or not lock.acquire(
                blocking=False):
            return None
        try:
            with ring.mu:
                if ring.error is not None or ring.entries:
                    return None   # FIFO: queued frames must go first
            _h_t0 = time.monotonic_ns() if trace_mod.hist_active else 0
            fd = sock.fileno()
            # fast path: the whole frame in ONE ctypes crossing —
            # send3 takes the three buffers as pointer args (bytes
            # pass straight through c_void_p; only non-bytes payloads
            # need a Python-side address), so there is no per-frame
            # iovec marshalling at all
            pay = parts[2] if len(parts) == 3 else b""
            if type(pay) is bytes:
                parg, _keep = pay, None
            elif len(pay):
                _keep = np.frombuffer(pay, np.uint8)
                parg = _keep.ctypes.data
            else:
                parg, _keep = None, None
            total = len(parts[0]) + len(parts[1]) + len(pay)
            # small frames: GIL-HELD crossing (PyDLL, slice 0 so the C
            # side can never poll) — the MSG_DONTWAIT sendmsg is ~2us,
            # and releasing the GIL for it lets the peer's just-woken
            # poller steal the interpreter, costing the sender a whole
            # dispatch pass to get it back
            w = 0
            ng = (self._net_ng if total <= _NOGIL_MAX else None)
            if ng is not None:
                w = ng.ompi_tpu_net_send3(
                    fd, parts[0], len(parts[0]), parts[1],
                    len(parts[1]), parg, len(pay), 0)
            if w == 0:   # big frame, no PyDLL, or instant EAGAIN
                w = net.ompi_tpu_net_send3(
                    fd, parts[0], len(parts[0]), parts[1],
                    len(parts[1]), parg, len(pay), _WRITE_SLICE_NS)
            if w == total:
                trace_mod.count("btl_tcp_native_writes_total")
                trace_mod.count("btl_tcp_native_batched_frames_total")
                if _h_t0:
                    trace_mod.record_hist(
                        "btl_tcp_write_ns", time.monotonic_ns() - _h_t0)
                return True
            if w < 0:
                err = OSError(-w, f"{os.strerror(-w)} "
                              "(native direct write)")
                self._fail_ring(ring, err)
                if raise_errors:
                    raise err
                return False
            if w == 0:
                return None   # not writable at all: ring + writer
            # partial frame on the wire: committed — resume through the
            # iovec loop below until complete (torn frames desync)
            keep = [np.frombuffer(p, np.uint8) for p in parts if len(p)]
            flat = [(v.ctypes.data, v.nbytes) for v in keep]
            written = w
            calls = 1
            idx = off = 0
            adv = w
            while idx < len(flat) and adv >= flat[idx][1]:
                adv -= flat[idx][1]
                idx += 1
            off = adv
            while written < total:
                n = len(flat) - idx
                pa = (ctypes.c_uint64 * (2 * n))()
                k = 0
                for a, ln in flat[idx:]:
                    pa[k] = a
                    pa[k + 1] = ln
                    k += 2
                pa[0] += off
                pa[1] -= off
                w = net.ompi_tpu_net_writev(fd, pa, n, _WRITE_SLICE_NS)
                if w < 0:
                    err = OSError(-w, f"{os.strerror(-w)} "
                                  "(native direct write)")
                    self._fail_ring(ring, err)
                    if raise_errors:
                        raise err
                    return False
                if w > 0:
                    calls += 1
                    written += w
                    off += w
                    while idx < len(flat) and off >= flat[idx][1]:
                        off -= flat[idx][1]
                        idx += 1
                    continue
                if written == 0:
                    return None   # not writable at all: ring + writer
                # mid-frame backpressure: the frame MUST complete (a
                # torn frame desyncs the stream) — park bounded, re-run
                # the FT contract, and on abandonment kill the socket
                # so the receiver sees EOF instead of a desynced stream
                trace_mod.count("btl_tcp_native_parks_total")
                if self._stop.is_set():
                    err = ConnectionError("endpoint closed mid-write")
                    self._fail_ring(ring, err)
                    try:
                        sock.close()
                    except OSError:
                        pass
                    if raise_errors:
                        raise err
                    return False
                ft = self.ft_check
                if ft is not None:
                    try:
                        ft(peer, cid)
                    except BaseException:
                        self._fail_ring(ring, ConnectionError(
                            "FT verdict mid-write"))
                        try:
                            sock.close()
                        except OSError:
                            pass
                        if raise_errors:
                            raise
                        return False
            del keep
            trace_mod.count("btl_tcp_native_writes_total", calls)
            trace_mod.count("btl_tcp_native_batched_frames_total")
            if _h_t0:
                trace_mod.record_hist("btl_tcp_write_ns",
                                      time.monotonic_ns() - _h_t0)
            return True
        finally:
            lock.release()

    def _send_native(self, peer: int, cid: Optional[int], prefix: bytes,
                     hdr: bytes, payload, sock=None, lock=None) -> None:
        """Ring enqueue with the buffer-reuse contract: bytes payloads
        are immutable and ride as-is (send returns immediately — the
        batching win); small views are copied into the entry; large
        views ride zero-copy and the sender parks until its drained
        ticket is reached, FT-checked between bounded slices."""
        nbytes = len(payload)
        ring = self._ring(peer)
        parts = (prefix, hdr, payload) if nbytes else (prefix, hdr)
        if not ring.in_burst():
            # lone send (not part of a burst): write on THIS thread —
            # the pingpong latency path.  Synchronous, so views of any
            # size go zero-copy with no drain wait.
            if self._direct_write(peer, ring, parts, raise_errors=True,
                                  cid=cid,
                                  sl=(sock, lock) if lock else None):
                return
        await_drain = False
        if nbytes and not isinstance(payload, bytes):
            if nbytes <= int(var_registry.get("btl_tcp_copy_limit") or 0):
                payload = bytes(payload)
                parts = (prefix, hdr, payload)
            else:
                await_drain = True
        cap = int(var_registry.get("btl_tcp_ring_bytes") or (4 << 20))
        nb = len(prefix) + len(hdr) + nbytes
        while True:
            with ring.mu:
                if ring.error is not None:
                    raise ConnectionError(
                        f"btl/tcp: native ring to rank {peer} failed "
                        f"({ring.error})")
                # always admit at least one frame: a single frame above
                # the cap must not deadlock against an empty ring
                if not ring.entries or ring.pending_bytes + nb <= cap:
                    ring.enq += 1
                    ticket = ring.enq
                    ring.entries.append((parts, nb, ticket, cid))
                    ring.pending_bytes += nb
                    break
                seen = ring.ctr[0]
            self._park_ring(peer, cid, ring, seen)   # ring full
        self._kick_writer()
        if not await_drain:
            return
        while True:   # zero-copy view: reusable only once on the wire
            with ring.mu:
                if ring.error is not None:
                    raise ConnectionError(
                        f"btl/tcp: native ring to rank {peer} failed "
                        f"({ring.error})")
                seen = ring.ctr[0]
            if seen >= ticket:
                return
            self._park_ring(peer, cid, ring, seen)

    def _park_ring(self, peer: int, cid: Optional[int], ring: _TxRing,
                   seen: int) -> None:
        """One bounded GIL-released park on the ring's drained counter,
        then the full Python FT contract — Arena._wait's discipline on
        the send side."""
        ar = _park_lib()
        if ar is not None:
            ar.ompi_tpu_arena_wait_change(ring.ctr_addr, seen, 0,
                                          _PARK_SLICE_NS)
        else:
            time.sleep(0.0005)
        trace_mod.count("btl_tcp_native_parks_total")
        if self._stop.is_set():
            raise ConnectionError("btl/tcp: endpoint closed mid-send")
        ft = self.ft_check
        if ft is not None:
            ft(peer, cid)

    def _ring(self, peer: int) -> _TxRing:
        with self._lock:
            ring = self._rings.get(peer)
            if ring is None:
                ring = self._rings[peer] = _TxRing()
            return ring

    def drop_ring(self, peer: int) -> None:
        """Rebind/teardown path: fail and forget the peer's submission
        ring — parked senders wake into ConnectionError (the PML's
        park-and-heal classes), and the next send to the peer's new
        incarnation starts a fresh ring."""
        with self._lock:
            ring = self._rings.pop(peer, None)
        if ring is not None:
            self._fail_ring(ring, ConnectionError("peer rebound"))

    def _fail_ring(self, ring: _TxRing, exc: BaseException) -> None:
        """Pending frames die the way bytes in a dead kernel buffer die;
        parked senders wake (counter bump breaks the wait-for-change)
        and surface ConnectionError — the same class the python plane's
        broken socket raises, so the PML heal ladder is shared."""
        with ring.mu:
            if ring.error is None:
                ring.error = exc
            ring.entries.clear()
            ring.pending_bytes = 0
            ring.ctr[0] += 1   # break wait_change parks; error is sticky
        self._wake_ring(ring)

    def _wake_ring(self, ring: _TxRing) -> None:
        ar = _park_lib()
        if ar is not None:
            ar.ompi_tpu_arena_wake(ring.ctr_addr, 0)

    def _kick_writer(self) -> None:
        if self._writer is None:
            with self._lock:
                if self._writer is None and not self._stop.is_set():
                    t = threading.Thread(target=self._writer_loop,
                                         name=f"btl-writer-{self.rank}",
                                         daemon=True)
                    self._writer = t
                    t.start()
                    self._threads.append(t)
        with self._wlock:
            self._wctr[0] += 1
            parked = self._writer_parked
        if parked:   # a busy writer re-reads the doorbell lock-free
            ar = _park_lib()
            if ar is not None:
                ar.ompi_tpu_arena_wake(self._wctr_addr, 0)

    def _flush_ring_locked(self, peer: int, sock: socket.socket) -> None:
        """Python-plane prelude, under the per-peer out lock the writer
        also drains under: anything still in the peer's submission ring
        hits the wire BEFORE this frame, so a mid-run plane flip never
        reorders a sender's stream."""
        ring = self._rings.get(peer)
        if ring is None:
            return
        while True:
            with ring.mu:
                if ring.error is not None or not ring.entries:
                    return
                parts, nb, ticket, _cid = ring.entries.popleft()
                ring.pending_bytes -= nb
            try:
                _send_all(sock, *parts)
            except OSError as e:
                self._fail_ring(ring, e)
                raise
            with ring.mu:
                if ring.error is None:
                    ring.ctr[0] = ticket
            self._wake_ring(ring)

    def _writer_loop(self) -> None:
        """The single native writer: sweeps every peer's submission
        ring, draining whole backlogs in batched GIL-released sendmsg
        calls, and parks on the doorbell futex when idle.  Missed-wakeup
        guard: the doorbell count is captured BEFORE the sweep, so an
        enqueue racing the park bumps the word past ``seen`` and the
        wait returns immediately."""
        from ompi_tpu import _native

        net = _net_lib()
        ar = _park_lib()
        spins = _native.PARK_SPINS
        while not self._stop.is_set():
            with self._wlock:
                seen = self._wctr[0]
            with self._lock:
                rings = list(self._rings.items())
            progressed = False
            backlogged = False
            for peer, ring in rings:
                if ring.entries and ring.error is None:
                    if self._drain_ring(peer, ring, net):
                        progressed = True
                    if ring.entries and ring.error is None:
                        backlogged = True
            if progressed or backlogged:
                # a backlogged peer's drain already parked in POLLOUT
                # inside the native call — no doorbell wait on top
                continue
            with self._wlock:
                self._writer_parked = True
                cur = self._wctr[0]
            if cur != seen:   # a ring was kicked mid-sweep: re-sweep
                self._writer_parked = False
                continue
            if ar is not None:
                ar.ompi_tpu_arena_wait_change(self._wctr_addr, seen,
                                              spins, _WRITER_IDLE_NS)
            else:
                time.sleep(0.0005)
            self._writer_parked = False
            trace_mod.count("btl_tcp_native_parks_total")

    def _drain_ring(self, peer: int, ring: _TxRing, net) -> bool:
        """Drain one peer's backlog under the per-peer out lock (the
        python plane's send path takes the same lock, so the two planes
        never interleave mid-frame).  Returns True when bytes moved."""
        with self._lock:
            sock = self._out.get(peer)
            lock = self._out_locks.get(peer)
        if sock is None or lock is None:
            # enqueue raced a rebind/close: entries die with the ring
            self._fail_ring(ring, ConnectionError("socket dropped"))
            return False
        if not lock.acquire(timeout=0.05):
            return False   # python-plane send in flight; next sweep
        try:
            with ring.mu:
                batch = list(ring.entries)
            if not batch:
                return False
            # scatter-gather list: ≤ 3 iovecs per frame; numpy views
            # give zero-copy addresses for read-only bytes too
            keep = []       # buffer refs pinned for the native call
            flat = []
            for parts, _nb, _ticket, _cid in batch:
                for p in parts:
                    if len(p):
                        v = np.frombuffer(p, np.uint8)
                        keep.append(v)
                        flat.append((v.ctypes.data, v.nbytes))
            total = sum(ln for _a, ln in flat)
            _h_t0 = time.monotonic_ns() if trace_mod.hist_active else 0
            written = 0
            calls = 0
            idx = 0         # first not-fully-written iovec
            off = 0         # bytes of flat[idx] already written
            fd = sock.fileno()
            while written < total:
                n = len(flat) - idx
                pa = (ctypes.c_uint64 * (2 * n))()
                k = 0
                for a, ln in flat[idx:]:
                    pa[k] = a
                    pa[k + 1] = ln
                    k += 2
                pa[0] += off
                pa[1] -= off
                w = net.ompi_tpu_net_writev(fd, pa, n, _WRITE_SLICE_NS)
                if w < 0:
                    self._fail_ring(ring, OSError(
                        -w, f"{os.strerror(-w)} (native writev)"))
                    return written > 0
                if w > 0:
                    calls += 1
                    written += w
                    off += w
                    while idx < len(flat) and off >= flat[idx][1]:
                        off -= flat[idx][1]
                        idx += 1
                    continue
                # slice expired without progress (peer backpressure):
                # re-run the FT contract, then wait again
                trace_mod.count("btl_tcp_native_parks_total")
                if self._stop.is_set():
                    self._fail_ring(ring, ConnectionError(
                        "endpoint closed mid-drain"))
                    return written > 0
                ft = self.ft_check
                if ft is not None:
                    try:
                        ft(peer, None)
                    except Exception as e:  # noqa: BLE001 — FT verdict
                        self._fail_ring(ring, e)
                        return written > 0
            del keep
            # the whole batch is on the wire: retire + publish tickets
            with ring.mu:
                last = 0
                for _parts, nb, ticket, _cid in batch:
                    if not ring.entries:
                        break   # a concurrent _fail_ring cleared us
                    ring.entries.popleft()
                    ring.pending_bytes -= nb
                    last = ticket
                if last and ring.error is None:
                    ring.ctr[0] = last
            self._wake_ring(ring)
            trace_mod.count("btl_tcp_native_writes_total", calls)
            trace_mod.count("btl_tcp_native_batched_frames_total",
                            len(batch))
            if _h_t0:
                trace_mod.record_hist("btl_tcp_write_ns",
                                      time.monotonic_ns() - _h_t0)
            return True
        finally:
            lock.release()

    def _peer_sock(self, peer: int) -> tuple[socket.socket, threading.Lock]:
        with self._lock:
            sock = self._out.get(peer)
            if sock is not None:
                return sock, self._out_locks[peer]
            addr = self._peers.get(peer)
        if addr is None:
            raise ConnectionError(
                f"btl/tcp: no address for rank {peer} (modex incomplete)")
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        for opt, var in ((socket.SO_SNDBUF, "btl_tcp_sndbuf"),
                         (socket.SO_RCVBUF, "btl_tcp_rcvbuf")):
            v = var_registry.get(var)
            if v:
                sock.setsockopt(socket.SOL_SOCKET, opt, v)
        # hello frame identifies us to the acceptor (under the alias the
        # acceptor knows us by, for cross-job connections)
        with self._lock:
            my_id = self._alias.get(peer, self.rank)
        hello = dss.pack({"hello": my_id})
        _send_all(sock, struct.pack("<II", len(hello), len(hello)), hello)
        with self._lock:
            # lost the race with another sender thread? keep the first
            existing = self._out.get(peer)
            if existing is not None:
                sock.close()
                return existing, self._out_locks[peer]
            self._out[peer] = sock
            self._out_locks[peer] = threading.Lock()
            return sock, self._out_locks[peer]

    # -- receiving ---------------------------------------------------------

    def _accept_loop(self) -> None:
        try:
            self._listener.settimeout(0.2)
        except OSError:
            return   # close() won the race before the thread started
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._native_ok:
                self._register_conn(conn)
                continue
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _register_conn(self, sock: socket.socket) -> None:
        """Hand an accepted connection to the shared poller instead of
        spawning a per-socket read loop.  The socket goes nonblocking:
        from here on only the poller touches it, and both the native and
        python poll branches read with per-call readiness."""
        sock.setblocking(False)
        c = _Conn(sock)
        with self._lock:
            self._conns.append(c)
            if self._poller is None and not self._stop.is_set():
                # the wake pipe is born with the poller and dies with it
                self._wake_r, self._wake_w = os.pipe()
                os.set_blocking(self._wake_r, False)
                os.set_blocking(self._wake_w, False)
                t = threading.Thread(target=self._poll_loop,
                                     name=f"btl-poll-{self.rank}",
                                     daemon=True)
                self._poller = t
                t.start()
                self._threads.append(t)
        self._wake_poller()

    def _wake_poller(self) -> None:
        if self._wake_w >= 0:
            try:
                os.write(self._wake_w, b"\0")
            except (BlockingIOError, OSError):
                pass   # pipe full ⇒ a wake is already pending

    def _drain_wake_pipe(self) -> None:
        try:
            while os.read(self._wake_r, 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _poll_loop(self) -> None:
        """One thread parks across EVERY accepted connection.  The
        `btl_tcp_native` var is re-read each iteration, so a runtime
        flip moves frame parsing between the native and python branches
        without touching the sockets.  Slices are bounded: the loop
        returns to Python (stop flag, fresh fd snapshot) at least every
        _POLL_SLICE_NS even when fully idle."""
        from ompi_tpu import _native

        net = _net_lib()
        spins = max(0, _native.PARK_SPINS // 16)
        while not self._stop.is_set():
            with self._lock:
                conns = list(self._conns)
            use_native = (net is not None
                          and bool(var_registry.get("btl_tcp_native"))
                          and len(conns) + 1 <= 1024)
            if use_native:
                nfds = len(conns) + 1
                fds = (ctypes.c_int64 * nfds)()
                fds[0] = self._wake_r
                for i, c in enumerate(conns):
                    fds[i + 1] = c.fd
                rdy = (ctypes.c_uint8 * nfds)()
                rc = net.ompi_tpu_net_poll(fds, nfds, rdy, spins,
                                           _POLL_SLICE_NS)
                if rc == 0:
                    trace_mod.count("btl_tcp_native_parks_total")
                    continue
                if rc < 0:
                    ready = conns   # service-all: dead fds prune here
                else:
                    if rdy[0]:
                        self._drain_wake_pipe()
                    ready = [c for i, c in enumerate(conns)
                             if rdy[i + 1]]
            else:
                try:
                    rl, _, _ = select.select(
                        [self._wake_r] + [c.sock for c in conns],
                        [], [], 0.05)
                except (OSError, ValueError):
                    rl = [c.sock for c in conns]   # service-all prunes
                if self._wake_r in rl:
                    self._drain_wake_pipe()
                ready = [c for c in conns if c.sock in rl]
            # the service mutex serializes socket reads against pulling
            # recv-waiters (progress()); a stale ready list after losing
            # the race is harmless — the reads just EAGAIN
            with self._svc_mu:
                for c in ready:
                    try:
                        self._service_conn(c,
                                           net if use_native else None)
                    except (OSError, ValueError) as e:
                        self._drop_conn(c, e)

    def progress(self, budget_s: float = 0.0005) -> bool:
        """Receiver-pull service pass (≈ opal_progress running in the
        waiting thread): a caller blocked on a recv polls the accepted
        connections itself and, if it wins the service lock, drains and
        dispatches ready frames on ITS OWN thread — the frame that
        completes its request is parsed and matched right here, with no
        poller-thread wake and no completion-event handoff on the
        critical path.  The parked poller stays running as the backstop
        for every other request, so callers may stop pulling at any
        time.  One bounded GIL-released poll slice per call; the caller
        re-runs its Python checks (request done, FT verdicts, stop
        flags) between calls.  Returns False when the native plane is
        off/down or the endpoint is stopping — the caller goes back to
        event-waiting."""
        net = self._net_h
        if (net is None or self._stop.is_set()
                or not var_registry.get("btl_tcp_native")):
            return False
        with self._lock:
            conns = list(self._conns)
        if not conns or len(conns) + 1 > 1024:
            return False
        nfds = len(conns) + 1
        fds = (ctypes.c_int64 * nfds)()
        fds[0] = self._wake_r
        for i, c in enumerate(conns):
            fds[i + 1] = c.fd
        rdy = (ctypes.c_uint8 * nfds)()
        rc = net.ompi_tpu_net_poll(fds, nfds, rdy, 0,
                                   int(budget_s * 1e9))
        if rc <= 0:
            return True   # idle slice (or service-all noise): re-check
        if rdy[0]:
            # take the re-snapshot signal: conns are re-read on every
            # pull anyway, and leaving the byte would turn each poll
            # into an instant (empty) return — a hot loop
            self._drain_wake_pipe()
        ready = [c for i, c in enumerate(conns) if rdy[i + 1]]
        if ready and self._svc_mu.acquire(blocking=False):
            try:
                for c in ready:
                    try:
                        self._service_conn(c, net)
                    except (OSError, ValueError) as e:
                        self._drop_conn(c, e)
            finally:
                self._svc_mu.release()
        return True

    def _drop_conn(self, c: _Conn, exc: BaseException) -> None:
        with self._lock:
            try:
                self._conns.remove(c)
            except ValueError:
                pass
        try:
            c.sock.close()
        except OSError:
            pass

    def _service_conn(self, c: _Conn, net) -> None:
        """Pull whatever the connection has pending: finish an
        in-flight direct landing first, then gulp into the staging
        buffer and parse frames.  Bounded per call — a slow sender
        cannot starve the other connections."""
        if c.pending is not None and not self._land_step(c, net):
            return   # landing still short of bytes; poller re-arms
        while True:
            if net is not None:
                n = net.ompi_tpu_net_read(c.fd, c.addr + c.used,
                                          _CONN_BUF - c.used)
                if n in (-errno.EAGAIN, -errno.EWOULDBLOCK):
                    return
                if n <= 0:   # NET_EOF or -errno
                    raise OSError("btl/tcp: connection lost "
                                  f"(native read {n})")
            else:
                try:
                    n = c.sock.recv_into(c.mv[c.used:])
                except (BlockingIOError, InterruptedError):
                    return
                if n == 0:
                    raise OSError("btl/tcp: connection closed")
            c.used += n
            self._parse_frames(c, net)
            if c.pending is not None and not self._land_step(c, net):
                return

    def _parse_frames(self, c: _Conn, net) -> None:
        """Parse every complete frame in the staging buffer (native
        scan or python struct — bit-identical framing), dispatch them,
        and decide whether the trailing partial should switch to direct
        landing (big rndv payloads recv straight into the plan
        destination instead of round-tripping the staging buffer)."""
        from ompi_tpu import _native

        while True:
            triples = []
            if net is not None:
                nf = net.ompi_tpu_net_scan(c.addr, c.used,
                                           self._scan_addr, _SCAN_MAX)
                if nf < 0:
                    raise OSError(
                        f"btl/tcp: malformed frame stream ({nf})")
                so = self._scan_out
                for i in range(nf):
                    triples.append((so[3 * i], so[3 * i + 1],
                                    so[3 * i + 2]))
            else:
                off = 0
                while len(triples) < _SCAN_MAX and c.used - off >= 8:
                    total, hlen = struct.unpack_from("<II", c.buf, off)
                    if hlen > total:
                        raise OSError("btl/tcp: malformed frame prefix")
                    if c.used - off - 8 < total:
                        break
                    triples.append((off, total, hlen))
                    off += 8 + total
            consumed = 0
            for off, total, hlen in triples:
                hdr = dss.unpack(bytes(c.mv[off + 8:off + 8 + hlen]),
                                 n=1)[0]
                payload = bytes(c.mv[off + 8 + hlen:off + 8 + total])
                if "hello" in hdr:
                    c.peer = hdr["hello"]
                else:
                    self.on_frame(c.peer, hdr, payload)
                consumed = off + 8 + total
            more = len(triples) == _SCAN_MAX
            rem = c.used - consumed
            if not more and rem >= 8:
                total, hlen = struct.unpack_from("<II", c.buf, consumed)
                if hlen > total:
                    raise OSError("btl/tcp: malformed frame prefix")
                if 8 + hlen >= _CONN_BUF:
                    # headers are small by contract; a header that can
                    # never fit the staging buffer would deadlock —
                    # fail the connection loudly instead
                    raise OSError(
                        f"btl/tcp: oversized frame header ({hlen}B)")
                if 8 + total >= _LAND_MIN and rem >= 8 + hlen:
                    hdr = dss.unpack(
                        bytes(c.mv[consumed + 8:consumed + 8 + hlen]),
                        n=1)[0]
                    plen = total - hlen
                    dst = None
                    sink = self.recv_sink
                    # direct zero-copy landing is a native-plane
                    # feature: the python fallback stages + copies,
                    # exactly like the pre-poller per-socket read loop
                    if net is not None and sink is not None \
                            and "hello" not in hdr:
                        try:
                            dst = sink(hdr, plen)
                        except Exception:  # noqa: BLE001 — fall back
                            dst = None
                    staged = dst is None
                    if staged:
                        dst = bytearray(plen)
                    dmv = memoryview(dst).cast("B")
                    daddr = _native.addr_of(dmv)
                    if daddr is None:   # read-only sink? stage instead
                        staged = True
                        dst = bytearray(plen)
                        dmv = memoryview(dst).cast("B")
                        daddr = _native.addr_of(dmv)
                    avail = rem - 8 - hlen
                    if avail:
                        dmv[:avail] = c.mv[consumed + 8 + hlen:c.used]
                    c.pending = [hdr, dmv, daddr, avail, plen, staged]
                    consumed = c.used
            if consumed:
                left = c.used - consumed
                if left:
                    # RHS of a bytearray slice-assign copies first, so
                    # the overlapping move is safe and allocation-free
                    c.buf[0:left] = c.buf[consumed:c.used]
                c.used = left
            if not more:
                return

    def _land_step(self, c: _Conn, net) -> bool:
        """Advance an in-flight direct landing by one bounded slice.
        True ⇒ the frame completed and was dispatched; False ⇒ short
        read, poller re-arms (the FT contract runs in the poller's
        outer loop via the stop flag and connection errors)."""
        hdr, dmv, daddr, filled, plen, staged = c.pending
        while filled < plen:
            if self._stop.is_set():
                raise OSError("btl/tcp: endpoint closed mid-landing")
            if net is not None:
                m = net.ompi_tpu_net_recv_into(c.fd, daddr + filled,
                                               plen - filled,
                                               _LAND_SLICE_NS)
                if m < 0:   # NET_EOF or -errno
                    raise OSError("btl/tcp: connection lost "
                                  f"(native landing {m})")
                if m == 0:
                    trace_mod.count("btl_tcp_native_parks_total")
                    c.pending[3] = filled
                    return False
            else:
                try:
                    m = c.sock.recv_into(dmv[filled:])
                except (BlockingIOError, InterruptedError):
                    c.pending[3] = filled
                    return False
                if m == 0:
                    raise OSError("btl/tcp: connection closed "
                                  "mid-landing")
            filled += m
        c.pending = None
        if "hello" in hdr:
            c.peer = hdr["hello"]
        elif staged:
            self.on_frame(c.peer, hdr, bytes(dmv))
        else:
            done = self.recv_sink_done
            if done is not None:
                done(hdr, plen)
        return True

    def _read_loop(self, conn: socket.socket) -> None:
        peer = -1
        with conn:
            while not self._stop.is_set():
                hdr8 = _recv_exact(conn, 8)
                if hdr8 is None:
                    return
                total, hdr_len = struct.unpack("<II", hdr8)
                blob = _recv_exact(conn, total)
                if blob is None:
                    return
                header = dss.unpack(blob[:hdr_len], n=1)[0]
                payload = blob[hdr_len:]
                if "hello" in header:
                    peer = header["hello"]
                    continue
                self.on_frame(peer, header, payload)

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            rings = list(self._rings.values())
            self._rings.clear()
            conns = list(self._conns)
            self._conns.clear()
            socks = list(self._out.values())
            self._out.clear()
            poller = self._poller
        for ring in rings:
            self._fail_ring(ring, ConnectionError("btl/tcp closed"))
        # doorbell the writer and poller out of their parks
        with self._wlock:
            self._wctr[0] += 1
        ar = _park_lib()
        if ar is not None:
            ar.ompi_tpu_arena_wake(self._wctr_addr, 0)
        self._wake_poller()
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        if poller is not None:
            poller.join(timeout=1.0)
            if not poller.is_alive() and self._wake_r >= 0:
                # only reap the pipe once the poller is provably out of
                # poll()/select() on it — closing early risks fd reuse
                for fd in (self._wake_r, self._wake_w):
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                self._wake_r = self._wake_w = -1


class SelfBTL:
    """Loopback delivery (≈ btl/self): frames to self never touch a socket."""

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        self.rank = rank
        self.on_frame = on_frame

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        assert peer == self.rank
        self.on_frame(self.rank, header, payload)


class ProcBTL:
    """Same-process direct delivery — the degenerate single-copy case of
    vader's xpmem mode (btl_vader_component.c:61-69): when two ranks share
    an address space (threads-as-ranks harness, in-process jobs) a frame
    is ONE direct call into the peer's frame handler — no ring, no poller
    wakeup, no serialization of the payload.  The PML's per-(peer, cid)
    sequence numbers keep ordering correct when mixed with other BTLs.

    Endpoints register in a process-global table under a unique token;
    the business card is ``pid:token`` and reachability is pid equality.
    """

    _registry: dict[int, "ProcBTL"] = {}
    _next_token = iter(range(1, 1 << 62))
    _reg_lock = threading.Lock()

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        import os

        self.rank = rank
        self.on_frame = on_frame
        # optional compiled fast lane: (peer, tag, cid, seq, payload) →
        # bool, installed by the owning PML when its matching engine is
        # native — delivers with no header object at all
        self.on_fast = None
        self._alias: dict[int, int] = {}
        self._peer_tokens: dict[int, int] = {}
        # honor simulated host identities: sim-plm ranks on different
        # fake hosts must NOT short-circuit through the address space
        from ompi_tpu.core.sysinfo import host_identity

        self.hostname = host_identity()
        with ProcBTL._reg_lock:
            self.token = next(ProcBTL._next_token)
            ProcBTL._registry[self.token] = self
        self.address = f"{os.getpid()}:{self.token}:{self.hostname}"

    def set_alias(self, peer: int, my_id: int) -> None:
        self._alias[peer] = my_id

    def can_reach(self, card: str) -> bool:
        import os

        try:
            pid, token, host = card.split(":", 2)
        except ValueError:
            return False
        return (pid == str(os.getpid()) and host == self.hostname
                and int(token) in ProcBTL._registry)

    def connect(self, peer: int, card: str) -> bool:
        if not self.can_reach(card):
            return False
        self._peer_tokens[peer] = int(card.split(":", 2)[1])
        return True

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        target = ProcBTL._registry.get(self._peer_tokens[peer])
        if target is None:
            raise ConnectionError(f"btl/proc: peer {peer} endpoint closed")
        target.on_frame(self._alias.get(peer, self.rank), header, payload)

    def send_fast(self, peer: int, tag: int, cid: int, seq: int,
                  payload, dt, elems: int, shp) -> bool:
        """Header-free delivery into the peer's compiled engine; False ⇒
        the peer declined (no engine, fencing active, out-of-order) and
        the caller re-sends the same frame via the header path.  dt/
        elems/shp are the scalar header fields the engine materializes
        only when it must (unexpected storage, allocate-on-match)."""
        target = ProcBTL._registry.get(self._peer_tokens.get(peer, -1))
        if target is None or target.on_fast is None:
            return False
        return target.on_fast(self._alias.get(peer, self.rank),
                              tag, cid, seq, payload, dt, elems, shp)

    def close(self) -> None:
        with ProcBTL._reg_lock:
            ProcBTL._registry.pop(self.token, None)


@btl_framework.component
class TcpBTLComponent(Component):
    NAME = "tcp"
    PRIORITY = 10

    def create(self, rank: int, on_frame: OnFrame) -> TcpBTL:
        return TcpBTL(rank, on_frame)


@btl_framework.component
class SelfBTLComponent(Component):
    NAME = "self"
    PRIORITY = 90

    def create(self, rank: int, on_frame: OnFrame) -> SelfBTL:
        return SelfBTL(rank, on_frame)


@btl_framework.component
class ProcBTLComponent(Component):
    """Same-address-space direct delivery (≈ vader's xpmem single-copy
    mode degenerated to zero-copy calls) — priority above shm: when ranks
    share a process, a function call beats a ring."""

    NAME = "proc"
    PRIORITY = 70

    def create(self, rank: int, on_frame: OnFrame) -> ProcBTL:
        return ProcBTL(rank, on_frame)


@btl_framework.component
class ShmBTLComponent(Component):
    """Shared-memory rings for same-host ranks (≈ btl/vader — priority
    between self and tcp, exactly the reference's exclusivity ordering:
    btl_vader_component.c:61-69)."""

    NAME = "shm"
    PRIORITY = 50

    def create(self, rank: int, on_frame: OnFrame):
        from ompi_tpu.mpi.btl_shm import ShmBTL

        return ShmBTL(rank, on_frame)


class BtlEndpoint:
    """Per-job BTL multiplexer (≈ bml/r2, bml.h:220-232): routes each frame
    to the best reachable BTL — self for loopback, shm rings for same-host
    peers, tcp otherwise.  MCA selection on the btl framework (``--mca btl
    ^shm``, ``--mca btl self,tcp``) gates which transports are built; the
    self BTL is always on (loopback is load-bearing for COMM_SELF and
    collective self-sends, like coll/self in the reference)."""

    def __init__(self, rank: int, on_frame: OnFrame) -> None:
        self.rank = rank
        enabled = {c.NAME for c in btl_framework._eligible()}
        self.self_btl = SelfBTL(rank, on_frame)
        self.tcp_btl = TcpBTL(rank, on_frame) if "tcp" in enabled else None
        self.shm_btl = None
        if "shm" in enabled:
            from ompi_tpu.mpi.btl_shm import ShmBTL

            self.shm_btl = ShmBTL(rank, on_frame)
        self.proc_btl = ProcBTL(rank, on_frame) if "proc" in enabled else None
        if self.tcp_btl is None and self.shm_btl is None:
            raise MPIException(
                "btl selection leaves no transport for remote peers "
                "(need tcp and/or shm)")
        self._cards: dict[int, str] = {}   # peer → full business card
        self._shm_ok: set[int] = set()     # peers with a live shm route
        self._proc_ok: set[int] = set()    # peers in my address space
        self._proc_no: set[int] = set()    # known peers that are NOT
        # deterministic chaos (ompi_tpu.testing.faultinject): when a
        # fault plan is armed, every header-path frame gets a seeded
        # drop/delay/dup verdict at this boundary.  None in production —
        # the hot path pays one attribute check.
        self._fault = None
        from ompi_tpu.testing import faultinject

        if faultinject.active():
            self._fault = faultinject.injector_for(rank)

    @property
    def address(self) -> str:
        """The combined business card: tcp address (``-`` when tcp is
        disabled), plus a segment per enabled same-host transport."""
        card = self.tcp_btl.address if self.tcp_btl is not None else "-"
        if self.shm_btl is not None:
            card += f";shm={self.shm_btl.address}"
        if self.proc_btl is not None:
            card += f";proc={self.proc_btl.address}"
        return card

    @staticmethod
    def _split_card(card: str) -> tuple[str, Optional[str], Optional[str]]:
        """→ (tcp, shm segment, proc segment)."""
        parts = card.split(";")
        tcp, shm, proc = parts[0], None, None
        for p in parts[1:]:
            if p.startswith("shm="):
                shm = p[4:]
            elif p.startswith("proc="):
                proc = p[5:]
        return tcp, shm, proc

    def set_peers(self, peers: dict[int, str]) -> None:
        self._cards.update(peers)
        if self.tcp_btl is not None:
            self.tcp_btl.set_peers(
                {p: self._split_card(c)[0] for p, c in peers.items()})

    def set_alias(self, peer: int, my_id: int) -> None:
        if self.tcp_btl is not None:
            self.tcp_btl.set_alias(peer, my_id)
        if self.shm_btl is not None:
            self.shm_btl.set_alias(peer, my_id)
        if self.proc_btl is not None:
            self.proc_btl.set_alias(peer, my_id)

    def peer_alive(self, peer: int) -> Optional[bool]:
        """Same-host pid-liveness: route the question to the shm BTL's
        shared, rate-limited probe (the pid travels in the peer's shm
        business-card segment).  None when unknowable — remote peer, shm
        disabled, or no pid in the card — True/False otherwise."""
        if self.shm_btl is None or peer == self.rank:
            return None if self.shm_btl is None else True
        card = self._cards.get(peer)
        shm_seg = self._split_card(card)[1] if card else None
        return self.shm_btl.probe_alive(peer, shm_seg)

    def max_peer_id(self) -> int:
        """Highest peer id this endpoint knows (for dpm namespace bases)."""
        if self.tcp_btl is None:
            return max(self._cards, default=-1)
        with self.tcp_btl._lock:
            return max(self.tcp_btl._peers, default=-1)

    def try_send_inline(self, peer: int, header: dict,
                        payload: bytes = b"") -> bool:
        """Inline fast path (≈ mca_bml_base_sendi → btl_sendi,
        pml_ob1_isend.c:89-119): deliver the frame on the CALLER's thread
        when it cannot block — self loopback always, shm when the ring has
        room.  False ⇒ caller enqueues for the send worker.  Safe to mix
        with queued sends: the PML reorders by per-(peer,cid) sequence."""
        if self._fault is not None and peer != self.rank:
            verdict = self._fault.on_frame(peer, header)
            if verdict != "send":
                # the verdict is identity-hashed: the worker path would
                # draw the SAME verdict, so resolve it here (True = the
                # frame's fate is sealed; nothing for the worker to do)
                self._apply_fault(verdict, peer, header, payload)
                return True
        ok = self._try_send_inline(peer, header, payload)
        if ok and trace_mod.active:
            # AFTER success only: a declined inline attempt is re-sent by
            # the worker (whose endpoint.send emits its own instant) — an
            # entry-time emit would trace that frame twice
            trace_mod.instant("btl", "send_inline", rank=self.rank,
                              peer=peer, nbytes=len(payload),
                              t=header.get("t"))
        return ok

    def _try_send_inline(self, peer: int, header: dict,
                         payload: bytes = b"") -> bool:
        if peer == self.rank:
            self.self_btl.send(peer, header, payload)
            return True
        if self.proc_btl is not None and (peer in self._proc_ok
                                          or self._proc_route(peer)):
            self.proc_btl.send(peer, header, payload)
            return True
        if self.shm_btl is not None and (peer in self._shm_ok
                                         or self._shm_route(peer)):
            from ompi_tpu.mpi.btl_shm import FrameTooBig, PeerDeadError

            try:
                return self.shm_btl.try_send(peer, header, payload)
            except FrameTooBig:
                return False   # worker path reroutes oversize over tcp
            except PeerDeadError:
                self._drop_shm(peer)
                return False   # worker path surfaces/retries it
        if self.tcp_btl is not None:
            try:
                return self.tcp_btl.try_send(peer, header, payload)
            except Exception:  # noqa: BLE001 — inline contract: no raise
                return False
        return False

    def send(self, peer: int, header: dict, payload: bytes = b"") -> None:
        if self._fault is not None and peer != self.rank:
            verdict = self._fault.on_frame(peer, header)
            if verdict != "send":
                self._apply_fault(verdict, peer, header, payload)
                return
        self._send_routed(peer, header, payload)

    def _apply_fault(self, verdict, peer: int, header: dict,
                     payload) -> None:
        """Execute a non-"send" chaos verdict.  drop: the frame vanishes
        (the caller believes it was sent — exactly a lossy wire).  dup:
        delivered twice (the PML's seq gate holds the duplicate).  delay:
        re-sent later off a timer, payload copied first (zero-copy views
        alias user buffers the caller is free to reuse at completion).

        Never raises: callers include try_send_inline, whose contract is
        a non-raising bool — a verdict-sealed frame that then hits a
        dead route degrades to a drop (the lossy-wire semantics the
        verdict already committed to), it does not surface a raw
        ConnectionError into application code."""
        if verdict == "drop":
            return
        if verdict == "dup":
            try:
                self._send_routed(peer, header, payload)
                self._send_routed(peer, header, payload)
            except Exception:  # noqa: BLE001 — degrade to drop
                pass
            return
        _, ms = verdict
        data = bytes(payload)

        def later() -> None:
            try:
                self._send_routed(peer, header, data)
            except Exception:  # noqa: BLE001 — a dead route ends the delay
                pass

        t = threading.Timer(ms / 1000.0, later)
        t.daemon = True
        t.start()

    def _send_routed(self, peer: int, header: dict,
                     payload: bytes = b"") -> None:
        if trace_mod.active:
            trace_mod.instant("btl", "send", rank=self.rank, peer=peer,
                              nbytes=len(payload), t=header.get("t"))
        if peer == self.rank:
            self.self_btl.send(peer, header, payload)
            return
        if self.proc_btl is not None:
            if peer in self._proc_ok or self._proc_route(peer):
                self.proc_btl.send(peer, header, payload)
                return
        oversize: Optional[BaseException] = None
        if self.shm_btl is not None:
            # steady state: one set lookup, then straight into the ring
            if peer in self._shm_ok or self._shm_route(peer):
                from ompi_tpu.mpi.btl_shm import FrameTooBig, PeerDeadError

                try:
                    self.shm_btl.send(peer, header, payload)
                    return
                except FrameTooBig as e:
                    oversize = e   # oversize frame rides tcp; PML reorders
                except PeerDeadError:
                    # stale ring of a dead/respawning peer: drop the route
                    # and surface a retryable failure — the frame must NOT
                    # be silently lost in the orphaned mapping
                    self._drop_shm(peer)
                    raise ConnectionError(
                        f"rank {peer} died (shm ring orphaned); routes "
                        f"dropped pending rebind")
        if self.tcp_btl is None:
            if oversize is not None:
                raise MPIException(
                    f"frame to rank {peer} exceeds the shm ring's "
                    f"single-frame limit ({oversize}) and tcp is disabled "
                    f"— raise --mca btl_shm_ring_size or re-enable tcp "
                    f"for oversize fallback") from oversize
            raise MPIException(
                f"no btl route to rank {peer}: tcp is disabled and the "
                f"peer is not shm-reachable")
        self.tcp_btl.send(peer, header, payload)

    def _shm_route(self, peer: int) -> bool:
        shm_card = self._split_card(self._cards.get(peer, ""))[1]
        if shm_card and self.shm_btl.connect(peer, shm_card):
            self._shm_ok.add(peer)
            return True
        return False

    def _drop_shm(self, peer: int) -> None:
        self._shm_ok.discard(peer)
        self.shm_btl.drop_peer(peer)

    def _proc_route(self, peer: int) -> bool:
        proc_card = self._split_card(self._cards.get(peer, ""))[2]
        if proc_card and self.proc_btl.connect(peer, proc_card):
            self._proc_ok.add(peer)
            return True
        if peer in self._cards:
            # a known peer that is NOT in my address space stays that
            # way — cache the miss so per-send fast-lane checks are one
            # set lookup (a respawn rebind clears it via drop routes)
            self._proc_no.add(peer)
        return False

    def rebind(self, peer: int, card: str) -> None:
        """Re-point every transport at a peer's NEW business card (the
        peer was respawned by errmgr/respawn and re-announced itself).
        Stale sockets/rings are dropped; the next send redials lazily."""
        self._cards[peer] = card
        tcp_addr, _, _ = self._split_card(card)
        if self.tcp_btl is not None:
            with self.tcp_btl._lock:
                self.tcp_btl._peers[peer] = tcp_addr
                sock = self.tcp_btl._out.pop(peer, None)
                self.tcp_btl._out_locks.pop(peer, None)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            # fail+forget the native submission ring: parked senders
            # wake into ConnectionError and the new incarnation gets a
            # fresh ring on first send
            self.tcp_btl.drop_ring(peer)
        if self.shm_btl is not None:
            self._drop_shm(peer)
        if self.proc_btl is not None:
            self._proc_ok.discard(peer)
            self._proc_no.discard(peer)
            self.proc_btl._peer_tokens.pop(peer, None)

    def close(self) -> None:
        if self.tcp_btl is not None:
            self.tcp_btl.close()
        if self.shm_btl is not None:
            self.shm_btl.close()
        if self.proc_btl is not None:
            self.proc_btl.close()
