"""OSC — one-sided communication (MPI RMA windows).

≈ ompi/mca/osc (osc.h:370-408).  The reference has two strategies: map
windows onto RDMA put/get (osc/rdma, osc_rdma_comm.c:418,539) or emulate
over p2p (osc/pt2pt).  Host-path windows here are the pt2pt strategy
re-designed around an **active-message service**: each window runs a service
thread on a private dup of the communicator; PUT/GET/ACC/FETCH/LOCK requests
are applied atomically against the local buffer.  Synchronization:

- ``fence``  — active-target: an allreduce of sent-op counts tells each rank
  how many incoming ops to wait for, then a barrier (the standard
  counting-fence; the reference's pt2pt fence does the same bookkeeping).
- ``lock/unlock`` — passive-target: queued exclusive/shared locks at the
  target service; unlock flushes (waits until the target applied all my
  ops) before releasing.

Device-path RMA needs none of this machinery: a "window" on TPU is an
identically-sharded array and put/get are ``ppermute``/gather collectives —
see DeviceCommunicator.permute and the shmem device docs (SURVEY.md §3.5
TPU mapping).
"""

from __future__ import annotations

import itertools
import os
import struct
import threading
from typing import Any, Optional

import numpy as np

from ompi_tpu.core import dss, output
from ompi_tpu.mpi import op as op_mod
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.constants import ANY_SOURCE, ERR_REVOKED, MPIException
from ompi_tpu.mpi.request import Request

__all__ = ["Window", "DeviceWindow", "SharedWindow"]

_log = output.get_stream("osc")

_shwin_nonce = itertools.count(1)  # SharedWindow segment disambiguation

# Reserved tags on the window's private comm, in a range disjoint from the
# collective tags (coll/base.py TAG_* 1..10) — the service thread's
# ANY_SOURCE receive must never match a collective running on the same comm.
_TAG_REQ = 500
_TAG_REPLY = 501
# request-returning ops (rget/rget_accumulate) carry a unique reply tag so
# several can be outstanding to the same target without reply cross-matching
_TAG_RDYN_BASE = 1000
_TAG_RDYN_SPAN = 1_000_000


# first byte of a raw-payload control frame; dss type tags are 1..10, so
# the two framings are distinguishable from the first byte
_RAW_MAGIC = 0xFF

# dtype kinds safe to ship by their ``.str`` descriptor (structured /
# extension dtypes lose information there and take the dss path instead)
_RAW_KINDS = frozenset("biufc")


def _ctrl_send(comm, dest: int, obj: Any, tag: int,
               payload: Optional[np.ndarray] = None) -> Request:
    """Send one control message.  ``payload`` (an ndarray) is appended RAW
    after the dss header and rehydrated as a zero-copy view on the far
    side — the plan-collapsed fast path for bulk put/get traffic: ONE
    staging copy of the data total, where dss-packing the array inside the
    tuple paid three (tobytes, buffer assembly, unpack copy)."""
    if payload is not None:
        pay = np.ascontiguousarray(payload)
        if pay.dtype.kind in _RAW_KINDS:
            hdr = dss.pack((obj, pay.dtype.str, list(pay.shape)))
            frame = np.empty(5 + len(hdr) + pay.nbytes, np.uint8)
            frame[0] = _RAW_MAGIC
            frame[1:5] = np.frombuffer(struct.pack("<I", len(hdr)),
                                       np.uint8)
            frame[5:5 + len(hdr)] = np.frombuffer(hdr, np.uint8)
            if pay.nbytes:
                frame[5 + len(hdr):] = pay.reshape(-1).view(np.uint8)
            return comm._coll_isend(frame, dest, tag)
        obj = (*obj, pay)   # exotic dtype: embed in the dss record
    buf = np.frombuffer(dss.pack(obj), dtype=np.uint8)
    return comm._coll_isend(buf, dest, tag)


def _decode_ctrl(arr: np.ndarray) -> Any:
    """Decode one received control frame; a raw-appended payload comes
    back as a zero-copy ndarray view into the frame, appended to the
    header tuple (so dispatch sees the same shape either way)."""
    arr = np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
    if len(arr) and int(arr[0]) == _RAW_MAGIC:
        (hlen,) = struct.unpack_from("<I", arr, 1)
        obj, dtspec, shape = dss.unpack(
            arr[5:5 + hlen].tobytes(), n=1)[0]
        dtype = np.dtype(dtspec)
        n = 1
        for s in shape:
            n *= s
        view = np.frombuffer(arr, dtype=dtype, count=n,
                             offset=5 + hlen).reshape(shape)
        return (*obj, view)
    return dss.unpack(arr.tobytes(), n=1)[0]


def _ctrl_recv(comm, source: int, tag: int) -> Any:
    arr = comm._coll_irecv(None, source, tag).wait()
    return _decode_ctrl(arr)


def _check_predefined(op) -> None:
    """MPI rule: accumulate/fetch ops must be predefined (MPI-3.1 §11.3.4);
    the target rehydrates them by name, so user ops cannot travel."""
    if getattr(op_mod, op.name.upper(), None) is not op:
        raise MPIException(
            f"RMA accumulate requires a predefined op, got {op!r} "
            f"(user-defined ops are not valid for MPI_Accumulate)")


class _LockState:
    def __init__(self) -> None:
        self.holder: Optional[int] = None  # origin rank holding exclusive
        self.shared: set[int] = set()
        self.queue: list[tuple[int, bool]] = []  # (origin, exclusive)


class Window:
    """An RMA window over a local numpy buffer (collective constructor).

    ``create_dynamic`` builds a window with no initial memory; local regions
    are exposed with :meth:`attach` (local op, ≈ MPI_Win_attach) and remote
    ranks address them by the base offset attach returned — the analog of
    exchanging attached addresses out-of-band in MPI (MPI-3.1 §11.2.4).
    """

    def __init__(self, comm, size: Optional[int] = None,
                 buffer: Optional[np.ndarray] = None,
                 dtype=np.uint8, name: str = "win",
                 info=None, _dynamic: bool = False) -> None:
        self._dynamic = _dynamic
        # consulted info hints (≈ osc_rdma/osc_pt2pt reading win info):
        # no_locks=true promises the app never uses passive-target sync —
        # lock/unlock/lock_all then fail fast instead of running a
        # pointless lock service protocol
        self.info = info
        self._no_locks = bool(info) and str(
            info.get("no_locks") or "").lower() in ("true", "1")
        self._regions: dict[int, np.ndarray] = {}   # base offset → flat view
        self._next_base = 0
        if _dynamic:
            buffer = np.zeros(0, dtype=dtype)
        elif buffer is None:
            if size is None:
                raise MPIException("Window needs size= or buffer=")
            buffer = np.zeros(size, dtype=dtype)
        buffer = np.asarray(buffer)
        if not buffer.flags.c_contiguous:
            # a copy would silently decouple the window from the caller's
            # array (remote puts landing somewhere the caller never sees)
            raise MPIException(
                "Window buffer must be C-contiguous; pass a contiguous "
                "array (np.ascontiguousarray) and keep a reference to it")
        # flat VIEW (never a copy, given contiguity): RMA offsets address
        # elements in row-major order and range checks agree with indexing
        self.buf = buffer.reshape(-1)
        self._parent_comm = comm   # revocation coherence (see _check_ft)
        self.comm = comm.dup(name=f"{name}.osc")
        self.name = name
        self._buf_lock = threading.RLock()
        self._lock_state = _LockState()
        self._applied_from: dict[int, int] = {}   # origin → ops applied
        self._applied_total = 0
        self._sent_to = [0] * comm.size           # my ops per target
        self._cv = threading.Condition(self._buf_lock)
        self._errors: list[str] = []          # failed incoming put/acc ops
        self._service_dead = False
        self._epoch_reqs: list[Request] = []
        self._origin_lock = threading.Lock()      # serializes blocking ops
        self._ids = itertools.count(1)
        # PSCW epoch state (≈ osc.h:391-394 post/start/complete/wait)
        self._posts: set[int] = set()             # targets that posted to me
        self._pscw_done: set[int] = set()         # origins that completed
        self._access_group: Optional[list[int]] = None
        self._exposure_group: Optional[set[int]] = None
        self._service = threading.Thread(
            target=self._serve, name=f"osc-{name}-{comm.rank}", daemon=True)
        self._service.start()

    # -- dynamic windows ---------------------------------------------------

    @classmethod
    def create_dynamic(cls, comm, dtype=np.uint8,
                       name: str = "dynwin", info=None) -> "Window":
        """≈ MPI_Win_create_dynamic: a window with no memory attached;
        expose regions later with :meth:`attach` (collective constructor,
        local attach).  ``info`` hints (e.g. no_locks) apply as on a
        created window."""
        return cls(comm, name=name, dtype=dtype, info=info, _dynamic=True)

    def attach(self, array: np.ndarray) -> int:
        """≈ MPI_Win_attach (local): expose ``array`` through this dynamic
        window and return its base offset — the "address" remote ranks use.
        A one-element guard gap separates regions so an access can never
        silently span two attachments (MPI forbids spanning)."""
        if not self._dynamic:
            raise MPIException("attach is only valid on a dynamic window")
        array = np.asarray(array)
        if not array.flags.c_contiguous:
            raise MPIException("attach needs a C-contiguous array")
        flat = array.reshape(-1)
        with self._cv:
            base = self._next_base
            self._regions[base] = flat
            self._next_base = base + flat.size + 1
        return base

    def detach(self, base: int) -> None:
        """≈ MPI_Win_detach (local)."""
        with self._cv:
            if self._regions.pop(base, None) is None:
                raise MPIException(f"detach: no region attached at {base}")

    def _locate(self, offset: int, count: int) -> np.ndarray:
        """Resolve [offset, offset+count) to a writable flat view — the
        window buffer itself, or the containing attached region of a
        dynamic window.  Caller holds ``_buf_lock``."""
        if not self._dynamic:
            self._check_range(offset, count)
            return self.buf[offset:offset + count]
        if count < 0:
            raise MPIException(f"negative RMA count {count}")
        for base, arr in self._regions.items():
            if base <= offset and offset + count <= base + arr.size:
                return arr[offset - base:offset - base + count]
        raise MPIException(
            f"RMA access [{offset}:{offset + count}] hits no attached "
            f"region of dynamic window {self.name!r}")

    # -- origin side -------------------------------------------------------

    def _track(self, target: int, req: Optional[Request] = None) -> None:
        """Count an issued op toward fence/flush totals; reap finished
        requests (amortized — a scan per op would be quadratic when the
        send worker lags the issue rate)."""
        self._sent_to[target] += 1
        if req is not None:
            self._epoch_reqs.append(req)
            if len(self._epoch_reqs) > 256:
                self._epoch_reqs = [
                    r for r in self._epoch_reqs if not r.done()]

    def _check_range(self, offset: int, count: int) -> None:
        if offset < 0 or count < 0 or offset + count > self.buf.size:
            raise MPIException(
                f"RMA access [{offset}:{offset + count}] outside window "
                f"of {self.buf.size} elements")

    def _recv_reply(self, source: int) -> Any:
        status, payload = _ctrl_recv(self.comm, source, _TAG_REPLY)
        if status == "err":
            raise MPIException(
                f"RMA op failed at rank {source}: {payload}")
        return payload

    def put(self, target: int, data: np.ndarray, offset: int = 0) -> None:
        """≈ MPI_Put: completes locally at the next sync (fence/unlock)."""
        data = np.ascontiguousarray(data)
        if target == self.comm.rank:
            self._apply_put(self.comm.rank, offset, data)  # raises pre-track
            self._track(target)
            return
        req = _ctrl_send(self.comm, target,
                         ("put", self.comm.rank, offset), _TAG_REQ,
                         payload=data)
        self._track(target, req)

    def put_strided(self, target: int, data: np.ndarray, offset: int = 0,
                    stride: int = 1) -> None:
        """Strided put: element i lands at ``offset + i*stride`` — one wire
        message and one counted op (the shmem_iput transport; the reference
        expresses this as a vector datatype over MPI_Put)."""
        data = np.ascontiguousarray(data).reshape(-1)
        if stride == 1:
            return self.put(target, data, offset)
        if stride < 1:
            raise MPIException(f"put_strided needs stride >= 1, got {stride}")
        if target == self.comm.rank:
            self._apply_put_strided(self.comm.rank, offset, stride, data)
            self._track(target)
            return
        req = _ctrl_send(self.comm, target,
                         ("puts", self.comm.rank, offset, stride),
                         _TAG_REQ, payload=data)
        self._track(target, req)

    def get(self, target: int, count: int, offset: int = 0) -> np.ndarray:
        """≈ MPI_Get (blocking convenience: data returns immediately)."""
        if target == self.comm.rank:
            with self._buf_lock:
                return self._locate(offset, count).copy()
        with self._origin_lock:
            _ctrl_send(self.comm, target,
                       ("get", self.comm.rank, offset, count), _TAG_REQ).wait()
            return np.asarray(self._recv_reply(target))

    def accumulate(self, target: int, data: np.ndarray, op=op_mod.SUM,
                   offset: int = 0) -> None:
        """≈ MPI_Accumulate: elementwise op applied atomically at target."""
        _check_predefined(op)
        data = np.ascontiguousarray(data)
        if target == self.comm.rank:
            self._apply_acc(self.comm.rank, offset, data, op.name)
            self._track(target)
            return
        req = _ctrl_send(self.comm, target,
                         ("acc", self.comm.rank, offset, op.name),
                         _TAG_REQ, payload=data)
        self._track(target, req)

    def fetch_op(self, target: int, value, op=op_mod.SUM,
                 offset: int = 0) -> np.ndarray:
        """≈ MPI_Fetch_and_op: atomic read-modify-write, returns old value."""
        _check_predefined(op)
        value = np.ascontiguousarray(value)
        if target == self.comm.rank:
            old = self._apply_fetch(self.comm.rank, offset, value, op.name)
            self._track(target)
            return old
        with self._origin_lock:
            self._track(target)
            _ctrl_send(self.comm, target,
                       ("fetch", self.comm.rank, offset, value, op.name),
                       _TAG_REQ).wait()
            return np.asarray(self._recv_reply(target))

    def _reply_tag(self) -> int:
        return _TAG_RDYN_BASE + (next(self._ids) % _TAG_RDYN_SPAN)

    def _async_reply(self, target: int, rtag: int) -> Request:
        """Post the reply receive for a request-returning op; the returned
        request completes with the decoded payload (or the target's error)."""
        inner = self.comm._coll_irecv(None, target, rtag)
        outer = Request(kind="rma")

        def _finish(r: Request) -> None:
            try:
                status, payload = _decode_ctrl(r.wait())
            except BaseException as e:          # transport failure
                outer.fail(e)
                return
            if status == "err":
                outer.fail(MPIException(
                    f"RMA op failed at rank {target}: {payload}"))
            else:
                outer.complete(np.asarray(payload))

        inner.add_completion_callback(_finish)
        return outer

    def get_accumulate(self, target: int, data: np.ndarray, op=op_mod.SUM,
                       offset: int = 0) -> np.ndarray:
        """≈ MPI_Get_accumulate: atomically fetch the target range and
        combine ``data`` into it; returns the pre-op contents.  ``NO_OP``
        gives an atomic get, ``REPLACE`` an atomic fetching put."""
        return self.rget_accumulate(target, data, op, offset).wait()

    # -- request-returning ops (≈ MPI_Rput/Rget/Raccumulate, MPI-3.1 §11.3.5;
    # completion of the request = local completion; remote completion still
    # needs flush/unlock/fence, exactly as in MPI) ------------------------

    def rput(self, target: int, data: np.ndarray, offset: int = 0) -> Request:
        """≈ MPI_Rput: the request completes when the origin buffer is
        reusable (the data is packed at issue, so that is immediate for the
        local case and send-completion otherwise)."""
        data = np.ascontiguousarray(data)
        if target == self.comm.rank:
            self._apply_put(self.comm.rank, offset, data)
            self._track(target)
            done = Request(kind="rma")
            done.complete(None)
            return done
        req = _ctrl_send(self.comm, target,
                         ("put", self.comm.rank, offset), _TAG_REQ,
                         payload=data)
        self._track(target, req)
        return req

    def raccumulate(self, target: int, data: np.ndarray, op=op_mod.SUM,
                    offset: int = 0) -> Request:
        """≈ MPI_Raccumulate."""
        _check_predefined(op)
        data = np.ascontiguousarray(data)
        if target == self.comm.rank:
            self._apply_acc(self.comm.rank, offset, data, op.name)
            self._track(target)
            done = Request(kind="rma")
            done.complete(None)
            return done
        req = _ctrl_send(self.comm, target,
                         ("acc", self.comm.rank, offset, op.name),
                         _TAG_REQ, payload=data)
        self._track(target, req)
        return req

    def rget(self, target: int, count: int, offset: int = 0) -> Request:
        """≈ MPI_Rget: ``request.wait()`` returns the fetched array.
        Several rgets may be outstanding to the same target (each reply
        rides a unique tag)."""
        if target == self.comm.rank:
            with self._buf_lock:
                out = self._locate(offset, count).copy()
            done = Request(kind="rma")
            done.complete(out)
            return done
        rtag = self._reply_tag()
        reply = self._async_reply(target, rtag)
        _ctrl_send(self.comm, target,
                   ("get2", self.comm.rank, offset, count, rtag), _TAG_REQ)
        return reply

    def rget_accumulate(self, target: int, data: np.ndarray, op=op_mod.SUM,
                        offset: int = 0) -> Request:
        """≈ MPI_Rget_accumulate: wait() returns the pre-op target range."""
        _check_predefined(op)
        data = np.ascontiguousarray(data)
        if target == self.comm.rank:
            old = self._apply_fetch(self.comm.rank, offset, data, op.name)
            self._track(target)
            done = Request(kind="rma")
            done.complete(old)
            return done
        rtag = self._reply_tag()
        reply = self._async_reply(target, rtag)
        self._track(target)
        _ctrl_send(self.comm, target,
                   ("fetch2", self.comm.rank, offset, data, op.name, rtag),
                   _TAG_REQ)
        return reply

    def compare_swap(self, target: int, compare, value,
                     offset: int = 0) -> np.ndarray:
        """≈ MPI_Compare_and_swap (single element)."""
        if target == self.comm.rank:
            old = self._apply_cswap(self.comm.rank, offset, compare, value)
            self._track(target)
            return old
        with self._origin_lock:
            self._track(target)
            _ctrl_send(self.comm, target,
                       ("cswap", self.comm.rank, offset,
                        np.asarray(compare), np.asarray(value)), _TAG_REQ).wait()
            return np.asarray(self._recv_reply(target))

    # -- synchronization ---------------------------------------------------

    def _check_ft(self, what: str) -> None:
        """Epoch-entry ULFM gate: a window whose parent communicator was
        revoked is itself poisoned (the dup inherits the revocation here,
        so every member's epochs error coherently), and an already-revoked
        window refuses new epochs with MPI_ERR_REVOKED."""
        from ompi_tpu.mpi import ft

        if (self.comm.pml.ft is None
                and self._parent_comm.pml.ft is None):
            return   # FT never engaged in this process: zero-cost exit
        if (ft.comm_is_revoked(self._parent_comm)
                and not ft.comm_is_revoked(self.comm)):
            ft.pml_ft(self.comm.pml).mark_revoked(self.comm.cid)
        if ft.comm_is_revoked(self.comm):
            raise MPIException(
                f"window {self.name!r}: {what} on a revoked communicator",
                error_class=ERR_REVOKED)

    def fence(self) -> None:
        """Active-target epoch boundary (≈ MPI_Win_fence)."""
        self._check_ft("fence")
        if trace_mod.active:   # epoch spans on the osc timeline
            with trace_mod.span("osc", "fence", rank=self.comm.pml.rank,
                                win=self.name):
                return self._fence_impl()
        return self._fence_impl()

    def _fence_impl(self) -> None:
        for r in self._epoch_reqs:
            r.wait()
        self._epoch_reqs.clear()
        # every rank learns how many ops target it: column sums of the
        # sent-counts matrix
        sent = np.array(self._sent_to, dtype=np.int64)
        incoming = self.comm.allreduce(sent, op=op_mod.SUM)
        expected = int(incoming[self.comm.rank])
        with self._cv:
            self._cv.wait_for(lambda: self._applied_total >= expected
                              or self._service_dead)
            if self._service_dead and self._applied_total < expected:
                raise MPIException(
                    f"window {self.name!r}: service stopped with "
                    f"{expected - self._applied_total} incoming ops pending")
            errors, self._errors = self._errors, []
        self.comm.barrier()
        if errors:
            raise MPIException(
                "RMA ops failed at this target during the epoch: "
                + "; ".join(errors))

    # -- PSCW (generalized active target, ≈ osc.h:391-394) ----------------

    def post(self, origins: list[int]) -> None:
        """≈ MPI_Win_post: expose this window to ``origins`` (nonblocking).
        Matching ``start`` calls at the origins unblock once this arrives."""
        self._check_ft("post")
        if self._exposure_group is not None:
            raise MPIException("MPI_Win_post while an exposure epoch is open")
        self._exposure_group = set(origins)
        for o in origins:
            _ctrl_send(self.comm, o, ("post", self.comm.rank), _TAG_REQ)
        if trace_mod.active:
            trace_mod.instant("osc", "post", rank=self.comm.pml.rank,
                              win=self.name, origins=list(origins))

    def start(self, targets: list[int]) -> None:
        """≈ MPI_Win_start: open an access epoch to ``targets``; blocks until
        every target's post arrived (the reference may defer this wait to the
        first op — blocking here keeps the semantics strict and simple)."""
        self._check_ft("start")
        if self._access_group is not None:
            raise MPIException("MPI_Win_start while an access epoch is open")
        want = set(targets)
        with self._cv:
            self._cv.wait_for(lambda: want <= self._posts
                              or self._service_dead)
            if not want <= self._posts:
                raise MPIException(
                    f"window {self.name!r}: service stopped while waiting "
                    f"for posts from {sorted(want - self._posts)}")
            self._posts -= want
        self._access_group = list(targets)

    def complete(self) -> None:
        """≈ MPI_Win_complete: end the access epoch — all my ops to the
        targets are locally complete and a completion marker is on the wire
        behind them (FIFO per channel ⇒ ordered after every op)."""
        if self._access_group is None:
            raise MPIException("MPI_Win_complete without MPI_Win_start")
        _t0 = trace_mod.begin() if trace_mod.active else 0
        for r in self._epoch_reqs:
            r.wait()
        self._epoch_reqs.clear()
        for t in self._access_group:
            _ctrl_send(self.comm, t,
                       ("pscw_done", self.comm.rank, self._sent_to[t]),
                       _TAG_REQ)
        if _t0 and trace_mod.active:
            trace_mod.complete("osc", "pscw_complete", _t0,
                               rank=self.comm.pml.rank, win=self.name,
                               targets=list(self._access_group))
        self._access_group = None

    def wait(self) -> None:
        """≈ MPI_Win_wait: end the exposure epoch — blocks until every origin
        in the post group completed (hence all their ops are applied here)."""
        if self._exposure_group is None:
            raise MPIException("MPI_Win_wait without MPI_Win_post")
        _t0 = trace_mod.begin() if trace_mod.active else 0
        want = self._exposure_group
        with self._cv:
            self._cv.wait_for(lambda: want <= self._pscw_done
                              or self._service_dead)
            if not want <= self._pscw_done:
                raise MPIException(
                    f"window {self.name!r}: service stopped with "
                    f"incomplete origins {sorted(want - self._pscw_done)}")
            self._pscw_done -= want
            errors, self._errors = self._errors, []
        self._exposure_group = None
        if _t0 and trace_mod.active:
            trace_mod.complete("osc", "pscw_wait", _t0,
                               rank=self.comm.pml.rank, win=self.name)
        if errors:
            raise MPIException(
                "RMA ops failed at this target during the PSCW epoch: "
                + "; ".join(errors))

    def test_epoch(self) -> bool:
        """≈ MPI_Win_test: nonblocking wait(); True ⇒ epoch closed."""
        if self._exposure_group is None:
            raise MPIException("MPI_Win_test without MPI_Win_post")
        with self._cv:
            if not self._exposure_group <= self._pscw_done:
                return False
        self.wait()
        return True

    def lock_all(self) -> None:
        """≈ MPI_Win_lock_all: shared lock on every rank."""
        for t in range(self.comm.size):
            self.lock(t, exclusive=False)

    def unlock_all(self) -> None:
        """≈ MPI_Win_unlock_all."""
        for t in range(self.comm.size):
            self.unlock(t)

    def flush_all(self) -> None:
        """≈ MPI_Win_flush_all: my ops are applied at every target."""
        for t in range(self.comm.size):
            self.flush(t)

    def flush_local(self, target: int) -> None:
        """≈ MPI_Win_flush_local: origin buffers reusable.  Ops here pack at
        issue, so local completion only needs the sends drained."""
        for r in self._epoch_reqs:
            r.wait()
        self._epoch_reqs.clear()

    def flush_local_all(self) -> None:
        """≈ MPI_Win_flush_local_all (local completion is target-agnostic
        here — see flush_local)."""
        self.flush_local(-1)

    def get_group(self):
        """≈ MPI_Win_get_group."""
        return self.comm.group

    def get_name(self) -> str:
        """≈ MPI_Win_get_name."""
        return self.name

    def set_name(self, name: str) -> None:
        """≈ MPI_Win_set_name."""
        self.name = str(name)

    def set_info(self, info) -> None:
        """≈ MPI_Win_set_info (hints stored; no_locks honored at create)."""
        self.info = info

    def get_info(self):
        """≈ MPI_Win_get_info."""
        from ompi_tpu.mpi.info import Info

        return getattr(self, "info", None) or Info()

    def lock(self, target: int, exclusive: bool = True) -> None:
        """≈ MPI_Win_lock (passive target). A local target still goes
        through the service, keeping lock fairness uniform."""
        self._check_ft("lock")
        if self._no_locks:
            raise MPIException(
                "MPI_Win_lock on a window created with the no_locks=true "
                "info hint (the app promised no passive-target sync)",
                error_class=51)
        _t0 = trace_mod.begin() if trace_mod.active else 0
        with self._origin_lock:
            _ctrl_send(self.comm, target,
                       ("lock", self.comm.rank, bool(exclusive)),
                       _TAG_REQ).wait()
            self._recv_reply(target)  # grant
        if _t0 and trace_mod.active:
            trace_mod.complete("osc", "lock", _t0,
                               rank=self.comm.pml.rank, win=self.name,
                               target=target, exclusive=bool(exclusive))

    def unlock(self, target: int) -> None:
        """≈ MPI_Win_unlock: flush my ops at target, release the lock."""
        _t0 = trace_mod.begin() if trace_mod.active else 0
        with self._origin_lock:
            _ctrl_send(self.comm, target,
                       ("unlock", self.comm.rank, self._sent_to[target]),
                       _TAG_REQ).wait()
            self._recv_reply(target)  # flushed + released
        if _t0 and trace_mod.active:
            trace_mod.complete("osc", "unlock", _t0,
                               rank=self.comm.pml.rank, win=self.name,
                               target=target)

    def flush(self, target: int) -> None:
        """≈ MPI_Win_flush: wait until target applied all my ops."""
        if target == self.comm.rank or self._sent_to[target] == 0:
            return
        with self._origin_lock:
            _ctrl_send(self.comm, target,
                       ("flush", self.comm.rank, self._sent_to[target]),
                       _TAG_REQ).wait()
            self._recv_reply(target)

    def free(self) -> None:
        """Collective destructor (≈ MPI_Win_free)."""
        self.comm.barrier()
        _ctrl_send(self.comm, self.comm.rank, ("stop",), _TAG_REQ).wait()
        self._service.join(timeout=5)

    # -- target side (service thread) --------------------------------------

    def _serve(self) -> None:
        while True:
            try:
                msg = _ctrl_recv(self.comm, ANY_SOURCE, _TAG_REQ)
            except Exception as e:
                # a failed receive (peer death, transport teardown before
                # free()) must not leave waiters hanging silently: flag the
                # service as gone and wake them so fence() can raise
                with self._cv:
                    self._service_dead = True
                    self._cv.notify_all()
                _log.verbose(1, "window %r service stopped: %r",
                             self.name, e)
                return
            kind = msg[0]
            if kind == "stop":
                return
            try:
                self._dispatch(kind, msg)
            except Exception as e:
                self._dispatch_failed(kind, msg, e)

    def _dispatch_failed(self, kind: str, msg: tuple, e: Exception) -> None:
        """A bad op must not wedge the job: counted ops still bump the
        applied counter (so fences/flushes terminate) and reply-carrying
        ops turn the failure into the origin's exception."""
        origin = msg[1] if len(msg) > 1 else -1
        if kind in ("put", "puts", "acc", "fetch", "cswap", "fetch2"):
            with self._cv:
                if kind in ("put", "puts", "acc"):
                    # no reply channel: surface at this rank's next fence
                    self._errors.append(f"{kind} from rank {origin}: {e}")
                self._bump(origin)
        if kind in ("get", "fetch", "cswap", "lock", "unlock", "flush"):
            try:
                _ctrl_send(self.comm, origin, ("err", str(e)), _TAG_REPLY)
            except Exception:
                pass
        if kind in ("get2", "fetch2"):
            try:
                _ctrl_send(self.comm, origin, ("err", str(e)), msg[-1])
            except Exception:
                pass

    def _dispatch(self, kind: str, msg: tuple) -> None:
        if kind == "put":
            _, origin, offset, data = msg
            self._apply_put(origin, offset, data)
        elif kind == "puts":
            _, origin, offset, stride, data = msg
            self._apply_put_strided(origin, offset, stride, data)
        elif kind == "acc":
            _, origin, offset, opname, data = msg
            self._apply_acc(origin, offset, data, opname)
        elif kind == "get":
            _, origin, offset, count = msg
            with self._buf_lock:
                out = self._locate(offset, count).copy()
            _ctrl_send(self.comm, origin, ("ok",), _TAG_REPLY,
                       payload=out)
        elif kind == "get2":
            _, origin, offset, count, rtag = msg
            with self._buf_lock:
                out = self._locate(offset, count).copy()
            _ctrl_send(self.comm, origin, ("ok",), rtag, payload=out)
        elif kind == "fetch2":
            _, origin, offset, value, opname, rtag = msg
            old = self._apply_fetch(origin, offset, value, opname)
            _ctrl_send(self.comm, origin, ("ok",), rtag, payload=old)
        elif kind == "post":
            _, target = msg
            with self._cv:
                self._posts.add(target)
                self._cv.notify_all()
        elif kind == "pscw_done":
            # FIFO per (origin → me) channel on _TAG_REQ means every op the
            # origin issued this epoch was dispatched before this marker —
            # no applied-count handshake needed.  Validated explicitly (a
            # bare assert vanishes under -O, and an AssertionError swallowed
            # by the dispatch loop would hang the peer's Win_wait silently);
            # the epoch still completes so wait() returns with the error on
            # the record rather than deadlocking.
            _, origin, expected = msg
            with self._cv:
                applied = self._applied_from.get(origin, 0)
                if applied < expected:
                    # recorded on the epoch: the waiting Win_wait returns
                    # (no silent hang) but raises with this error
                    self._errors.append(
                        f"pscw_done from {origin} before its ops were "
                        f"applied ({applied} < {expected}) — per-channel "
                        f"FIFO violated")
                    _log.error("osc: %s", self._errors[-1])
                self._pscw_done.add(origin)
                self._cv.notify_all()
        elif kind == "fetch":
            _, origin, offset, value, opname = msg
            old = self._apply_fetch(origin, offset, value, opname)
            _ctrl_send(self.comm, origin, ("ok", old), _TAG_REPLY)
        elif kind == "cswap":
            _, origin, offset, compare, value = msg
            old = self._apply_cswap(origin, offset, compare, value)
            _ctrl_send(self.comm, origin, ("ok", old), _TAG_REPLY)
        elif kind == "lock":
            _, origin, exclusive = msg
            self._handle_lock(origin, exclusive)
        elif kind == "unlock":
            _, origin, expected = msg
            self._wait_applied(origin, expected)
            self._handle_unlock(origin)
            _ctrl_send(self.comm, origin, ("ok", None), _TAG_REPLY)
        elif kind == "flush":
            _, origin, expected = msg
            self._wait_applied(origin, expected)
            _ctrl_send(self.comm, origin, ("ok", None), _TAG_REPLY)
        else:
            raise MPIException(f"osc: unknown request {kind!r}")

    # -- local application (atomic under _buf_lock) ------------------------

    def _bump(self, origin: int) -> None:
        self._applied_from[origin] = self._applied_from.get(origin, 0) + 1
        self._applied_total += 1
        self._cv.notify_all()

    def _apply_put(self, origin: int, offset: int, data: np.ndarray) -> None:
        with self._cv:
            seg = self._locate(offset, len(data))
            seg[:] = data.astype(seg.dtype, copy=False)
            self._bump(origin)

    def _apply_put_strided(self, origin: int, offset: int, stride: int,
                           data: np.ndarray) -> None:
        with self._cv:
            span = (len(data) - 1) * stride + 1 if len(data) else 0
            seg = self._locate(offset, span)
            seg[::stride] = data.astype(seg.dtype, copy=False)
            self._bump(origin)

    def _apply_acc(self, origin: int, offset: int, data: np.ndarray,
                   opname: str) -> None:
        op = getattr(op_mod, opname.upper())
        with self._cv:
            seg = self._locate(offset, len(data))
            seg[:] = op.host(seg.copy(), data.astype(seg.dtype, copy=False))
            self._bump(origin)

    def _apply_fetch(self, origin: int, offset: int, value: np.ndarray,
                     opname: str) -> np.ndarray:
        op = getattr(op_mod, opname.upper())
        with self._cv:
            n = max(1, np.asarray(value).size)
            seg = self._locate(offset, n)
            old = seg.copy()
            seg[:] = op.host(
                old, np.asarray(value).astype(old.dtype, copy=False))
            self._bump(origin)
            return old

    def _apply_cswap(self, origin: int, offset: int, compare,
                     value) -> np.ndarray:
        with self._cv:
            seg = self._locate(offset, 1)
            old = seg.copy()
            if old[0] == np.asarray(compare).reshape(-1)[0]:
                seg[0] = np.asarray(value).reshape(-1)[0]
            self._bump(origin)
            return old

    def _wait_applied(self, origin: int, expected: int) -> None:
        with self._cv:
            self._cv.wait_for(
                lambda: self._applied_from.get(origin, 0) >= expected)

    # -- lock queueing -----------------------------------------------------

    def _handle_lock(self, origin: int, exclusive: bool) -> None:
        with self._cv:
            st = self._lock_state
            # new requests queue behind ANY waiter (even shared behind a
            # queued exclusive) — otherwise a stream of shared lockers
            # starves exclusive waiters forever
            grantable = (st.holder is None and not st.queue and
                         (exclusive is False or not st.shared))
            if grantable:
                if exclusive:
                    st.holder = origin
                else:
                    st.shared.add(origin)
            else:
                st.queue.append((origin, exclusive))
                return
        _ctrl_send(self.comm, origin, ("ok", None), _TAG_REPLY)

    def _handle_unlock(self, origin: int) -> None:
        grants = []
        with self._cv:
            st = self._lock_state
            if st.holder == origin:
                st.holder = None
            st.shared.discard(origin)
            while st.queue and st.holder is None:
                nxt, excl = st.queue[0]
                if excl:
                    if st.shared:
                        break
                    st.queue.pop(0)
                    st.holder = nxt
                    grants.append(nxt)
                    break
                st.queue.pop(0)
                st.shared.add(nxt)
                grants.append(nxt)
        for g in grants:
            _ctrl_send(self.comm, g, ("ok", None), _TAG_REPLY)


class DeviceWindow:
    """Device-resident RMA window: the osc/rdma strategy on ICI.

    ≈ ompi/mca/osc/rdma (osc_rdma_comm.c:418 put → btl_put, :539 get →
    btl_get): where the host Window above emulates RMA over p2p messages
    (the pt2pt strategy), a DeviceWindow maps put/get straight onto the
    one-sided remote-DMA kernels (ops/remote_dma) — bytes cross ICI once,
    origin→target, no service thread, no active messages.

    The window is a functional value: an identically-sharded jax array,
    one shard per rank, mutated by returning the new array (XLA donates
    the old buffer via the cached jit).  Epochs: ``fence()`` is a device
    barrier; per-op completion is implicit (each kernel drains its DMA
    before returning — the flush/quiet the reference must issue
    explicitly, osc_rdma_sync.c).
    """

    def __init__(self, dcomm, local_shape, dtype=np.float32, fill=0):
        self.comm = dcomm
        self.local_shape = tuple(int(s) for s in local_shape)
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(dcomm.axes if len(dcomm.axes) > 1 else dcomm.axes[0])
        shape = (dcomm.size,) + self.local_shape
        self.array = jax.jit(
            lambda: jnp.full(shape, fill, dtype=dtype),
            out_shardings=NamedSharding(dcomm.mesh, spec))()

    @property
    def dtype(self):
        return self.array.dtype

    def _origin_value(self, data) -> "Any":
        """Lift origin-local data (local_shape) to the sharded global
        layout run_method expects (every rank passes the same program —
        only the origin's shard is read by the kernel)."""
        import jax.numpy as jnp

        data = jnp.asarray(data, dtype=self.array.dtype)
        if data.shape != self.local_shape:
            raise MPIException(
                f"DeviceWindow: data shape {data.shape} must match the "
                f"window's local shape {self.local_shape}")
        return jnp.broadcast_to(data[None], self.array.shape)

    def put(self, data, origin: int, target: int) -> None:
        """origin's ``data`` lands in target's window shard (one-sided:
        only the origin→target ICI path moves bytes).  The old window
        buffer is donated to the kernel (no 2× window residency).

        Driver-mode convenience has a cost the traced path doesn't:
        ``data`` is replicated to every shard on the way in (run_method's
        uniform specs).  Hot paths should trace DeviceCommunicator.put
        inside their own shard_map instead."""
        self.array = self.comm.run_method(
            "put", self.array, self._origin_value(data),
            margs=(int(origin), int(target)), donate=(0,))

    def get(self, origin: int, target: int):
        """origin fetches target's window shard one-sided; returns the
        host value of that shard."""
        fetched = self.comm.run_method(
            "get", self.array, margs=(int(target), int(origin)))
        return np.asarray(fetched[int(origin)])

    def local(self, rank: int):
        """Host copy of ``rank``'s current window shard."""
        return np.asarray(self.array[int(rank)])

    def fence(self) -> None:
        """Active-target epoch boundary: device barrier (ops already
        completed per-kernel; the fence orders epochs)."""
        self.comm.run_method("barrier", np.zeros((self.comm.size,),
                                                 np.int32))

    def free(self) -> None:
        self.array = None


class SharedWindow:
    """≈ MPI_Win_allocate_shared + the osc/sm component: every rank of a
    shared-memory-domain communicator (MPI_Comm_split_type(
    COMM_TYPE_SHARED) — enforced) owns a contiguous slice of ONE shared
    segment, and any rank may load/store any slice directly — no
    messages, the memory IS the window (osc_sm_component.c's model).

    ``shared_query(rank)`` returns a numpy view of that rank's slice
    (zero-copy into the mapping).  ``sync()`` is the WIN_SYNC memory
    barrier + a communicator barrier; direct stores are visible to peers
    after it (x86 TSO + the mmap being literally the same pages).
    ``fetch_add(rank, offset8, delta)`` exposes the native u64 atomics
    on any aligned slot, the lock-free counter pattern osc/sm serves.
    """

    def __init__(self, comm, local_size: int, dtype=np.uint8,
                 name: str = "shwin") -> None:
        self.comm = comm
        self.name = name
        self.dtype = np.dtype(dtype)
        keys = np.asarray(comm.allgather(np.array(
            [comm._my_host_key()], np.int64))).ravel()
        if len(set(int(k) for k in keys)) != 1:
            raise MPIException(
                "SharedWindow requires a single-host communicator "
                "(split_type(COMM_TYPE_SHARED) first)", error_class=3)
        # per-rank slices padded to 8 bytes so every slice start is a
        # valid atomic slot (fetch_add's alignment contract)
        nbytes = (int(local_size) * self.dtype.itemsize + 7) & ~7
        self._local_bytes = int(local_size) * self.dtype.itemsize
        # padded slice sizes AND unpadded extents: shared_query(rank) must
        # report rank's OWN requested extent (heterogeneous local_size —
        # e.g. rank 0 owns the whole node buffer, everyone else passes 0 —
        # is the core MPI_Win_allocate_shared use case)
        both = np.asarray(comm.allgather(np.array(
            [nbytes, self._local_bytes], np.int64))).reshape(-1, 2)
        sizes = both[:, 0]
        self._extents = both[:, 1]
        self._offsets = np.concatenate([[0], np.cumsum(sizes)])
        total = int(self._offsets[-1])
        # rank 0 creates (nonce'd name — concurrent windows must not
        # collide), everyone attaches; same discipline as sharedfp/sm.
        # backing_dir() falls back when /dev/shm is absent — it resolves
        # identically in every same-host process.
        from ompi_tpu.core import shmseg

        base_dir = shmseg.backing_dir()
        safe = "".join(c for c in name if c.isalnum())[:16] or "shwin"
        self._seg = None
        err = ""
        # the create/attach outcome is AGREED collectively (the sharedfp
        # discipline): a rank-0 ENOSPC must raise on every rank, not
        # strand the others in the bcast/barrier below.  The name bcast
        # doubles as the outcome flag — empty name ⇒ create failed.
        if comm.rank == 0:
            nonce = os.getpid() << 16 | (next(_shwin_nonce) & 0xFFFF)
            seg_name = f"otpu-shwin-{safe}-{os.getuid()}-{nonce:x}"
            try:
                self._seg = shmseg.create(seg_name, max(total, 8),
                                          dir=base_dir, publish=False)
                np.frombuffer(self._seg.buf, np.uint8)[:] = 0
                self._seg.publish()
            except OSError as e:
                err = str(e)
                seg_name = ""
            comm.bcast(np.frombuffer(
                seg_name.encode().ljust(96), np.uint8).copy(), root=0)
        else:
            raw = np.asarray(comm.bcast(np.zeros(96, np.uint8), root=0))
            seg_name = bytes(raw).rstrip(b"\x00").rstrip().decode()
            if not seg_name:
                err = "segment creation failed on rank 0"
            else:
                try:
                    self._seg = shmseg.attach(
                        os.path.join(base_dir, seg_name))
                except OSError as e:
                    err = str(e)
        from ompi_tpu.mpi import op as op_mod

        ok = int(np.asarray(comm.allreduce(np.array(
            [0 if err else 1], np.int32), op=op_mod.MIN))[0])
        if not ok:
            if self._seg is not None:   # my attach worked; a peer's didn't
                try:
                    if comm.rank == 0:
                        self._seg.unlink()
                    self._seg.detach()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                self._seg = None
            raise MPIException(
                "MPI_Win_allocate_shared: segment setup failed"
                + (f": {err}" if err else " on a peer rank"),
                error_class=16)
        comm.barrier()

    def shared_query(self, rank: int) -> np.ndarray:
        """Zero-copy view of ``rank``'s slice (≈ MPI_Win_shared_query) —
        the REQUESTED extent (padding bytes are not exposed)."""
        lo = int(self._offsets[rank])
        return np.frombuffer(self._seg.buf, np.uint8,
                             count=int(self._extents[rank]),
                             offset=lo).view(self.dtype)

    @property
    def local(self) -> np.ndarray:
        return self.shared_query(self.comm.rank)

    def sync(self) -> None:
        """≈ MPI_Win_sync + barrier: order my stores before peers read."""
        self.comm.barrier()

    def fetch_add(self, rank: int, offset8: int, delta: int) -> int:
        """Native u64 atomic fetch-add on an 8-byte-aligned slot of
        ``rank``'s slice (lock-free cross-process counters)."""
        from ompi_tpu import _native

        fast = _native.fastdss()
        if fast is None:
            raise MPIException("native atomics unavailable",
                               error_class=16)
        return int(fast.atomic_add(
            self._seg.buf, int(self._offsets[rank]) + int(offset8) * 8, 
            int(delta)))

    def free(self) -> None:
        self.comm.barrier()
        if self.comm.rank == 0:
            self._seg.unlink()
        try:
            self._seg.detach()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

