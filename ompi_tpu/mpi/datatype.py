"""Datatype engine: typed memory layouts that pack/unpack and lower to XLA.

≈ the reference's two-level datatype system — opal/datatype (opal_datatype.h:104,
the compiled dt_elem_desc descriptors and the pack/unpack convertor,
opal_convertor.h:87,136) + ompi/datatype (ompi_datatype.h:67-68, MPI metadata
and constructors :178-189).

TPU-first re-design: a derived datatype *compiles* to an element-index map
(`segments`: byte (offset, length) runs per item, and `element_indices`: flat
element positions).  The host path packs with one vectorized numpy gather (the
native C++ convertor accelerates this in ompi_tpu/_native); the device path
reuses `element_indices` as a `jnp.take` gather so noncontiguous sends become
XLA ops instead of byte loops — pack loops would never tile onto the MXU.

Predefined types cover numpy + bfloat16 (TPU's native matmul dtype, absent in
the reference for obvious reasons).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional, Sequence

import numpy as np

from ompi_tpu.mpi.constants import MPIException

# native C++ convertor (ompi_tpu/_native): used above this payload size;
# below it, ctypes call overhead beats the numpy gather it would replace
_NATIVE_MIN_BYTES = 256

_U8P = ctypes.POINTER(ctypes.c_uint8)
_I64P = ctypes.POINTER(ctypes.c_int64)


def _native_convertor(nbytes: int):
    if nbytes < _NATIVE_MIN_BYTES:
        return None
    from ompi_tpu import _native  # cheap after first import (sys.modules)

    return _native.lib()


def _u8p(arr: np.ndarray):
    return arr.ctypes.data_as(_U8P)


def _i64p(arr: np.ndarray):
    return arr.ctypes.data_as(_I64P)


__all__ = [
    "Datatype", "PredefinedDatatype", "DerivedDatatype",
    "from_numpy", "BYTE", "INT8", "UINT8", "INT16", "UINT16", "INT32",
    "UINT32", "INT64", "UINT64", "FLOAT16", "BFLOAT16", "FLOAT32", "FLOAT64",
    "COMPLEX64", "COMPLEX128", "BOOL", "FLOAT", "DOUBLE", "INT", "LONG",
    "CHAR", "FLOAT_INT", "DOUBLE_INT", "LONG_INT",
]


class Datatype:
    """Base: a typed memory layout. ``size`` = payload bytes per item,
    ``extent`` = bytes spanned per item (≥ size for strided layouts)."""

    size: int
    extent: int
    base_np: np.dtype  # element dtype for op/reduction typing

    _committed = False

    def commit(self) -> "Datatype":
        """Compile the layout (≈ MPI_Type_commit → opal_datatype_commit)."""
        self._committed = True
        return self

    @property
    def committed(self) -> bool:
        return self._committed

    # -- layout queries ---------------------------------------------------

    def segments(self) -> list[tuple[int, int]]:
        """Byte (offset, length) runs for ONE item, offsets within extent."""
        raise NotImplementedError

    def element_indices(self) -> np.ndarray:
        """Flat element positions (in units of base_np) for one item, within
        extent/base_np.itemsize positions — the gather map for device packs."""
        raise NotImplementedError

    @property
    def elements_per_item(self) -> int:
        return self.size // self.base_np.itemsize

    # -- pack/unpack (host path; ≈ opal_convertor_pack/unpack) ------------

    def _byte_index(self, count: int) -> np.ndarray:
        idx1 = np.concatenate([
            np.arange(off, off + ln, dtype=np.int64)
            for off, ln in self.segments()
        ]) if self.segments() else np.empty(0, np.int64)
        if count == 1:
            return idx1
        base = np.arange(count, dtype=np.int64)[:, None] * self.extent
        return (base + idx1[None, :]).ravel()

    @property
    def is_contiguous(self) -> bool:
        """One gap-free run per item, items abutting — memcpy territory."""
        segs = self.segments()
        return (len(segs) == 1 and segs[0] == (0, self.size)
                and self.extent == self.size)

    def _seg_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """Segment (offsets, lengths) as int64 arrays for the native path
        (cached — the compiled descriptor of the opal convertor)."""
        arrs = getattr(self, "_seg_arrs", None)
        if arrs is None:
            segs = self.segments()
            arrs = (np.array([s[0] for s in segs], np.int64),
                    np.array([s[1] for s in segs], np.int64))
            self._seg_arrs = arrs
        return arrs

    def pack(self, buf: np.ndarray, count: int) -> bytes:
        """Gather `count` items from `buf` into contiguous bytes."""
        raw = np.ascontiguousarray(buf).view(np.uint8).ravel()
        if raw.nbytes < min_span(self, count):
            raise MPIException(
                f"pack: buffer has {raw.nbytes}B, datatype needs "
                f"{min_span(self, count)}B for count={count}")
        if count and self.is_contiguous:   # single-memcpy fast path
            return raw[:count * self.size].tobytes()
        native = _native_convertor(count * self.size)
        if native is not None:
            offs, lens = self._seg_arrays()
            out = np.empty(count * self.size, np.uint8)
            native.ompi_tpu_pack(
                _u8p(out), _u8p(raw), count, self.extent,
                _i64p(offs), _i64p(lens), len(offs))
            return out.tobytes()
        return raw[self._byte_index(count)].tobytes()

    def unpack(self, data: bytes, buf: np.ndarray, count: int) -> None:
        """Scatter contiguous bytes into `buf` according to the layout."""
        if buf.flags["C_CONTIGUOUS"] is False:
            raise MPIException("unpack requires a C-contiguous target buffer")
        raw = buf.view(np.uint8).reshape(-1)
        src = np.frombuffer(data, dtype=np.uint8)
        if len(src) < count * self.size:
            raise MPIException(
                f"unpack: got {len(src)}B, layout expects "
                f"{count * self.size}B", error_class=15)
        if raw.nbytes < min_span(self, count):
            raise MPIException(
                f"unpack: target buffer has {raw.nbytes}B, layout spans "
                f"{min_span(self, count)}B for count={count}",
                error_class=15)
        if count and self.is_contiguous:
            raw[:count * self.size] = src[:count * self.size]
            return
        native = _native_convertor(count * self.size)
        if native is not None:
            offs, lens = self._seg_arrays()
            src_c = np.ascontiguousarray(src[:count * self.size])
            native.ompi_tpu_unpack(
                _u8p(src_c), _u8p(raw), count, self.extent,
                _i64p(offs), _i64p(lens), len(offs))
            return
        idx = self._byte_index(count)
        raw[idx] = src[:len(idx)]

    # -- constructors (≈ ompi_datatype.h:178-189) -------------------------

    def contiguous(self, count: int) -> "DerivedDatatype":
        return DerivedDatatype._mk_contiguous(count, self)

    def vector(self, count: int, blocklength: int, stride: int) -> "DerivedDatatype":
        return DerivedDatatype._mk_vector(count, blocklength, stride, self)

    def indexed(self, blocklengths: Sequence[int],
                displacements: Sequence[int]) -> "DerivedDatatype":
        return DerivedDatatype._mk_indexed(blocklengths, displacements, self)

    def resized(self, extent: int) -> "DerivedDatatype":
        return DerivedDatatype._mk_resized(self, extent)


def min_span(dt: Datatype, count: int) -> int:
    """Min buffer bytes to hold `count` items (last item needs only size)."""
    if count <= 0:
        return 0
    # conservative: full segments of the last item must fit
    segs = dt.segments()
    last_end = max((off + ln for off, ln in segs), default=0)
    return (count - 1) * dt.extent + last_end


class PredefinedDatatype(Datatype):
    """A basic type wrapping a numpy dtype (≈ the 25 predefined opal types)."""

    def __init__(self, np_dtype, name: str) -> None:
        self.base_np = np.dtype(np_dtype)
        self.size = self.base_np.itemsize
        self.extent = self.base_np.itemsize
        self.name = name
        self._committed = True

    def segments(self) -> list[tuple[int, int]]:
        return [(0, self.size)]

    def element_indices(self) -> np.ndarray:
        return np.zeros(1, dtype=np.int64)

    def __repr__(self) -> str:
        return f"Datatype({self.name})"


class DerivedDatatype(Datatype):
    """A constructed layout, compiled to byte segments at commit."""

    def __init__(self, base: Datatype, pattern: list[tuple[int, int]],
                 extent: Optional[int] = None, name: str = "derived") -> None:
        # pattern: (element_offset, element_count) runs in units of base items
        self.base = base
        self.pattern = list(pattern)
        self.base_np = base.base_np
        self.name = name
        n_items = sum(c for _, c in pattern)
        self.size = n_items * base.size
        natural = max(((off + cnt) for off, cnt in pattern), default=0) * base.extent
        self.extent = extent if extent is not None else natural
        self._lock = threading.RLock()  # element_indices() nests segments()
        self._segs: Optional[list[tuple[int, int]]] = None
        self._elem_idx: Optional[np.ndarray] = None

    @classmethod
    def _mk_contiguous(cls, count: int, base: Datatype) -> "DerivedDatatype":
        return cls(base, [(0, count)], name=f"contig({count})")

    @classmethod
    def _mk_vector(cls, count: int, blocklength: int, stride: int,
               base: Datatype) -> "DerivedDatatype":
        pattern = [(i * stride, blocklength) for i in range(count)]
        return cls(base, pattern, name=f"vector({count},{blocklength},{stride})")

    @classmethod
    def _mk_indexed(cls, blocklengths: Sequence[int], displacements: Sequence[int],
                base: Datatype) -> "DerivedDatatype":
        if len(blocklengths) != len(displacements):
            raise MPIException("indexed: blocklengths/displacements mismatch")
        pattern = [(d, b) for d, b in zip(displacements, blocklengths)]
        return cls(base, pattern, name=f"indexed({len(pattern)})")

    @classmethod
    def _mk_resized(cls, base: Datatype, extent: int) -> "DerivedDatatype":
        dt = cls(base, [(0, 1)], extent=extent, name=f"resized({extent})")
        # resized keeps the base's full layout, only the extent changes
        dt.size = base.size
        dt._segs = base.segments()
        return dt

    def commit(self) -> "DerivedDatatype":
        self.segments()
        self.element_indices()
        self._committed = True
        return self

    def segments(self) -> list[tuple[int, int]]:
        with self._lock:
            if self._segs is None:
                segs: list[tuple[int, int]] = []
                bsegs = self.base.segments()
                for eoff, ecount in self.pattern:
                    for i in range(ecount):
                        origin = (eoff + i) * self.base.extent
                        for boff, blen in bsegs:
                            segs.append((origin + boff, blen))
                # merge adjacent runs (contiguity optimization, ≈ the
                # reference's descriptor optimizer)
                segs.sort()
                merged: list[tuple[int, int]] = []
                for off, ln in segs:
                    if merged and merged[-1][0] + merged[-1][1] == off:
                        merged[-1] = (merged[-1][0], merged[-1][1] + ln)
                    else:
                        merged.append((off, ln))
                self._segs = merged
            return self._segs

    def element_indices(self) -> np.ndarray:
        with self._lock:
            if self._elem_idx is None:
                isz = self.base_np.itemsize
                idx = []
                for off, ln in self.segments():
                    if off % isz or ln % isz:
                        raise MPIException(
                            f"datatype {self.name}: segments not aligned to "
                            f"base dtype {self.base_np}")
                    idx.append(np.arange(off // isz, (off + ln) // isz,
                                         dtype=np.int64))
                self._elem_idx = (np.concatenate(idx) if idx
                                  else np.empty(0, np.int64))
            return self._elem_idx

    def __repr__(self) -> str:
        return f"Datatype({self.name}, size={self.size}, extent={self.extent})"


def _bf16():
    import ml_dtypes

    return ml_dtypes.bfloat16


# Predefined types (≈ opal_datatype.h:51-52's 25 predefined + MPI aliases)
BYTE = PredefinedDatatype(np.uint8, "byte")
INT8 = PredefinedDatatype(np.int8, "int8")
UINT8 = PredefinedDatatype(np.uint8, "uint8")
INT16 = PredefinedDatatype(np.int16, "int16")
UINT16 = PredefinedDatatype(np.uint16, "uint16")
INT32 = PredefinedDatatype(np.int32, "int32")
UINT32 = PredefinedDatatype(np.uint32, "uint32")
INT64 = PredefinedDatatype(np.int64, "int64")
UINT64 = PredefinedDatatype(np.uint64, "uint64")
FLOAT16 = PredefinedDatatype(np.float16, "float16")
BFLOAT16 = PredefinedDatatype(_bf16(), "bfloat16")
FLOAT32 = PredefinedDatatype(np.float32, "float32")
FLOAT64 = PredefinedDatatype(np.float64, "float64")
COMPLEX64 = PredefinedDatatype(np.complex64, "complex64")
COMPLEX128 = PredefinedDatatype(np.complex128, "complex128")
BOOL = PredefinedDatatype(np.bool_, "bool")

# MPI-spelling aliases
FLOAT = FLOAT32
DOUBLE = FLOAT64
INT = INT32
LONG = INT64
CHAR = INT8

# Pair types for MAXLOC/MINLOC (value, index) — structured dtypes
FLOAT_INT = PredefinedDatatype(np.dtype([("val", np.float32), ("loc", np.int32)]),
                               "float_int")
DOUBLE_INT = PredefinedDatatype(np.dtype([("val", np.float64), ("loc", np.int32)]),
                                "double_int")
LONG_INT = PredefinedDatatype(np.dtype([("val", np.int64), ("loc", np.int32)]),
                              "long_int")

_BY_NP: dict = {}
for _t in (INT8, UINT8, INT16, UINT16, INT32, UINT32, INT64, UINT64,
           FLOAT16, BFLOAT16, FLOAT32, FLOAT64, COMPLEX64, COMPLEX128, BOOL,
           FLOAT_INT, DOUBLE_INT, LONG_INT):
    _BY_NP.setdefault(_t.base_np, _t)


def from_numpy(dtype) -> PredefinedDatatype:
    """Map a numpy dtype to the predefined Datatype (auto-typing for arrays)."""
    dt = np.dtype(dtype)
    try:
        return _BY_NP[dt]
    except KeyError:
        raise MPIException(f"no predefined datatype for numpy dtype {dt}") from None
